"""The polylint rule set — every rule encodes a real codebase invariant.

| Rule  | Invariant                                                        |
|-------|------------------------------------------------------------------|
| PL001 | host syncs only at annotated resolve points in hot-path functions|
| PL002 | time.time() stamps events; durations subtract monotonic clocks   |
| PL003 | except Exception must log, re-raise, use the error, or justify   |
| PL004 | nothing blocks lexically inside a ``with ...lock:`` body         |
| PL005 | threads set daemon= or are joined by an owning stop()/shutdown() |
| PL006 | jit boundaries stay pure; donated buffers are reassigned         |
| PL007 | metric families are snake_case with unit suffixes (obs/ contract)|
| PL008 | dispatch-side code never blocks on device results (readback is   |
|       | the process side's job — the lookahead pipeline's contract)      |

Static analysis trades recall for precision: each rule documents the
lexical approximation it makes, and the escape hatch for deliberate
violations is always an explicit ``# polylint: disable=PLxxx(reason)``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from .core import FileContext, Finding, Rule, register


# -- shared AST helpers -------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """Dotted path of a Name/Attribute chain ('' when not a plain chain)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_no_nested_functions(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class defs
    (their bodies execute elsewhere, not lexically here)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                stack.append(child)


# -- PL001: host-sync-in-hot-path --------------------------------------------


@register
class HostSyncInHotPath(Rule):
    """Host↔device syncs (np.asarray / device_get / .item() /
    block_until_ready, and int()/float() over device handles) stall the
    lookahead pipeline. Inside hot-path functions of engine/, models/ and
    ops/ they are only legal at deliberate, annotated resolve points
    (engine.py _resolve_slot/_process_step/_process_spec).

    Approximation: "hot path" = function names matching
    ^_?(resolve|process|dispatch|decode|step); int()/float() fire only
    when their argument subtree contains a flagged sync call or a name
    ending in _dev/_device (the repo's device-handle convention).
    """

    id = "PL001"
    name = "host-sync-in-hot-path"
    description = ("host sync in a hot-path function without an explicit "
                   "polylint annotation")

    HOT_RE = re.compile(r"^_?(resolve|process|dispatch|decode|step)")
    SYNC_CALLS = {
        "np.asarray", "np.array", "numpy.asarray", "numpy.array",
        "jax.device_get", "jax.block_until_ready",
    }
    SYNC_ATTRS = {"item", "block_until_ready"}
    DEV_NAME_RE = re.compile(r"(_dev|_device)$")

    def applies(self, rel: str) -> bool:
        return rel.startswith(("polykey_tpu/engine/", "polykey_tpu/models/",
                               "polykey_tpu/ops/"))

    def _is_sync_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if call_name(node) in self.SYNC_CALLS:
            return True
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr in self.SYNC_ATTRS
                and not node.args and not node.keywords)

    def _touches_device(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if self._is_sync_call(sub):
                return True
            if isinstance(sub, ast.Name) and self.DEV_NAME_RE.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) \
                    and self.DEV_NAME_RE.search(sub.attr):
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in iter_functions(ctx.tree):
            if not self.HOT_RE.match(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if self._is_sync_call(node):
                    what = name or f".{node.func.attr}()"  # type: ignore[union-attr]
                    yield ctx.finding(
                        self.id, node,
                        f"host sync ({what}) in hot-path function "
                        f"'{fn.name}' — annotate deliberate resolve points "
                        "with # polylint: disable=PL001(reason)",
                    )
                elif name in ("int", "float") and node.args \
                        and self._touches_device(node.args[0]):
                    yield ctx.finding(
                        self.id, node,
                        f"{name}() over a device value in hot-path function "
                        f"'{fn.name}' forces a blocking transfer — resolve "
                        "via the async-copy path or annotate",
                    )


# -- PL002: wall-clock-for-durations ------------------------------------------


@register
class WallClockForDurations(Rule):
    """time.time() may stamp events (cross-process correlation) but never
    be subtracted: NTP steps the wall clock and produces negative or
    wildly wrong latencies. Durations use time.monotonic() — the
    obs/trace.py precedent (Span start/end are monotonic; the flight
    recorder stamps events with wall time separately).

    Approximation: flags a `-` BinOp whose operand is a time.time() call
    or a name assigned from time.time() anywhere in the same file.
    """

    id = "PL002"
    name = "wall-clock-for-durations"
    description = "time.time() used in duration arithmetic"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        wall_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and call_name(node.value) == "time.time":
                for target in node.targets:
                    path = dotted(target)
                    if path:
                        wall_names.add(path)

        def is_wall(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call) and call_name(sub) == "time.time":
                    return True
                if dotted(sub) in wall_names:
                    return True
            return False

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                    and (is_wall(node.left) or is_wall(node.right)):
                yield ctx.finding(
                    self.id, node,
                    "duration computed from time.time() — wall clocks step "
                    "under NTP; use time.monotonic() for intervals",
                )


# -- PL003: silent-except ------------------------------------------------------


@register
class SilentExcept(Rule):
    """An ``except Exception`` that neither logs, re-raises, uses the
    caught error, nor carries a justification comment sits between a
    request and a silent wedge: the failure vanishes and the client
    hangs to its deadline. The handler must do ONE of: re-raise, call a
    logger (.error/.warning/...), reference the bound exception (e.g.
    push it into the request's out queue), or carry a comment explaining
    why swallowing is safe (suppression comments don't count — they
    suppress other rules, they don't justify this one).
    """

    id = "PL003"
    name = "silent-except"
    description = "except Exception swallows the error with no trace"

    LOG_ATTRS = {"debug", "info", "warning", "warn", "error", "exception",
                 "critical", "log"}

    def _handler_is_broad(self, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True                     # bare except
        return isinstance(handler.type, ast.Name) \
            and handler.type.id in ("Exception", "BaseException")

    def _body_handles(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in self.LOG_ATTRS:
                    return True
                if call_name(node).startswith(("logging.", "traceback.")):
                    return True
            if handler.name and isinstance(node, ast.Name) \
                    and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._handler_is_broad(node):
                continue
            if self._body_handles(node):
                continue
            end = node.body[-1].end_lineno or node.lineno
            if ctx.has_justification(node.lineno, end):
                continue
            yield ctx.finding(
                self.id, node,
                "broad except swallows the error silently — log it, "
                "re-raise, surface it to the caller, or add a "
                "justification comment",
            )


# -- PL004: blocking-call-under-lock ------------------------------------------


@register
class BlockingUnderLock(Rule):
    """The engine/gateway locks guard metrics and queue state shared with
    gRPC handler threads; a blocking wait inside a ``with ...lock:`` body
    (sleep, join, event wait, gRPC call, blocking queue get/put) turns
    every reader into a convoy and can deadlock shutdown. Queue get/put
    fire only when the receiver looks like a queue or a blocking
    timeout=/block= keyword is present — dict.get under a lock is fine.
    """

    id = "PL004"
    name = "blocking-call-under-lock"
    description = "blocking call lexically inside a lock body"

    BLOCK_ATTRS = {"sleep", "wait", "join", "result", "acquire"}
    QUEUE_HINT_RE = re.compile(r"(queue|_q$|submit)", re.IGNORECASE)

    def _lock_expr(self, item: ast.withitem) -> bool:
        return "lock" in dotted(item.context_expr).lower()

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(self._lock_expr(item) for item in node.items):
                continue
            for sub in walk_no_nested_functions(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                func = sub.func
                attr = func.attr if isinstance(func, ast.Attribute) else ""
                blocking = (
                    name == "time.sleep"
                    or name.startswith("grpc.")
                    or attr in self.BLOCK_ATTRS
                )
                if not blocking and attr in ("get", "put"):
                    receiver = dotted(func.value) if isinstance(func, ast.Attribute) else ""
                    has_block_kw = any(
                        kw.arg in ("timeout", "block") for kw in sub.keywords
                    )
                    blocking = bool(self.QUEUE_HINT_RE.search(receiver)) \
                        or has_block_kw
                if blocking:
                    yield ctx.finding(
                        self.id, sub,
                        f"blocking call ({name or attr}) inside a lock "
                        "body — move the wait outside the critical section",
                    )


# -- PL005: thread-hygiene -----------------------------------------------------


@register
class ThreadHygiene(Rule):
    """Every threading.Thread must either set daemon= at construction or
    be joined by an owning stop()/shutdown() path — otherwise process
    exit hangs on a forgotten worker (the engine/watchdog/exposition
    precedent: all three are daemons AND joined on shutdown).

    Approximation: a Thread construction without daemon= passes if the
    variable/attribute it is assigned to has .join() called on it
    anywhere in the module, or feeds a loop whose variable is joined
    (``for t in threads: t.join()``).
    """

    id = "PL005"
    name = "thread-hygiene"
    description = "thread neither daemon nor joined by an owner"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        join_receivers: set[str] = set()
        loop_iters: dict[str, str] = {}    # loop var -> iterated name
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                receiver = dotted(node.func.value)
                if receiver:
                    join_receivers.add(receiver)
            if isinstance(node, ast.For):
                var, it = dotted(node.target), dotted(node.iter)
                if var and it:
                    loop_iters[var] = it
        # daemon-flag assignment after construction: x.daemon = True
        daemon_assigned: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Attribute) \
                            and target.attr == "daemon":
                        base = dotted(target.value)
                        if base:
                            daemon_assigned.add(base)

        def joined(path: str) -> bool:
            if path in join_receivers or path in daemon_assigned:
                return True
            # for t in <path>: t.join()
            return any(it == path and var in join_receivers
                       for var, it in loop_iters.items())

        for stmt in ast.walk(ctx.tree):
            if not isinstance(stmt, (ast.Assign, ast.Expr, ast.AnnAssign)):
                continue
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                name = call_name(call)
                if not (name.endswith(".Thread") or name == "Thread"):
                    continue
                if any(kw.arg == "daemon" for kw in call.keywords):
                    continue
                targets: list[str] = []
                if isinstance(stmt, ast.Assign):
                    targets = [dotted(t) for t in stmt.targets]
                elif isinstance(stmt, ast.AnnAssign) and stmt.target is not None:
                    targets = [dotted(stmt.target)]
                if any(t and joined(t) for t in targets):
                    continue
                yield ctx.finding(
                    self.id, call,
                    "threading.Thread without daemon= and no owning "
                    ".join() in this module — set daemon=True or join it "
                    "from a stop()/shutdown() path",
                )


# -- PL006: jit-boundary purity ------------------------------------------------


@register
class JitBoundaryPurity(Rule):
    """Functions handed to jax.jit trace once and replay: closing over
    mutable ``self`` state, calling the Python/NumPy RNG, or reading
    clocks bakes trace-time values into the compiled executable (or
    recompiles per instance). Separately, buffers listed in
    donate_argnames are dead after the call — every call site must
    reassign the donated expression from the jit's outputs (the engine's
    ``..., self.paged = self._jit_...(..., self.paged, ...)`` chain).

    Approximation: purity checks cover functions defined in the same
    module as their jax.jit site (decorator, partial(jax.jit, ...), or
    jax.jit(fn, ...)); donation checks cover jit handles assigned to
    attributes in the same module and require the donated Name/Attribute
    to be an assignment target somewhere in the calling function.
    """

    id = "PL006"
    name = "jit-boundary-purity"
    description = "impure jit-compiled function or unreassigned donated buffer"

    IMPURE_CALL_PREFIXES = ("random.", "np.random.", "numpy.random.", "time.")

    def _jit_decorated(self, fn: ast.FunctionDef) -> bool:
        for dec in fn.decorator_list:
            if dotted(dec) == "jax.jit":
                return True
            if isinstance(dec, ast.Call):
                if call_name(dec) == "jax.jit":
                    return True
                if call_name(dec) in ("partial", "functools.partial") \
                        and dec.args and dotted(dec.args[0]) == "jax.jit":
                    return True
        return False

    def _purity_findings(self, ctx: FileContext,
                         fn: ast.FunctionDef) -> Iterator[Finding]:
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs}
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "self" \
                    and "self" not in params:
                yield ctx.finding(
                    self.id, node,
                    f"jit-compiled '{fn.name}' closes over mutable self "
                    "state — pass device state explicitly",
                )
                break
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name.startswith(self.IMPURE_CALL_PREFIXES):
                    yield ctx.finding(
                        self.id, node,
                        f"jit-compiled '{fn.name}' calls {name}() — the "
                        "value is baked in at trace time; use jax.random "
                        "keys / pass clocks as arguments",
                    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        module_fns = {
            fn.name: fn for fn in ctx.tree.body
            if isinstance(fn, ast.FunctionDef)
        }
        checked: set[str] = set()
        # (a) decorated functions
        for fn in iter_functions(ctx.tree):
            if self._jit_decorated(fn):
                checked.add(fn.name)
                yield from self._purity_findings(ctx, fn)
        # (b) jax.jit(fn, ...) call sites + donation contracts
        donating: dict[str, tuple[ast.FunctionDef, list[int]]] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and call_name(node) == "jax.jit"):
                continue
            if not node.args:
                continue
            target_fn: Optional[ast.FunctionDef] = None
            arg0 = node.args[0]
            if isinstance(arg0, ast.Name) and arg0.id in module_fns:
                target_fn = module_fns[arg0.id]
                if target_fn.name not in checked:
                    checked.add(target_fn.name)
                    yield from self._purity_findings(ctx, target_fn)
            elif isinstance(arg0, ast.Lambda):
                for sub in ast.walk(arg0):
                    if isinstance(sub, ast.Name) and sub.id == "self":
                        yield ctx.finding(
                            self.id, arg0,
                            "lambda passed to jax.jit closes over self — "
                            "hoist it to a pure function",
                        )
                        break
            if target_fn is None:
                continue
            donated = self._donated_indices(node, target_fn)
            if not donated:
                continue
            handle = self._assigned_handle(ctx, node)
            if handle:
                donating[handle] = (target_fn, donated)
        if donating:
            yield from self._check_donation_sites(ctx, donating)

    def _donated_indices(self, jit_call: ast.Call,
                         fn: ast.FunctionDef) -> list[int]:
        param_names = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        indices: list[int] = []
        for kw in jit_call.keywords:
            if kw.arg == "donate_argnames" \
                    and isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) \
                            and el.value in param_names:
                        indices.append(param_names.index(el.value))
            elif kw.arg == "donate_argnums" \
                    and isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, int):
                        indices.append(el.value)
        return indices

    def _assigned_handle(self, ctx: FileContext,
                         jit_call: ast.Call) -> Optional[str]:
        """Attribute name the jax.jit(...) result is bound to
        (``self._jit_prefill = jax.jit(...)`` -> '_jit_prefill')."""
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and node.value is jit_call:
                for target in node.targets:
                    if isinstance(target, ast.Attribute):
                        return target.attr
                    if isinstance(target, ast.Name):
                        return target.id
        return None

    def _check_donation_sites(
        self, ctx: FileContext,
        donating: dict[str, tuple[ast.FunctionDef, list[int]]],
    ) -> Iterator[Finding]:
        for fn in iter_functions(ctx.tree):
            assigned: set[str] = set()
            calls: list[ast.Call] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        for sub in ast.walk(target):
                            path = dotted(sub)
                            if path:
                                assigned.add(path)
                elif isinstance(node, ast.Call):
                    calls.append(node)
            for call in calls:
                func = call.func
                attr = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else "")
                if attr not in donating:
                    continue
                target_fn, donated = donating[attr]
                for idx in donated:
                    if idx >= len(call.args):
                        continue       # passed by keyword / starred: skip
                    path = dotted(call.args[idx])
                    if not path:
                        continue       # complex expression: skip
                    if path not in assigned:
                        pname = ([a.arg for a in target_fn.args.posonlyargs
                                  + target_fn.args.args][idx]
                                 if idx < len(target_fn.args.args) else idx)
                        yield ctx.finding(
                            self.id, call,
                            f"'{path}' is donated to {attr}() (param "
                            f"{pname!r}) but never reassigned from its "
                            "outputs in this function — the buffer is "
                            "dead after the call",
                        )


# -- PL007: prometheus-naming --------------------------------------------------


@register
class PrometheusNaming(Rule):
    """Metric families follow the obs/ contract: snake_case throughout,
    counters end in ``_total``, histograms carry an explicit unit suffix
    (``_ms``/``_bytes``/``_seconds``). A family that breaks the pattern
    breaks every recording rule and dashboard written against the
    convention. Checks literal names at Counter/Gauge/HistogramMetric
    construction, registry .counter/.gauge/.histogram/.get_or_create,
    and the render_counter/render_gauge/render_histogram helpers.
    """

    id = "PL007"
    name = "prometheus-naming"
    description = "metric family violates the obs/ naming contract"

    SNAKE_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")
    HIST_SUFFIXES = ("_ms", "_bytes", "_seconds", "_us", "_total")
    KIND_BY_CLASS = {"Counter": "counter", "Gauge": "gauge",
                     "HistogramMetric": "histogram"}

    def _metric_sites(self, tree: ast.AST) -> Iterator[tuple[ast.Call, str, str]]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1] if name else ""
            kind: Optional[str] = None
            name_arg: Optional[ast.expr] = None
            if tail in ("render_counter", "render_gauge", "render_histogram"):
                kind = tail.split("_", 1)[1]
                name_arg = node.args[0] if node.args else None
            elif tail in ("counter", "gauge", "histogram") \
                    and isinstance(node.func, ast.Attribute):
                kind = tail
                name_arg = node.args[0] if node.args else None
            elif tail == "get_or_create" and len(node.args) >= 2:
                cls = dotted(node.args[0])
                kind = self.KIND_BY_CLASS.get(cls.rsplit(".", 1)[-1])
                name_arg = node.args[1]
            elif tail in self.KIND_BY_CLASS and isinstance(node.func, ast.Name):
                kind = self.KIND_BY_CLASS[tail]
                name_arg = node.args[0] if node.args else None
            if kind and isinstance(name_arg, ast.Constant) \
                    and isinstance(name_arg.value, str):
                yield node, kind, name_arg.value

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node, kind, name in self._metric_sites(ctx.tree):
            if not self.SNAKE_RE.match(name):
                yield ctx.finding(
                    self.id, node,
                    f"metric family {name!r} is not snake_case",
                )
                continue
            if kind == "counter" and not name.endswith("_total"):
                yield ctx.finding(
                    self.id, node,
                    f"counter family {name!r} must end in _total",
                )
            elif kind == "histogram" and not name.endswith(self.HIST_SUFFIXES):
                yield ctx.finding(
                    self.id, node,
                    f"histogram family {name!r} needs a unit suffix "
                    f"({'/'.join(self.HIST_SUFFIXES)})",
                )


# -- PL008: dispatch-side-sync -------------------------------------------------


@register
class DispatchSideSync(Rule):
    """The lookahead dispatch pipeline's contract (engine.py): the
    DISPATCH side enqueues device work and returns; readback belongs
    only on the PROCESS side (`_process_step`/`_process_spec`), one
    batched sanctioned `_host_crossing` per block. A blocking
    ``device_get`` / ``block_until_ready`` / implicit sync
    (``np.asarray`` over a device handle, ``.item()``) anywhere in
    `_dispatch_step` / `_upload_slot_state` — or in a method they
    transitively call — re-serializes the loop host-side and silently
    erases the overlap the pipeline exists for (r03: 587 ms roundtrip
    against 62 ms of device compute per block).

    Approximation: the callee closure is the static same-file call
    graph over ``self.X(...)`` and bare ``X(...)`` calls starting from
    the root functions; cross-object calls (``self.metrics.X``) are
    other classes' code and out of scope. PL001 already polices
    name-matched hot functions — this rule adds the reachability
    closure, so a helper with an innocuous name can't hide a sync on
    the dispatch path. Deliberate sites (e.g. the dev-dirty cold-start
    resolve) annotate with ``# polylint: disable=PL008(reason)``.
    """

    id = "PL008"
    name = "dispatch-side-sync"
    description = ("blocking device readback reachable from the dispatch "
                   "side of the lookahead pipeline")

    ROOTS = ("_dispatch_step", "_upload_slot_state")
    SYNC_CALLS = HostSyncInHotPath.SYNC_CALLS
    SYNC_ATTRS = HostSyncInHotPath.SYNC_ATTRS
    DEV_NAME_RE = HostSyncInHotPath.DEV_NAME_RE

    def applies(self, rel: str) -> bool:
        return rel.startswith("polykey_tpu/engine/")

    def _is_sync_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if call_name(node) in self.SYNC_CALLS:
            return True
        return (isinstance(node.func, ast.Attribute)
                and node.func.attr in self.SYNC_ATTRS
                and not node.args and not node.keywords)

    def _touches_device(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if self._is_sync_call(sub):
                return True
            if isinstance(sub, ast.Name) and self.DEV_NAME_RE.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) \
                    and self.DEV_NAME_RE.search(sub.attr):
                return True
        return False

    def _closure(self, funcs: dict) -> set[str]:
        """Names reachable from ROOTS over the same-file call graph."""
        seen: set[str] = set()
        frontier = [r for r in self.ROOTS if r in funcs]
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            for node in ast.walk(funcs[name]):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                # Same-class calls only: bare X(...) or self.X(...) with
                # exactly one dot — self.metrics.X(...) is another
                # object's method and must NOT pull a same-named local
                # function into the closure.
                callee = cname[len("self."):] \
                    if cname.startswith("self.") else cname
                if callee and "." not in callee and callee in funcs \
                        and callee not in seen:
                    frontier.append(callee)
        return seen

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        funcs = {}
        for fn in iter_functions(ctx.tree):
            funcs.setdefault(fn.name, fn)
        for fn_name in sorted(self._closure(funcs)):
            via = "" if fn_name in self.ROOTS else \
                " (reachable from the dispatch side)"
            for node in ast.walk(funcs[fn_name]):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if self._is_sync_call(node):
                    what = name or f".{node.func.attr}()"  # type: ignore[union-attr]
                    yield ctx.finding(
                        self.id, node,
                        f"blocking readback ({what}) in '{fn_name}'{via} — "
                        "readback belongs on the process side; move it to "
                        "_process_step or annotate the deliberate site",
                    )
                elif name in ("int", "float") and node.args \
                        and self._touches_device(node.args[0]):
                    yield ctx.finding(
                        self.id, node,
                        f"{name}() over a device value in '{fn_name}'{via} "
                        "forces a blocking transfer on the dispatch side",
                    )
