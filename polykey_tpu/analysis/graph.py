"""graphlint — compiled-graph contract analysis (the second analysis tier).

polylint (rules.py) checks what the *source* promises; this module checks
what the *compiled graph* actually does. It traces the real engine/model
step functions under abstract inputs (``jax.eval_shape`` /
``jax.make_jaxpr`` / ``.lower()``) and drives a real CPU-backed engine,
verifying the invariants that gate paged-KV continuous batching at
ICI-limited speed — the production killers that are silent on TPU until
the latency graph melts:

| Check | Contract                                                         |
|-------|------------------------------------------------------------------|
| GL001 | recompile stability: each jitted step compiles once at warm-up   |
| GL002 | donation audit: every donate_argnames site aliases its buffers   |
| GL003 | dtype policy: no f64 anywhere; no weight upcasts in bf16 paths   |
| GL004 | host-transfer guard: no callbacks/unannotated transfers in steps |
| GL005 | shape/layout: kernel block contracts + sharding divisibility     |

Like polylint, graphlint trades recall for precision: every check
documents its approximation, deliberate violations are suppressed with
an explicit reason (class-level ``SUPPRESSIONS``), and pre-existing debt
grandfathers through a content-hashed baseline
(``graphlint-baseline.json``, reusing the PR 2 machinery). Analyzer
infrastructure failures surface as blocking GL000 findings — a broken
probe must never read as a clean graph.

Run::

    make graphlint                                  # repo gate (CI parity)
    python -m polykey_tpu.analysis graph            # same, direct
    python -m polykey_tpu.analysis graph --json     # machine-readable
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import re
import sys
import time
import warnings
from functools import partial
from pathlib import Path
from typing import Callable, Iterator, Optional

from .baseline import (
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import Finding, UsageError, parse_only, require_full_run

GRAPH_BASELINE = "graphlint-baseline.json"

# Raised for each collected stream before the engine is declared wedged.
_COLLECT_TIMEOUT_S = 180.0


def _ensure_cpu_backend() -> None:
    """Pin jax to a simulated multi-device CPU platform.

    GL001's recompile sweep and GL004's guard smoke need a real engine but
    no hardware; GL005's sharding walk wants >= 8 devices. Must run before
    jax initializes its backend — mirror tests/conftest.py: this image
    pre-imports a TPU plugin and pins JAX_PLATFORMS, so the env var alone
    is not enough and the platform is forced via jax.config too.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


# -- check registry -----------------------------------------------------------


class GraphCheck:
    """One compiled-graph contract. Subclasses set id/name/description and
    implement run(env) -> list[Finding].

    SUPPRESSIONS maps a finding's snippet key to the reason it is a
    deliberate, reviewed exception — the graph-tier analogue of polylint's
    ``# polylint: disable=`` comments (jaxpr findings have no source line
    to hang a comment on)."""

    id: str = "GL000"
    name: str = "unnamed"
    description: str = ""
    SUPPRESSIONS: dict[str, str] = {}

    def run(self, env: "GraphEnv") -> list[Finding]:
        raise NotImplementedError


_GRAPH_REGISTRY: dict[str, GraphCheck] = {}


def register_graph(cls: type[GraphCheck]) -> type[GraphCheck]:
    inst = cls()
    if inst.id in _GRAPH_REGISTRY:
        raise ValueError(f"duplicate graph check id {inst.id}")
    _GRAPH_REGISTRY[inst.id] = inst
    return cls


def all_graph_checks() -> list[GraphCheck]:
    return [_GRAPH_REGISTRY[k] for k in sorted(_GRAPH_REGISTRY)]


def graph_finding(rule: str, path: str, key: str, message: str) -> Finding:
    """A graph-tier finding. `key` is the stable identity string — it
    feeds both the baseline fingerprint (via Finding.snippet) and the
    per-check SUPPRESSIONS lookup, so it must not embed counters,
    addresses, or timings."""
    return Finding(rule=rule, path=path, line=0, message=message, snippet=key)


# -- engine driving (shared by GL001 / GL004) ---------------------------------


def _collect_stream(request, timeout: float = _COLLECT_TIMEOUT_S):
    """Drain one GenRequest's out queue; returns (tokens, error)."""
    tokens: list[int] = []
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return tokens, "timed out waiting for engine output"
        try:
            kind, value = request.out.get(timeout=remaining)
        except queue.Empty:
            return tokens, "timed out waiting for engine output"
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            return tokens, None
        else:
            return tokens, str(value)


def drive_engine(engine, waves: list[list]) -> list[str]:
    """Submit requests wave-by-wave (later waves land while earlier ones
    are still decoding — the occupancy variation GL001 needs) and drain
    every stream. Returns the error strings (empty = clean run)."""
    errors: list[str] = []
    all_requests = []
    for wave in waves:
        for request in wave:
            engine.submit(request)
        all_requests.extend(wave)
        # A short beat between waves so admission interleaves with live
        # decode lanes rather than batching everything into one burst.
        time.sleep(0.05)
    for request in all_requests:
        _, error = _collect_stream(request)
        if error is not None:
            errors.append(error)
    return errors


def measure_recompiles(
    handles: dict[str, object], drive: Callable[[], list[str]]
) -> tuple[dict[str, tuple[int, int]], list[str], list[str]]:
    """Core of GL001: snapshot each jit handle's executable-cache size,
    run `drive`, snapshot again. Returns (sizes {name: (before, after)},
    drive errors, compile log lines captured during the drive)."""
    import logging

    before = {name: h._cache_size() for name, h in handles.items()}

    compile_lines: list[str] = []

    class _Capture(logging.Handler):
        def emit(self, record: logging.LogRecord) -> None:
            msg = record.getMessage()
            if "ompil" in msg:  # "Compiling"/"Finished XLA compilation of"
                compile_lines.append(msg.splitlines()[0][:200])

    import jax

    handler = _Capture(level=logging.DEBUG)
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(handler)
    try:
        with jax.log_compiles(True):
            errors = drive()
    finally:
        jax_logger.removeHandler(handler)

    sizes = {
        name: (before[name], h._cache_size()) for name, h in handles.items()
    }
    return sizes, errors, compile_lines


def recompile_findings(
    label: str, handles: dict[str, object], drive: Callable[[], list[str]]
) -> tuple[list[Finding], dict[str, tuple[int, int]]]:
    """Core of GL001 for one engine: any handle whose executable cache
    grows during `drive` recompiled at serving time; any handle whose
    cache is empty beforehand was missed by warmup."""
    findings: list[Finding] = []
    for name, handle in handles.items():
        if not hasattr(handle, "_cache_size"):
            findings.append(graph_finding(
                "GL000", f"graph:{label}", f"{label}:{name}:no-probe",
                f"jit handle {name} has no _cache_size probe on this "
                "jax — GL001 cannot verify recompile stability",
            ))
            return findings, {}
    sizes, errors, compile_lines = measure_recompiles(handles, drive)
    for error in errors:
        findings.append(graph_finding(
            "GL000", f"graph:{label}", f"{label}:drive-error",
            f"GL001 sweep on {label} hit a request error: {error}",
        ))
    for name, (before, after) in sizes.items():
        if before == 0:
            findings.append(graph_finding(
                "GL001", f"graph:{label}", f"{label}:{name}:cold",
                f"{name} had an empty executable cache after warmup — "
                "compile warmup no longer covers this step, so the first "
                "real request pays its compile",
            ))
        if after > before:
            detail = "; ".join(compile_lines[:3])
            findings.append(graph_finding(
                "GL001", f"graph:{label}", f"{label}:{name}:grew",
                f"{name} compiled {after - before} new executable(s) "
                f"during the serving sweep ({before} -> {after}) — a "
                "shape/static-arg variant reached serving that warmup "
                f"never compiled{': ' + detail if detail else ''}",
            ))
    return findings, sizes


# -- jaxpr walking (shared by GL003 / GL004) ----------------------------------


def iter_jaxprs(jaxpr) -> Iterator:
    """Yield a jaxpr and every nested jaxpr (pjit bodies, scan/while
    bodies, cond branches, custom_* calls), depth-first."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for value in eqn.params.values():
            yield from _nested_jaxprs(value)


def _nested_jaxprs(value) -> Iterator:
    if hasattr(value, "jaxpr") and hasattr(value, "consts"):  # ClosedJaxpr
        yield from iter_jaxprs(value.jaxpr)
    elif hasattr(value, "eqns") and hasattr(value, "invars"):  # Jaxpr
        yield from iter_jaxprs(value)
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from _nested_jaxprs(item)


def _eqn_avals(jaxpr) -> Iterator:
    for var in list(jaxpr.invars) + list(jaxpr.constvars) + list(jaxpr.outvars):
        aval = getattr(var, "aval", None)
        if aval is not None:
            yield aval
    for eqn in jaxpr.eqns:
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None:
                yield aval


def dtype_findings(
    label: str, closed_jaxpr, weight_shapes: set[tuple[int, ...]],
    bf16_path: bool,
) -> list[Finding]:
    """Core of GL003. Walks a traced step's jaxpr (nested bodies
    included) for:

    - any float64 value anywhere (inputs, intermediates, outputs) — with
      a bf16/f32 serving stack an f64 is always an accident (a Python
      float promotion under x64) and doubles bandwidth where it lands;
    - in bf16 paths, ``convert_element_type`` to f32 applied to a
      weight-shaped bf16 operand — the classic silent upcast that doubles
      weight HBM traffic. Activation-precision f32 (norms, softmax,
      logits) is deliberate mixed precision and does NOT fire: only
      operands whose shape matches a params leaf (ndim >= 2) are flagged.
    """
    import numpy as np

    findings: list[Finding] = []
    seen_f64: set[str] = set()
    seen_upcast: set[str] = set()
    for sub in iter_jaxprs(closed_jaxpr.jaxpr):
        for aval in _eqn_avals(sub):
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and dtype in (np.float64, np.complex128):
                key = f"{label}:f64:{getattr(aval, 'shape', ())}"
                if key not in seen_f64:
                    seen_f64.add(key)
                    findings.append(graph_finding(
                        "GL003", f"graph:{label}", key,
                        f"float64 value {aval} in the compiled graph of "
                        f"{label} — the serving stack is bf16/f32; an f64 "
                        "is an accidental Python-float promotion",
                    ))
        if not bf16_path:
            continue
        for eqn in sub.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            new_dtype = eqn.params.get("new_dtype")
            if new_dtype is None or np.dtype(new_dtype) != np.float32:
                continue
            operand = eqn.invars[0]
            aval = getattr(operand, "aval", None)
            if aval is None:
                continue
            import jax.numpy as jnp

            if getattr(aval, "dtype", None) != jnp.bfloat16:
                continue
            shape = tuple(getattr(aval, "shape", ()))
            if shape in weight_shapes:
                key = f"{label}:upcast:{shape}"
                if key not in seen_upcast:
                    seen_upcast.add(key)
                    findings.append(graph_finding(
                        "GL003", f"graph:{label}", key,
                        f"bf16 weight tensor {shape} upcast to f32 inside "
                        f"{label} — doubles its HBM read on every step; "
                        "keep weights bf16 into the matmul "
                        "(preferred_element_type handles accumulation)",
                    ))
    return findings


_CALLBACK_PRIMITIVES = ("infeed", "outfeed")


def callback_findings(label: str, closed_jaxpr) -> list[Finding]:
    """Core of GL004's static half: any callback/infeed/outfeed primitive
    inside a jitted step is a host round-trip per dispatch — fatal for a
    loop whose whole design is 'one hidden sync per block'."""
    findings: list[Finding] = []
    seen: set[str] = set()
    for sub in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in sub.eqns:
            name = eqn.primitive.name
            if "callback" in name or name in _CALLBACK_PRIMITIVES:
                key = f"{label}:{name}"
                if key not in seen:
                    seen.add(key)
                    findings.append(graph_finding(
                        "GL004", f"graph:{label}", key,
                        f"host callback primitive '{name}' inside the "
                        f"compiled graph of {label} — every dispatch pays "
                        "a device->host round-trip (debug prints and "
                        "io_callback must not ship in step functions)",
                    ))
    return findings


# -- donation auditing (GL002) ------------------------------------------------

_ALIAS_RE = re.compile(r"(?:may|must)-alias")


def audit_donation_site(
    label: str, lower: Callable[[], object], donated_big_leaves: int
) -> list[Finding]:
    """Core of GL002: lower + compile one donate_argnames site, fail on
    dropped-donation warnings and on an input_output_alias map smaller
    than the donated buffer count.

    `donated_big_leaves` counts donated array leaves >= 1 KiB — XLA may
    legitimately decline to alias a scalar, but a non-aliased page pool
    or parameter tree is exactly the regression this check exists for
    (donation silently dropped = double HBM residency + a copy per step).
    """
    findings: list[Finding] = []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        try:
            compiled = lower().compile()
        except Exception as e:  # infra failure must be visible, not a pass
            findings.append(graph_finding(
                "GL000", f"graph:{label}", f"{label}:lower-failed",
                f"GL002 could not lower/compile {label}: "
                f"{type(e).__name__}: {e}",
            ))
            return findings
    for w in caught:
        message = str(w.message)
        if "donated" in message.lower():
            findings.append(graph_finding(
                "GL002", f"graph:{label}", f"{label}:dropped-donation",
                f"XLA dropped a donation while compiling {label}: "
                f"{message.splitlines()[0]}",
            ))
    aliased = len(_ALIAS_RE.findall(compiled.as_text()))
    if aliased < donated_big_leaves:
        findings.append(graph_finding(
            "GL002", f"graph:{label}", f"{label}:alias-deficit",
            f"{label} donates {donated_big_leaves} buffer(s) >= 1 KiB but "
            f"the compiled executable aliases only {aliased} — a donated "
            "buffer that does not alias its output still exists twice in "
            "HBM and costs a copy every step",
        ))
    return findings


def count_big_leaves(tree, min_bytes: int = 1024) -> int:
    import jax

    return sum(
        1 for leaf in jax.tree_util.tree_leaves(tree)
        if getattr(leaf, "nbytes", 0) >= min_bytes
    )


# -- shape/layout contracts (GL005) -------------------------------------------


def _axis_extent(mesh, axes) -> int:
    if axes is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    extent = 1
    for axis in axes:
        extent *= mesh.shape[axis]
    return extent


def sharding_divisibility(
    label: str, shape: tuple[int, ...], sharding
) -> list[Finding]:
    """Core of GL005's sharding half: every dim a PartitionSpec annotates
    must be divisible by its mesh-axis extent — GSPMD silently pads the
    remainder (wasted HBM + ragged collectives), and for the KV pool a
    padded page axis corrupts the page-index arithmetic."""
    findings: list[Finding] = []
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return findings
    for dim, (size, axes) in enumerate(zip(shape, tuple(spec))):
        extent = _axis_extent(mesh, axes)
        if extent > 1 and size % extent != 0:
            findings.append(graph_finding(
                "GL005", f"graph:{label}",
                f"{label}:dim{dim}:{size}%{extent}",
                f"{label}: dim {dim} (size {size}) is sharded over mesh "
                f"axes {axes!r} (extent {extent}) but {size} % {extent} "
                "!= 0 — GSPMD pads the remainder",
            ))
    return findings


def gate_consistency_findings(configs) -> list[Finding]:
    """Core of GL005's gate half: kernel-eligibility gates must agree
    with the alignment rules their kernels assume — a config that passes
    the gate but breaks alignment would compile-fail (or silently
    mis-tile) on first hardware contact."""
    from ..ops.flash_attention import _FLASH_HEAD_DIMS

    findings: list[Finding] = []
    for cfg in configs:
        folded = cfg.num_kv_heads * cfg.head_dim
        eligible = folded % 128 == 0
        if eligible and cfg.head_dim % 8 != 0:
            findings.append(graph_finding(
                "GL005", "graph:ops.gates",
                f"paged-gate:{cfg.name}",
                f"{cfg.name}: paged kernel eligible (folded lanes "
                f"{folded}) but head_dim {cfg.head_dim} is not "
                "sublane-aligned — the DMA slice would mis-tile",
            ))
        if cfg.head_dim in _FLASH_HEAD_DIMS and cfg.head_dim % 64 != 0:
            findings.append(graph_finding(
                "GL005", "graph:ops.gates",
                f"flash-gate:{cfg.name}",
                f"{cfg.name}: head_dim {cfg.head_dim} is in "
                "_FLASH_HEAD_DIMS but not 64-aligned — the proven set "
                "must only contain Mosaic-tileable dims",
            ))
    return findings


def abstract_contract(
    label: str, fn: Callable, args: tuple,
    expected: list[tuple[tuple[int, ...], str]],
) -> list[Finding]:
    """Core of GL005's kernel half: abstract-eval `fn(*args)` (traces the
    pallas_call block machinery without lowering — runs on CPU) and
    compare the flattened outputs against (shape, dtype) expectations. A
    trace-time exception means the block/grid arithmetic itself is
    inconsistent for this geometry."""
    import jax

    try:
        out = jax.eval_shape(fn, *args)
    except Exception as e:
        return [graph_finding(
            "GL005", f"graph:{label}", f"{label}:abstract-eval",
            f"abstract eval of {label} failed — block/grid contract is "
            f"inconsistent for this geometry: {type(e).__name__}: "
            f"{str(e).splitlines()[0][:160]}",
        )]
    leaves = jax.tree_util.tree_leaves(out)
    got = [(tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves]
    want = [(tuple(shape), dtype) for shape, dtype in expected]
    if got != want:
        return [graph_finding(
            "GL005", f"graph:{label}", f"{label}:out-contract",
            f"{label}: abstract outputs {got} != contract {want}",
        )]
    return []


# -- shared fixture environment -----------------------------------------------


class GraphEnv:
    """Lazily-built fixtures shared across checks: a warmed plain CPU
    engine, a warmed speculative engine, an unwarmed bf16 engine for
    tracing, and a tiny train step. Engines are built once — GL001 drives
    them, GL002 lowers their handles, GL004 smokes them under the
    transfer guard.

    profile="full" is the repo gate; profile="smoke" shrinks warmup for
    the test suite (1 bucket, 2 slots, greedy-only)."""

    def __init__(self, profile: str = "full"):
        self.profile = profile
        self.logs: list[str] = []
        self._plain = None
        self._spec = None
        self._ragged = None
        self._spec_ragged = None
        self._hostkv = None
        self._bf16 = None
        self._train = None
        self._jaxprs = None

    # -- configs -------------------------------------------------------------

    def _base_config(self):
        from ..engine.config import EngineConfig

        if self.profile == "smoke":
            return EngineConfig(
                model="tiny-llama", tokenizer="byte", dtype="float32",
                max_decode_slots=2, page_size=8, num_pages=64,
                max_seq_len=64, prefill_buckets=(16,),
                max_new_tokens_cap=16, default_max_new_tokens=6,
                compile_warmup=True, warm_sampled_variants=False,
            )
        return EngineConfig(
            model="tiny-llama", tokenizer="byte", dtype="float32",
            max_decode_slots=4, page_size=8, num_pages=64,
            max_seq_len=64, prefill_buckets=(16, 32),
            max_new_tokens_cap=32, default_max_new_tokens=8,
            compile_warmup=True, warm_sampled_variants=True,
        )

    # -- engines -------------------------------------------------------------

    def plain_engine(self):
        if self._plain is None:
            from ..engine.engine import InferenceEngine

            self.logs.append("building plain CPU engine (compile warmup)")
            self._plain = InferenceEngine(self._base_config())
        return self._plain

    def spec_engine(self):
        if self._spec is None:
            import dataclasses

            from ..engine.engine import InferenceEngine

            self.logs.append("building speculative CPU engine (warmup)")
            config = dataclasses.replace(
                self._base_config(), draft_model="tiny-llama", spec_gamma=2,
            )
            self._spec = InferenceEngine(config)
        return self._spec

    def bf16_engine(self):
        """Unwarmed bf16 engine: GL003/GL004 only trace its step
        functions (make_jaxpr), never execute them — construction cost is
        params init + device_put."""
        if self._bf16 is None:
            import dataclasses

            from ..engine.engine import InferenceEngine

            config = dataclasses.replace(
                self._base_config(), dtype="bfloat16", compile_warmup=False,
            )
            self._bf16 = InferenceEngine(config)
        return self._bf16

    def ragged_engine(self):
        """Warmed CPU engine with the ragged dispatch path on (ISSUE
        12): same geometry as the plain engine, so GL001 can compare
        the two executable censuses directly — the ragged path's whole
        claim is that the per-bucket prefill variants collapse into one
        resident executable."""
        if self._ragged is None:
            import dataclasses

            from ..engine.engine import InferenceEngine

            self.logs.append("building ragged CPU engine (warmup)")
            config = dataclasses.replace(
                self._base_config(), ragged_dispatch=True,
            )
            self._ragged = InferenceEngine(config)
        return self._ragged

    def spec_ragged_engine(self):
        """Warmed CPU engine with BOTH the draft model and the ragged
        dispatch path on (ISSUE 19): the unified spec×ragged path's
        whole claim is that gamma-token verify windows ride the flat
        stream as ordinary ranges — GL001 asserts the path adds zero
        post-warmup executables at both lookahead depths, and GL004's
        census pins its sanctioned-crossing set to the bucketed spec
        engine's (the unification must not mint new crossings)."""
        if self._spec_ragged is None:
            import dataclasses

            from ..engine.engine import InferenceEngine

            self.logs.append("building spec x ragged CPU engine (warmup)")
            config = dataclasses.replace(
                self._base_config(), draft_model="tiny-llama",
                spec_gamma=2, ragged_dispatch=True,
            )
            self._spec_ragged = InferenceEngine(config)
        return self._spec_ragged

    def hostkv_engine(self):
        """Warmed CPU engine with the host KV tier active (ISSUE 15):
        a deliberately TIGHT device pool + an aggressive resident
        floor, so the standard request sweep spills cold prefix pages
        to host at retire and — because GL001/GL004 drive the same mix
        twice (depths 1 and 2) — faults them back on the revisit. Both
        new crossing paths (the eviction gather's packed D2H read, the
        restore's page-payload upload) then run under the transfer
        guard, and the gather/scatter pair's recompile stability is
        probed like any other handle."""
        if self._hostkv is None:
            import dataclasses

            from ..engine.engine import InferenceEngine

            self.logs.append("building host-KV CPU engine (warmup)")
            config = dataclasses.replace(
                self._base_config(), prefix_cache=True,
                num_pages=28, host_kv_bytes=64 << 20,
                host_kv_resident_pages=24,
            )
            self._hostkv = InferenceEngine(config)
        return self._hostkv

    def engines(self):
        yield "engine.plain", self.plain_engine()
        if self.profile != "smoke":
            yield "engine.spec", self.spec_engine()
            yield "engine.ragged", self.ragged_engine()
            yield "engine.spec_ragged", self.spec_ragged_engine()
            yield "engine.hostkv", self.hostkv_engine()

    def jit_handles(self, engine) -> dict[str, object]:
        handles = {
            "_jit_prefill": engine._jit_prefill,
            "_jit_decode": engine._jit_decode,
            "_jit_merge": engine._jit_merge,
            "_jit_retire": engine._jit_retire,
        }
        if engine._spec:
            handles["_jit_spec_prefill"] = engine._jit_spec_prefill
            handles["_jit_spec_decode"] = engine._jit_spec_decode
        if engine._ragged:
            # The bucketed prefill handle is deliberately never compiled
            # in ragged mode — it is census-asserted EMPTY instead (the
            # cold-handle check would misread an intentional zero).
            del handles["_jit_prefill"]
            handles["_jit_ragged"] = engine._jit_ragged
            if engine._spec:
                # Unified path (ISSUE 19): admissions ride the ragged
                # stream, so the bucketed spec prefill never compiles
                # either (census-watched like _jit_prefill); the plain
                # ragged handle only holds the gate-fail fallback, which
                # is warmed (and reachable) only without the top-p
                # prefilter on sampled-warm builds.
                del handles["_jit_spec_prefill"]
                handles["_jit_ragged_spec"] = engine._jit_ragged_spec
                cfg = engine.config
                if not (cfg.warm_sampled_variants
                        and cfg.top_p_candidates == 0):
                    del handles["_jit_ragged"]
        if engine._host_kv is not None:
            # The host tier's fixed-width gather/scatter pair (ISSUE
            # 15): warmed at construction, and a spill or page fault
            # mid-sweep must never mint another executable.
            handles["_jit_kv_gather"] = engine._jit_kv_gather
            handles["_jit_kv_restore"] = engine._jit_kv_restore
        return handles

    def request_mix(self, sampled: bool) -> list[list]:
        """The representative sweep: a slot-filling greedy burst (padded
        group widths 1/2/4), a mid-flight sampled wave (greedy=False
        variants + top-k/top-p paths), then a chunked long prompt plus a
        short chaser (occupancy 1..slots, chunk interleaving)."""
        from ..engine.engine import GenRequest

        def req(prompt_len: int, temperature: float = 0.0,
                top_p: float = 1.0, top_k: int = 0, max_new: int = 6,
                seed: int = 7) -> GenRequest:
            prompt = ("abcdefgh" * 12)[:prompt_len]
            return GenRequest(
                prompt=prompt, max_new_tokens=max_new,
                temperature=temperature, top_p=top_p, top_k=top_k,
                seed=seed,
            )

        if self.profile == "smoke":
            return [
                [req(3), req(12)],
                [req(40)],            # > largest bucket: chunked prefill
                [req(7)],
            ]
        waves = [
            [req(3), req(10), req(20), req(28)],
        ]
        if sampled:
            waves.append([
                req(5, temperature=0.7, top_p=0.9, top_k=5),
                req(18, temperature=1.0),
            ])
        waves.append([req(40), req(6)])  # chunked long prompt + chaser
        return waves

    # -- train fixture (GL002's train.py:110 site) ---------------------------

    def train_fixture(self):
        """(train_step, state, batch) for the donated train step, tiny
        config on a single-device mesh."""
        if self._train is None:
            import jax
            import jax.numpy as jnp
            import numpy as np

            from ..models.config import get_config
            from ..models.transformer import init_params
            from ..parallel.mesh import MeshConfig, create_mesh
            from ..train.train import make_train_step

            cfg = get_config("tiny-llama")
            mesh = create_mesh(MeshConfig(), jax.devices()[:1])
            init_state, train_step, shard_batch = make_train_step(cfg, mesh)
            params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
            state = init_state(params)
            B, T = 2, 8
            tokens = np.zeros((B, T), np.int32)
            targets = np.zeros((B, T), np.int32)
            positions = np.broadcast_to(np.arange(T), (B, T)).astype(np.int32)
            batch = shard_batch(tokens, targets, positions)
            self._train = (train_step, state, batch)
        return self._train

    # -- donation sites (GL002) ----------------------------------------------

    def donation_sites(self):
        """Yield (label, lower_thunk, donated_big_leaf_count) for every
        donate_argnames site: engine.py plain prefill/decode, spec
        prefill/decode, train.py train_step (state). The decode sites
        donate the double-buffered slot state (last_tokens / seq_lens /
        active) alongside the pools (ISSUE 6) — those leaves join the
        big-leaf count (tiny at smoke scale, real at 48 slots) and any
        dropped-donation warning on them fails the audit either way."""
        import jax
        import numpy as np

        for engine_label, engine in self.engines():
            cfg = engine.config
            dev = engine._dev
            put = partial(jax.device_put, device=engine._repl)
            bucket = cfg.prefill_buckets[0]
            window = (
                jax.device_put(
                    np.zeros((1, bucket), np.int32), engine._prefill_tok),
                put(np.zeros((1,), np.int32)),
                put(np.zeros((1,), np.int32)),
                put(np.zeros((1, cfg.pages_per_seq), np.int32)),
                put(np.zeros((1, 2), np.int32)),
                put(np.zeros((1,), np.float32)),
                put(np.ones((1,), np.float32)),
                put(np.zeros((1,), np.int32)),
            )
            # Donated double-buffered slot state rides the decode sites
            # alongside the pools (ISSUE 6): count its leaves too, so an
            # alias dropped on a 48-slot deployment's vectors is a
            # deficit, not a rounding error.
            slot_state = (dev["last_tokens"], dev["seq_lens"], dev["active"])
            if engine._spec:
                pools = (engine.paged, engine.d_paged)
                # The per-lane gamma dial donates alongside the slot
                # state (ISSUE 19): it advances on device every round.
                dial = (dev["accept_ewma"], dev["gamma_lane"])
                if engine._ragged:
                    # Unified path: the prefill site IS the mixed
                    # spec×ragged dispatch — audit ITS donations (both
                    # pools + slot state + dial) instead of the bucketed
                    # spec prefill it never compiles.
                    from ..engine.engine import ragged_zero_operands

                    B = cfg.max_decode_slots
                    gmax = engine._gamma_max
                    pre = ragged_zero_operands(
                        B, engine._ragged_spec_width[gmax],
                        cfg.pages_per_seq,
                    )
                    yield (
                        f"{engine_label}._jit_ragged_spec",
                        partial(
                            engine._jit_ragged_spec.lower,
                            engine.params, engine.draft_params,
                            engine.model_cfg, engine.draft_cfg,
                            engine.paged, engine.d_paged,
                            dev["last_tokens"], dev["seq_lens"],
                            dev["page_tables"], dev["active"],
                            dev["caps"], dev["seeds"],
                            dev["temperature"], dev["top_p"],
                            dev["top_k"], *dial, *pre,
                            gamma=gmax,
                            eos_id=engine.tokenizer.eos_id,
                            gamma_low=engine._gamma_low,
                            gamma_max=engine._gamma_max,
                            greedy=True, candidates=0, mesh=engine.mesh,
                        ),
                        count_big_leaves((pools, slot_state, dial)),
                    )
                else:
                    yield (
                        f"{engine_label}._jit_spec_prefill",
                        partial(
                            engine._jit_spec_prefill.lower,
                            engine.params, engine.draft_params,
                            engine.model_cfg, engine.draft_cfg,
                            engine.paged, engine.d_paged, *window,
                            greedy=True, candidates=cfg.top_p_candidates,
                            mesh=engine.mesh,
                        ),
                        count_big_leaves(pools),
                    )
                yield (
                    f"{engine_label}._jit_spec_decode",
                    partial(
                        engine._jit_spec_decode.lower,
                        engine.params, engine.draft_params,
                        engine.model_cfg, engine.draft_cfg,
                        engine.paged, engine.d_paged,
                        dev["last_tokens"], dev["seq_lens"],
                        dev["page_tables"], dev["active"], dev["caps"],
                        dev["seeds"], dev["temperature"], dev["top_p"],
                        dev["top_k"], *dial,
                        gamma=engine._gamma_max,
                        eos_id=engine.tokenizer.eos_id,
                        gamma_low=engine._gamma_low,
                        gamma_max=engine._gamma_max,
                        candidates=0, mesh=engine.mesh,
                    ),
                    count_big_leaves((pools, slot_state, dial)),
                )
            elif engine._ragged:
                # The ragged engine's prefill site IS the mixed ragged
                # dispatch: audit its donations (pool + slot state)
                # instead of the bucketed prefill it never compiles.
                from ..engine.engine import ragged_zero_operands

                B = cfg.max_decode_slots
                W = engine._ragged_width
                pre = ragged_zero_operands(B, W, cfg.pages_per_seq)
                yield (
                    f"{engine_label}._jit_ragged",
                    partial(
                        engine._jit_ragged.lower,
                        engine.params, engine.model_cfg, engine.paged,
                        dev["last_tokens"], dev["seq_lens"],
                        dev["page_tables"], dev["active"], dev["caps"],
                        dev["seeds"], dev["temperature"], dev["top_p"],
                        dev["top_k"], *pre,
                        greedy=True, eos_id=engine.tokenizer.eos_id,
                        candidates=cfg.top_p_candidates, mesh=engine.mesh,
                    ),
                    count_big_leaves((engine.paged, slot_state)),
                )
            else:
                yield (
                    f"{engine_label}._jit_prefill",
                    partial(
                        engine._jit_prefill.lower,
                        engine.params, engine.model_cfg, engine.paged,
                        *window,
                        greedy=True, candidates=cfg.top_p_candidates,
                        mesh=engine.mesh,
                    ),
                    count_big_leaves(engine.paged),
                )
                yield (
                    f"{engine_label}._jit_decode",
                    partial(
                        engine._jit_decode.lower,
                        engine.params, engine.model_cfg, engine.paged,
                        dev["last_tokens"], dev["seq_lens"],
                        dev["page_tables"], dev["active"], dev["caps"],
                        dev["seeds"], dev["temperature"], dev["top_p"],
                        dev["top_k"],
                        greedy=True, steps=engine._block_steps,
                        eos_id=engine.tokenizer.eos_id,
                        candidates=cfg.top_p_candidates, mesh=engine.mesh,
                    ),
                    count_big_leaves((engine.paged, slot_state)),
                )
            # KV restore scatter — shared by the ISSUE 13 handoff
            # resume and the ISSUE 15 host-tier page fault: donates the
            # pool like every pool-touching dispatch, so its alias map
            # is audited like one. (The gather half of the pair donates
            # nothing — it is a read, the pool stays.)
            P = cfg.pages_per_seq
            zk = np.zeros(
                (engine.model_cfg.num_layers, P, cfg.page_size,
                 engine.model_cfg.num_kv_heads,
                 engine.model_cfg.head_dim),
                engine.paged.k.dtype,
            )
            yield (
                f"{engine_label}._jit_kv_restore",
                partial(
                    engine._jit_kv_restore.lower,
                    engine.paged, np.zeros((P,), np.int32), zk,
                    np.zeros_like(zk),
                ),
                count_big_leaves(engine.paged),
            )
        train_step, state, batch = self.train_fixture()
        yield (
            "train.train_step",
            partial(train_step.lower, state, *batch),
            count_big_leaves(state),
        )

    # -- traced step jaxprs (GL003 / GL004) ----------------------------------

    def step_jaxprs(self):
        """(label, closed_jaxpr, weight_shapes, bf16_path) tuples for the
        serving step functions, traced abstractly (never executed).
        Cached: GL003 and GL004 both walk these, and each trace runs the
        full model (including the decode scan) through make_jaxpr."""
        if self._jaxprs is None:
            self._jaxprs = list(self._trace_step_jaxprs())
        return self._jaxprs

    def _trace_step_jaxprs(self):
        import jax
        import numpy as np

        from ..engine import engine as engine_mod

        for bf16, eng in ((True, self.bf16_engine()),
                          (False, self.plain_engine())):
            cfg = eng.config
            weight_shapes = {
                tuple(leaf.shape)
                for leaf in jax.tree_util.tree_leaves(eng.params)
                if getattr(leaf, "ndim", 0) >= 2 and leaf.size >= 1024
            }
            if eng._dev_dirty or not eng._dev:
                eng._upload_slot_state()
            dev = eng._dev
            bucket = cfg.prefill_buckets[0]
            window = (
                np.zeros((1, bucket), np.int32),
                np.zeros((1,), np.int32), np.zeros((1,), np.int32),
                np.zeros((1, cfg.pages_per_seq), np.int32),
                np.zeros((1, 2), np.int32),
                np.zeros((1,), np.float32), np.ones((1,), np.float32),
                np.zeros((1,), np.int32),
            )
            label = "bf16" if bf16 else "f32"
            model_cfg, mesh = eng.model_cfg, eng.mesh
            prefill = jax.make_jaxpr(
                lambda params, paged, *rest: engine_mod._prefill_fn(
                    params, model_cfg, paged, *rest,
                    greedy=False, candidates=cfg.top_p_candidates, mesh=mesh,
                )
            )(eng.params, eng.paged, *window)
            yield (f"engine.{label}._prefill_fn", prefill,
                   weight_shapes, bf16)
            decode = jax.make_jaxpr(
                lambda params, paged, *rest: engine_mod._decode_fn(
                    params, model_cfg, paged, *rest,
                    greedy=False, steps=2, eos_id=eng.tokenizer.eos_id,
                    candidates=cfg.top_p_candidates, mesh=mesh,
                )
            )(eng.params, eng.paged, dev["last_tokens"], dev["seq_lens"],
              dev["page_tables"], dev["active"], dev["caps"], dev["seeds"],
              dev["temperature"], dev["top_p"], dev["top_k"])
            yield (f"engine.{label}._decode_fn", decode, weight_shapes, bf16)
            # Ragged mixed dispatch (ISSUE 12): traced at the function
            # level (no ragged engine needed — the dtype/callback
            # contracts are properties of the graph, not the warmup).
            # W pads B+W to the kernel's TOKEN_TILE, same rule as the
            # engine's _ragged_width — a misaligned stream would trace
            # only because the gather fallback serves off-TPU, and
            # would crash the kernel path wherever it engages.
            from ..engine.engine import ragged_zero_operands
            from ..ops.ragged_paged_attention_kernel import TOKEN_TILE

            B = cfg.max_decode_slots
            W = 16 + (-(B + 16)) % TOKEN_TILE
            pre = ragged_zero_operands(B, W, cfg.pages_per_seq)
            ragged = jax.make_jaxpr(
                lambda params, paged, *rest: engine_mod._ragged_fn(
                    params, model_cfg, paged, *rest,
                    greedy=False, eos_id=eng.tokenizer.eos_id,
                    candidates=cfg.top_p_candidates, mesh=mesh,
                )
            )(eng.params, eng.paged, dev["last_tokens"], dev["seq_lens"],
              dev["page_tables"], dev["active"], dev["caps"], dev["seeds"],
              dev["temperature"], dev["top_p"], dev["top_k"], *pre)
            yield (f"engine.{label}._ragged_fn", ragged, weight_shapes, bf16)

    def close(self) -> None:
        for engine in (self._plain, self._spec, self._ragged,
                       self._spec_ragged, self._hostkv, self._bf16):
            if engine is not None:
                engine.shutdown()
        self._plain = self._spec = self._ragged = None
        self._spec_ragged = self._hostkv = self._bf16 = None
        self._jaxprs = None


# -- GL001: recompile stability ----------------------------------------------


@register_graph
class RecompileStability(GraphCheck):
    """After compile warmup, a mixed-occupancy request sweep (bucketed and
    chunked prefill, greedy and sampled decode, admissions mid-decode,
    retires, spec rounds with the gamma dial) must not grow ANY jitted
    step's executable cache: one recompile per decode step is the
    canonical silent TPU production killer. Cache sizes are probed via
    the jit handles' _cache_size(), cross-checked with jax.log_compiles
    capture so a firing check names the compiled computation."""

    id = "GL001"
    name = "recompile-stability"
    description = ("each jitted engine step compiles exactly once "
                   "(at warm-up) across a mixed request sweep, at "
                   "lookahead depths 1 and 2")

    def run(self, env: GraphEnv) -> list[Finding]:
        findings: list[Finding] = []
        census: dict = {}
        for label, engine in env.engines():
            handles = env.jit_handles(engine)
            mix = env.request_mix(sampled=engine.config.warm_sampled_variants)
            # The sweep runs at both pipeline depths: depth 1 is the
            # synchronous dispatch-then-read shape, depth 2 the
            # double-buffered overlap (ISSUE 6). Double buffering is a
            # host-side scheduling change over DONATED device buffers —
            # it must not mint a single new executable (the donation
            # chain keeps shapes/dtypes identical across generations).
            # `_depth` is the knob POLYKEY_DISPATCH_LOOKAHEAD sets; the
            # sweep restores the engine's configured depth afterwards.
            def sweep(e=engine, m=mix):
                configured = e._depth
                try:
                    errors: list[str] = []
                    for depth in (1, 2):
                        e._depth = depth
                        errors.extend(drive_engine(e, m))
                    return errors
                finally:
                    e._depth = configured

            if engine._ragged:
                # The bucketed prefill handle is census-watched ACROSS
                # the ragged sweep: jit executable caches are shared
                # between engine instances with identical jit params
                # (the plain engine's warmup already populated this
                # one), so "gone" is a delta claim — serving through
                # the ragged engine must never compile a bucketed
                # variant — not an absolute-zero claim.
                prefill_before = engine._jit_prefill._cache_size()
                if engine._spec:
                    # Same delta claim for the bucketed spec prefill on
                    # the unified path (ISSUE 19): admissions ride the
                    # ragged stream, never the spec prefill buckets.
                    spec_prefill_before = (
                        engine._jit_spec_prefill._cache_size()
                    )
            found, sizes = recompile_findings(label, handles, sweep)
            if engine._ragged:
                sizes["_jit_prefill(bucketed)"] = (
                    prefill_before, engine._jit_prefill._cache_size()
                )
                if engine._spec:
                    sizes["_jit_spec_prefill(bucketed)"] = (
                        spec_prefill_before,
                        engine._jit_spec_prefill._cache_size(),
                    )
            findings.extend(found)
            census[label] = (engine, sizes)
            env.logs.append(
                f"GL001 {label} (depths 1+2): " + ", ".join(
                    f"{n}={b}->{a}" for n, (b, a) in sorted(sizes.items())
                )
            )
        findings.extend(self.census_findings(census, env))
        return findings

    @staticmethod
    def census_findings(census: dict, env) -> list[Finding]:
        """Variant-census comparison (ISSUE 12): with the ragged path
        on, the per-bucket prefill executables must be GONE (the
        bucketed handle compiled nothing) and the post-warmup executable
        census must be STRICTLY smaller than the bucketed engine's at
        identical geometry — one resident ragged executable replacing
        buckets × pad-groups × greedy variants."""
        findings: list[Finding] = []
        pairs = [
            # (ragged-mode label, bucketed baseline, watched-gone handles)
            ("engine.ragged", "engine.plain", ("_jit_prefill",)),
            ("engine.spec_ragged", "engine.spec",
             ("_jit_prefill", "_jit_spec_prefill")),
        ]
        for ragged_label, plain_label, gone_handles in pairs:
            if ragged_label not in census or plain_label not in census:
                continue
            _, plain_sizes = census[plain_label]
            _, ragged_sizes = census[ragged_label]
            watched = []
            for name in gone_handles:
                before, after = ragged_sizes.pop(
                    f"{name}(bucketed)", (0, 0)
                )
                watched.append((name, before, after))
                if after > before:
                    findings.append(graph_finding(
                        "GL001", f"graph:{ragged_label}",
                        f"{ragged_label}:{name}:not-gone",
                        f"the {ragged_label} engine compiled "
                        f"{after - before} bucketed {name} "
                        "executable(s) during its sweep — the ragged "
                        "path exists to make the per-bucket variants "
                        "unreachable, so any compile here means a code "
                        "path leaked back to the bucket table",
                    ))
            plain_total = sum(a for _, a in plain_sizes.values())
            ragged_total = sum(a for _, a in ragged_sizes.values())
            if ragged_total >= plain_total:
                findings.append(graph_finding(
                    "GL001", f"graph:{ragged_label}",
                    f"{ragged_label}:census-not-smaller",
                    f"{ragged_label} executable census {ragged_total} "
                    "is not strictly smaller than the bucketed "
                    f"{plain_label} engine's {plain_total} at identical "
                    "geometry — the single resident ragged executable "
                    "must REPLACE the per-bucket prefill variants, not "
                    "add to them",
                ))
            env.logs.append(
                f"GL001 census: {plain_label}={plain_total} "
                f"{ragged_label}={ragged_total} (" + ", ".join(
                    f"{n} {b}->{a}" for n, b, a in watched
                ) + ")"
            )
        return findings


# -- GL002: donation audit ----------------------------------------------------


@register_graph
class DonationAudit(GraphCheck):
    """Every donate_argnames site in the repo (engine.py plain/spec
    prefill+decode, train.py train_step) lowers and compiles with its
    donations intact: no dropped-donation warnings, and the compiled
    executable's input_output_alias map covers every donated buffer
    >= 1 KiB. The donation chain is also what totally orders dispatches
    on device (engine.py module docstring) — a dropped donation is a
    correctness smell, not just 2x pool HBM."""

    id = "GL002"
    name = "donation-audit"
    description = ("every donate_argnames site compiles to aliased "
                   "in-place buffer updates")

    def run(self, env: GraphEnv) -> list[Finding]:
        findings: list[Finding] = []
        for label, lower, big_leaves in env.donation_sites():
            site = audit_donation_site(label, lower, big_leaves)
            findings.extend(site)
            env.logs.append(
                f"GL002 {label}: {big_leaves} donated buffers, "
                f"{'CLEAN' if not site else f'{len(site)} finding(s)'}"
            )
        return findings


# -- GL003: dtype policy ------------------------------------------------------


@register_graph
class DtypePolicy(GraphCheck):
    """The serving steps' jaxprs obey the dtype policy: no float64
    anywhere (any path), and no f32 upcast of weight-shaped tensors in
    bf16 paths. Mixed-precision activations (norms/softmax/logits in f32)
    are the documented design and do not fire."""

    id = "GL003"
    name = "dtype-policy"
    description = ("no f64 anywhere; bf16 paths never upcast weight "
                   "tensors to f32")

    def run(self, env: GraphEnv) -> list[Finding]:
        findings: list[Finding] = []
        for label, jaxpr, weight_shapes, bf16 in env.step_jaxprs():
            found = dtype_findings(label, jaxpr, weight_shapes, bf16)
            findings.extend(found)
            env.logs.append(
                f"GL003 {label}: "
                f"{'CLEAN' if not found else f'{len(found)} finding(s)'}"
            )
        return findings


# -- GL004: host-transfer guard -----------------------------------------------


# Sanctioned-crossing census (ISSUE 19 satellite): the exact set of
# engine._host_crossing() sites each engine MODE is allowed to fire
# during the guarded serving smoke. This pins the tentpole's crossing
# drop as a GATE: a speculative engine's steady state crosses at the
# block boundary only ("spec-packed" — the once-per-round packed D2H
# that carries tokens, counts, AND the gamma dial), plus the cold-path
# admission/retire scalar sites every mode shares. A new fired site =
# a new per-dispatch host tax someone added without sanctioning it
# here; an expected site that never fires = the fixture stopped
# exercising a crossing this check claims to cover.
_BASE_CROSSINGS = frozenset({
    "merge-upload",         # lane merge scalar upload (admission)
    "first-token-resolve",  # cold-path first-token readback
    "retire-upload",        # retire scalar upload
})
SANCTIONED_CROSSINGS: dict[str, frozenset] = {
    "engine.plain": _BASE_CROSSINGS | {"block-packed"},
    "engine.ragged": _BASE_CROSSINGS | {"block-packed"},
    "engine.spec": _BASE_CROSSINGS | {"spec-packed"},
    "engine.spec_ragged": _BASE_CROSSINGS | {"spec-packed"},
    "engine.hostkv": _BASE_CROSSINGS | {
        "block-packed", "kv-evict-gather", "kv-fault-restore",
    },
}


@register_graph
class HostTransferGuard(GraphCheck):
    """Two halves. Static: the step jaxprs contain no callback/infeed/
    outfeed primitives (a host round-trip per dispatch). Dynamic: a live
    engine smoke runs with jax.transfer_guard('disallow') — the engine's
    deliberate crossings (resolve-point reads, lane merge/retire scalar
    uploads) are annotated with engine._host_crossing(), so any
    UNANNOTATED implicit host<->device transfer added to the serving loop
    raises and surfaces here. On CPU the guard catches implicit
    host-to-device transfers (device-to-host is zero-copy there); on TPU
    the same smoke catches both directions."""

    id = "GL004"
    name = "host-transfer-guard"
    description = ("no callbacks in compiled steps; serving loop passes "
                   "under jax.transfer_guard('disallow')")

    def run(self, env: GraphEnv) -> list[Finding]:
        findings: list[Finding] = []
        for label, jaxpr, _, _ in env.step_jaxprs():
            findings.extend(callback_findings(label, jaxpr))
        findings.extend(self._guarded_smoke(env))
        return findings

    def _guarded_smoke(self, env: GraphEnv) -> list[Finding]:
        # Both serving variants run under the guard: the spec dispatch
        # path has its own annotated crossings (packed + stats reads),
        # and an unannotated transfer added there must trip here too.
        # Both pipeline depths run (ISSUE 6): depth 2 exercises the
        # batched-readback path (_process_step draining LANDED copies
        # behind the dispatch frontier) — its reads must ride the same
        # sanctioned _host_crossing scope as the synchronous depth-1
        # read, or the guard trips here.
        import jax

        from ..engine.engine import CROSSING_CENSUS

        findings: list[Finding] = []
        for label, engine in env.engines():
            waves = env.request_mix(sampled=False)
            # Save/restore the three per-direction options, not the
            # umbrella: updating the umbrella propagates into them, so
            # restoring only it would wipe any pre-set per-direction
            # guard (verified against jax 0.4.37).
            direction_opts = (
                "jax_transfer_guard_host_to_device",
                "jax_transfer_guard_device_to_host",
                "jax_transfer_guard_device_to_device",
            )
            previous = {o: getattr(jax.config, o) for o in direction_opts}
            previous_umbrella = jax.config.jax_transfer_guard
            configured_depth = engine._depth
            census_before = dict(CROSSING_CENSUS)
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                errors = []
                for depth in (1, 2):
                    engine._depth = depth
                    errors.extend(drive_engine(engine, waves))
            finally:
                engine._depth = configured_depth
                # Umbrella first (it propagates into the directions),
                # then each saved per-direction value on top.
                jax.config.update("jax_transfer_guard", previous_umbrella)
                for opt, value in previous.items():
                    jax.config.update(opt, value)
            findings.extend(self._census_findings(
                label, census_before, dict(CROSSING_CENSUS), env,
            ))
            for error in errors:
                key = f"{label}:guarded-smoke"
                if "transfer" in error.lower():
                    findings.append(graph_finding(
                        "GL004", f"graph:{label}", key,
                        "unannotated host<->device transfer in the serving "
                        f"loop (engine smoke under transfer_guard=disallow): "
                        f"{error.splitlines()[0][:200]} — wrap deliberate "
                        "crossings in engine._host_crossing()",
                    ))
                else:
                    findings.append(graph_finding(
                        "GL000", f"graph:{label}", key + ":error",
                        f"GL004 guarded smoke hit a request error: {error}",
                    ))
            if engine.dead is not None:
                findings.append(graph_finding(
                    "GL004", f"graph:{label}",
                    f"{label}:guard-killed-engine",
                    "the engine loop died under transfer_guard=disallow "
                    f"({engine.dead.splitlines()[0][:200]}) — an unannotated "
                    "transfer sits on the loop path itself",
                ))
            if engine._host_kv is not None:
                # Fixture-rot guard (ISSUE 15): the host-KV engine
                # exists to run the eviction gather AND the fault
                # restore under the guard — a sweep that exercised
                # neither proved nothing about the new crossings.
                evicted = engine.metrics.kv_pages_evicted
                restored = engine.metrics.kv_pages_restored
                if evicted == 0 or restored == 0:
                    findings.append(graph_finding(
                        "GL000", f"graph:{label}",
                        f"{label}:hostkv-not-exercised",
                        "GL004's host-KV smoke recorded "
                        f"{evicted} evictions / {restored} restores — "
                        "the sweep no longer drives both host-tier "
                        "crossings (tighten the fixture pool or the "
                        "resident floor)",
                    ))
        env.logs.append(
            "GL004 guarded smoke: "
            + ("CLEAN" if not findings else f"{len(findings)} finding(s)")
        )
        return findings

    @staticmethod
    def _census_findings(label: str, before: dict, after: dict,
                         env) -> list[Finding]:
        """Sanctioned-crossing census for one engine's guarded sweep:
        the set of _host_crossing sites that FIRED (count delta > 0)
        must equal the mode's pinned SANCTIONED_CROSSINGS entry. The
        per-site deltas are logged, so a census regression names the
        site and its per-sweep crossing count."""
        expected = SANCTIONED_CROSSINGS.get(label)
        if expected is None:
            return []
        deltas = {
            site: after.get(site, 0) - before.get(site, 0)
            for site in set(after) | set(before)
        }
        fired = {site for site, n in deltas.items() if n > 0}
        env.logs.append(
            f"GL004 {label} crossing census: " + (", ".join(
                f"{site}={deltas[site]}" for site in sorted(fired)
            ) or "none")
        )
        findings: list[Finding] = []
        for site in sorted(fired - expected):
            findings.append(graph_finding(
                "GL004", f"graph:{label}",
                f"{label}:census:{site}",
                f"unsanctioned host-crossing site '{site}' fired "
                f"{deltas[site]}x during {label}'s guarded sweep — the "
                "serving loop grew a host tax outside the pinned census "
                "(add a per-block/cold-path justification to "
                "SANCTIONED_CROSSINGS or remove the crossing)",
            ))
        for site in sorted(expected - fired):
            findings.append(graph_finding(
                "GL000", f"graph:{label}",
                f"{label}:census-not-exercised:{site}",
                f"sanctioned crossing site '{site}' never fired during "
                f"{label}'s guarded sweep — the fixture no longer "
                "exercises a crossing the census claims to cover",
            ))
        return findings


# -- GL005: shape/layout contracts -------------------------------------------


@register_graph
class ShapeLayoutContracts(GraphCheck):
    """Pallas block-shape and sharding-annotation consistency, verified
    abstractly (no TPU needed):

    - the flash prefill and paged decode kernels trace under eval_shape
      for representative eligible geometries (128-aligned folded lanes,
      int8 KV variant included) and honor their output contracts;
    - kernel eligibility gates agree with the alignment rules they
      encode (use_paged_kernel's 128-lane fold, use_flash's proven head
      dims);
    - every sharding annotation the engine/train path would apply
      (params, KV pool, scale pools) divides its tensor dims exactly, for
      the serving meshes (tp/dp/sp/ep) and the north-star model set."""

    id = "GL005"
    name = "shape-layout-contracts"
    description = ("Pallas block contracts abstract-eval clean; sharding "
                   "annotations divide their dims")

    # Served model set: the tiny CPU-testable configs plus the north-star
    # serving targets (abstract shapes only — an 8B tree is free here).
    MODELS = ("tiny-llama", "tiny-mixtral", "llama-3-8b", "mixtral-8x7b")

    def run(self, env: GraphEnv) -> list[Finding]:
        findings: list[Finding] = []
        findings.extend(self._kernel_contracts())
        findings.extend(self._gate_consistency())
        findings.extend(self._sharding_contracts(env))
        return findings

    def _kernel_contracts(self) -> list[Finding]:
        import jax.numpy as jnp

        from ..ops import flash_attention as flash_mod
        from ..ops import paged_attention_kernel as paged_mod

        findings: list[Finding] = []
        # Flash prefill kernel: eligible geometry (D=64), ragged T/S that
        # the wrapper must pad to block multiples.
        B, T, S, Hq, Hk, D = 1, 130, 257, 4, 2, 64
        findings.extend(abstract_contract(
            "ops.flash_attention",
            lambda q, k, v, pos: flash_mod.flash_attention(
                q, k, v, pos, scale=D ** -0.5, force_kernel=True,
                block_q=64, block_k=128,
            ),
            (
                jnp.zeros((B, T, Hq, D), jnp.bfloat16),
                jnp.zeros((B, S, Hk, D), jnp.bfloat16),
                jnp.zeros((B, S, Hk, D), jnp.bfloat16),
                jnp.zeros((B, T), jnp.int32),
            ),
            [((B, T, Hq, D), "bfloat16")],
        ))
        # Paged decode DMA kernel: folded lane dim Hk*D = 128.
        N, ps, P = 8, 16, 4
        q = jnp.zeros((2, Hq, D), jnp.float32)
        kp = jnp.zeros((N, ps, Hk, D), jnp.float32)
        tables = jnp.zeros((2, P), jnp.int32)
        positions = jnp.zeros((2,), jnp.int32)
        window = jnp.zeros((1,), jnp.int32)
        page_range = jnp.asarray([0, P], jnp.int32)
        findings.extend(abstract_contract(
            "ops.paged_attention_kernel._decode_call",
            lambda *args: paged_mod._decode_call(
                *args, scale=D ** -0.5, logit_softcap=None, interpret=False,
            ),
            (q, kp, kp, tables, positions, window, page_range),
            [((2, Hq, D), "float32"),
             ((2, Hq, 1), "float32"), ((2, Hq, 1), "float32")],
        ))
        # int8-KV variant: (values, scales) pairs, scales [N, ps, Hk].
        kq = jnp.zeros((N, ps, Hk, D), jnp.int8)
        scales = jnp.zeros((N, ps, Hk), jnp.bfloat16)
        findings.extend(abstract_contract(
            "ops.paged_attention_kernel._decode_call[int8]",
            lambda q2, kv, sc, t, p, w, r: paged_mod._decode_call(
                q2, (kv, sc), (kv, sc), t, p, w, r,
                scale=D ** -0.5, logit_softcap=None, interpret=False,
            ),
            (q.astype(jnp.bfloat16), kq, scales, tables, positions, window,
             page_range),
            [((2, Hq, D), "float32"),
             ((2, Hq, 1), "float32"), ((2, Hq, 1), "float32")],
        ))
        findings.extend(self._ragged_contracts())
        return findings

    def _ragged_contracts(self) -> list[Finding]:
        """Ragged kernel (ISSUE 12) geometry/layout contracts, abstract:
        the mixed-stream kernel traces clean across the served model
        matrix's (Hk, D) geometries (page-group divisibility included —
        P deliberately NOT a multiple of G, the ceil arithmetic the
        grid must handle), the int8-KV variant honors the same output
        contract, and the token-tile alignment gate has teeth (a
        misaligned stream must be refused loudly, never silently
        mis-tiled)."""
        import jax.numpy as jnp

        from ..models.config import get_config
        from ..ops import ragged_paged_attention_kernel as ragged_mod

        findings: list[Finding] = []
        TT = ragged_mod.TOKEN_TILE
        T, S, P, N, ps = 2 * TT, 4, 5, 16, 8      # P % G != 0 by design
        starts = jnp.asarray([0, 1, 9, 12], jnp.int32)
        lens = jnp.asarray([1, 8, 3, 2], jnp.int32)
        kvs = jnp.asarray([24, 8, 11, 33], jnp.int32)
        tables = jnp.zeros((S, P), jnp.int32)
        window = jnp.zeros((1,), jnp.int32)
        for model in self.MODELS:
            cfg = get_config(model)
            Hq, Hk, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
            q = jnp.zeros((T, Hq, D), jnp.float32)
            kp = jnp.zeros((N, ps, Hk, D), jnp.float32)
            findings.extend(abstract_contract(
                f"ops.ragged_paged_attention_kernel[{model}]",
                lambda *args, D=D: ragged_mod._ragged_call(
                    *args, scale=D ** -0.5, logit_softcap=None,
                    interpret=False, pages_per_block=2, token_tile=TT,
                ),
                (q, kp, kp, tables, starts, lens, kvs, window),
                [((T, Hq, D), "float32")],
            ))
        # int8-KV variant: (values, scales) pairs, scales [N, ps, Hk].
        cfg = get_config("tiny-llama")
        Hq, Hk, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        q = jnp.zeros((T, Hq, D), jnp.bfloat16)
        kq = jnp.zeros((N, ps, Hk, D), jnp.int8)
        scales = jnp.zeros((N, ps, Hk), jnp.bfloat16)
        findings.extend(abstract_contract(
            "ops.ragged_paged_attention_kernel[int8]",
            lambda q2, kv, sc, *rest: ragged_mod._ragged_call(
                q2, (kv, sc), (kv, sc), *rest,
                scale=D ** -0.5, logit_softcap=None, interpret=False,
                pages_per_block=2, token_tile=TT,
            ),
            (q, kq, scales, tables, starts, lens, kvs, window),
            [((T, Hq, D), "float32")],
        ))
        # Token-tile alignment teeth: T not a multiple of token_tile
        # must raise — a silently mis-tiled stream would attribute
        # tokens to the wrong sequences.
        import jax

        try:
            jax.eval_shape(
                lambda *args: ragged_mod._ragged_call(
                    *args, scale=1.0, logit_softcap=None,
                    interpret=False, token_tile=TT,
                ),
                jnp.zeros((T + 3, Hq, D), jnp.bfloat16),
                jnp.zeros((N, ps, Hk, D), jnp.bfloat16),
                jnp.zeros((N, ps, Hk, D), jnp.bfloat16),
                tables, starts, lens, kvs, window,
            )
        except ValueError:
            pass
        else:
            findings.append(graph_finding(
                "GL005", "graph:ops.ragged_paged_attention_kernel",
                "ragged:tile-alignment-toothless",
                "a token stream that is not a multiple of token_tile "
                "traced clean — the alignment gate lost its teeth and a "
                "misaligned stream would silently mis-tile",
            ))
        return findings

    def _gate_consistency(self) -> list[Finding]:
        from ..models.config import get_config

        return gate_consistency_findings(
            get_config(name) for name in self.MODELS
        )

    def _sharding_contracts(self, env: GraphEnv) -> list[Finding]:
        import jax
        import jax.numpy as jnp

        from ..engine.kv_cache import init_paged_kv
        from ..models.config import get_config
        from ..models.transformer import init_params
        from ..parallel.mesh import MeshConfig, create_mesh
        from ..parallel.sharding import (
            paged_kv_scale_sharding,
            paged_kv_sharding,
            param_shardings,
        )

        findings: list[Finding] = []
        n_devices = len(jax.devices())
        mesh_cfgs = [
            ("tp2", MeshConfig(tp=2), 2),
            ("dp2", MeshConfig(dp=2), 2),
            ("sp2", MeshConfig(sp=2), 2),
            ("tp2dp2", MeshConfig(tp=2, dp=2), 4),
            ("ep2", MeshConfig(ep=2), 2),
        ]
        for model in self.MODELS:
            cfg = get_config(model)
            abstract_params = jax.eval_shape(
                lambda key, c=cfg: init_params(key, c, jnp.bfloat16),
                jax.random.PRNGKey(0),
            )
            pool = jax.eval_shape(
                lambda c=cfg: init_paged_kv(c, 64, 16, jnp.bfloat16)
            )
            scale_pool = jax.eval_shape(
                lambda c=cfg: init_paged_kv(
                    c, 64, 16, jnp.bfloat16, kv_dtype=jnp.int8)
            )
            for mesh_name, mesh_cfg, needed in mesh_cfgs:
                if needed > n_devices:
                    env.logs.append(
                        f"GL005 sharding {model}/{mesh_name}: skipped "
                        f"(needs {needed} devices, have {n_devices})"
                    )
                    continue
                if mesh_cfg.ep > 1 and not cfg.is_moe:
                    continue
                if cfg.num_kv_heads % mesh_cfg.tp != 0:
                    continue  # the engine refuses this combo up front
                mesh = create_mesh(
                    mesh_cfg,
                    jax.devices()[: needed],
                )
                shardings = param_shardings(
                    cfg, mesh, params_tree=abstract_params)
                flat_params, _ = jax.tree_util.tree_flatten(abstract_params)
                flat_shardings, _ = jax.tree_util.tree_flatten(shardings)
                for leaf, sharding in zip(flat_params, flat_shardings):
                    findings.extend(sharding_divisibility(
                        f"params[{model}/{mesh_name}]",
                        tuple(leaf.shape), sharding,
                    ))
                kv_sh = paged_kv_sharding(mesh)
                for leaf in jax.tree_util.tree_leaves(pool):
                    findings.extend(sharding_divisibility(
                        f"kv_pool[{model}/{mesh_name}]",
                        tuple(leaf.shape), kv_sh,
                    ))
                scale_sh = paged_kv_scale_sharding(mesh)
                for leaf in jax.tree_util.tree_leaves(scale_pool):
                    sh = kv_sh if leaf.ndim == 5 else scale_sh
                    findings.extend(sharding_divisibility(
                        f"kv_scale_pool[{model}/{mesh_name}]",
                        tuple(leaf.shape), sh,
                    ))
        return findings


# -- runner + CLI -------------------------------------------------------------


def apply_check_suppressions(findings: list[Finding]) -> list[Finding]:
    """Mark findings whose snippet key carries a class-level suppression
    (the graph tier's disable= analogue; reasons are mandatory by
    construction — the dict value IS the reason)."""
    from dataclasses import replace

    by_id = {check.id: check for check in all_graph_checks()}
    out: list[Finding] = []
    for f in findings:
        reason = by_id.get(f.rule, GraphCheck).SUPPRESSIONS.get(f.snippet)
        if reason is not None:
            out.append(replace(f, suppressed=True, reason=reason))
        else:
            out.append(f)
    return out


def run_graph_checks(
    env: Optional[GraphEnv] = None,
    only: Optional[set[str]] = None,
) -> tuple[list[Finding], GraphEnv]:
    _ensure_cpu_backend()
    if env is None:
        env = GraphEnv()
    findings: list[Finding] = []
    for check in all_graph_checks():
        if only is not None and check.id not in only:
            continue
        try:
            findings.extend(check.run(env))
        except Exception as e:  # a crashed check must not read as clean
            findings.append(graph_finding(
                "GL000", f"graph:{check.id}", f"{check.id}:crashed",
                f"check {check.id} ({check.name}) crashed: "
                f"{type(e).__name__}: {e}",
            ))
    return apply_check_suppressions(findings), env


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.analysis graph",
        description="graphlint: compiled-graph contract analysis for the "
                    "TPU serving stack (CPU-backed; no hardware needed)",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root the baseline file lives under (default: cwd)",
    )
    parser.add_argument(
        "--baseline", default=GRAPH_BASELINE, metavar="FILE",
        help="grandfathering baseline file (missing file = empty baseline)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file entirely",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather every current blocking finding into --baseline",
    )
    parser.add_argument(
        "--prune", action="store_true",
        help="drop baseline entries whose finding no longer fires, keep "
             "the rest, and exit (never adds entries)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings + summary as one JSON object",
    )
    parser.add_argument(
        "--list-checks", action="store_true",
        help="print the check table and exit",
    )
    parser.add_argument(
        "--only", default=None, metavar="GL001[,GL002...]",
        help="run only the named checks",
    )
    return parser


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_checks:
        for check in all_graph_checks():
            print(f"{check.id}  {check.name:<26} {check.description}")
        return 0

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"graphlint: --root {args.root} is not a directory",
              file=sys.stderr)
        return 2
    try:
        # A typo'd id silently running zero checks would read as a
        # clean graph (the exact failure mode GL000 exists to prevent),
        # and a partial run can't tell "fixed" from "not checked"
        # (shared refusal semantics, core.py).
        only = parse_only(args.only, set(_GRAPH_REGISTRY), noun="check")
        require_full_run(partial=only is not None, prune=args.prune,
                         write_baseline=args.write_baseline)
    except UsageError as e:
        print(f"graphlint: {e}", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    env = GraphEnv()
    try:
        findings, env = run_graph_checks(env, only=only)
    finally:
        env.close()
    elapsed = time.monotonic() - t0
    for line in env.logs:
        print(f"graphlint: {line}", file=sys.stderr)

    baseline_path = root / args.baseline
    if args.prune:
        # A crashed check is a partial run in disguise: its real findings
        # were replaced by GL000, so every entry it grandfathers would
        # read "fixed" and get dropped while the debt is still live.
        infra = [f for f in findings if f.rule == "GL000"]
        if infra:
            print(
                f"graphlint: refusing to prune with {len(infra)} GL000 "
                "analyzer-infrastructure finding(s) present — fix the "
                "probe first", file=sys.stderr)
            return 1
        kept, dropped = prune_baseline(baseline_path, findings)
        print(f"graphlint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} from {baseline_path} "
              f"({kept} kept)")
        return 0
    if args.write_baseline:
        # GL000 = the analyzer itself is broken; grandfathering it would
        # make graphlint exit 0 forever while verifying nothing — and a
        # crashed check is a partial run in disguise, so rewriting the
        # file now would drop its still-live grandfathered entries.
        # Refuse BEFORE touching the file.
        infra = [f for f in findings if f.rule == "GL000"]
        if infra:
            print(
                f"graphlint: refusing to write the baseline with "
                f"{len(infra)} GL000 analyzer-infrastructure finding(s) "
                "present — fix the probe first", file=sys.stderr)
            return 1
        count = write_baseline(baseline_path, findings)
        print(f"graphlint: wrote {count} baseline entr"
              f"{'y' if count == 1 else 'ies'} to {baseline_path}")
        return 0

    stale: list[str] = []
    if not args.no_baseline:
        findings, stale = apply_baseline(findings, load_baseline(baseline_path))
        if only is not None:
            # A partial run can't distinguish "fixed" from "not checked";
            # reporting entries of unrun checks as stale would be a false
            # debt-paid signal (and bad --prune advice).
            stale = []

    blocking = [f for f in findings if f.blocking]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "summary": {
                "blocking": len(blocking),
                "suppressed": suppressed,
                "baselined": baselined,
                "stale_baseline_entries": stale,
                "elapsed_s": round(elapsed, 1),
                "graph_clean": not blocking,
            },
        }, indent=2))
    else:
        for f in sorted(findings, key=lambda f: (f.path, f.rule, f.snippet)):
            if f.blocking:
                print(f.render())
        parts = [f"{len(blocking)} blocking"]
        if suppressed:
            parts.append(f"{suppressed} suppressed")
        if baselined:
            parts.append(f"{baselined} baselined")
        print(f"graphlint: {', '.join(parts)} ({elapsed:.1f}s)")
        if stale:
            print(
                f"graphlint: {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) — "
                "re-run with --prune to drop them",
            )
    return 1 if blocking else 0
