"""Grandfathering baseline: pre-existing findings that don't fail the run.

A baseline entry is keyed by a content fingerprint — sha1 over
(rule | path | stripped source line | occurrence index) — NOT by line
number, so unrelated edits above a grandfathered finding don't churn the
file. The occurrence index disambiguates identical lines in one file.

The committed baseline should trend toward empty: fix findings instead
of baselining them; ``--write-baseline`` exists for adopting polylint on
a codebase with debt, and stale entries are reported so the file shrinks
as debt is paid.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "polylint-baseline.json"


def fingerprint(finding: Finding, occurrence: int) -> str:
    payload = f"{finding.rule}|{finding.path}|{finding.snippet}|{occurrence}"
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]


def _with_fingerprints(findings: list[Finding]) -> list[tuple[str, Finding]]:
    seen: dict[tuple[str, str, str], int] = {}
    out: list[tuple[str, Finding]] = []
    for f in findings:
        key = (f.rule, f.path, f.snippet)
        occurrence = seen.get(key, 0)
        seen[key] = occurrence + 1
        out.append((fingerprint(f, occurrence), f))
    return out


def load_baseline(path: Path) -> dict:
    """Baseline dict (empty when the file doesn't exist)."""
    if not path.is_file():
        return {"version": BASELINE_VERSION, "findings": {}}
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}"
        )
    return data


def write_baseline(path: Path, findings: list[Finding]) -> int:
    """Grandfather every blocking finding; returns the entry count.

    Fingerprints are computed over the FULL finding list (suppressed
    ones included) so occurrence indices line up with apply_baseline's —
    filtering first would shift the index of a blocking finding that
    shares its source line with a suppressed twin.
    """
    entries = {
        fp: {
            "rule": f.rule, "path": f.path, "line": f.line,
            "message": f.message,
        }
        for fp, f in _with_fingerprints(findings) if f.blocking
    }
    path.write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "findings": entries},
            indent=2, sort_keys=True,
        ) + "\n",
        encoding="utf-8",
    )
    return len(entries)


def prune_baseline(path: Path, findings: list[Finding]) -> tuple[int, int]:
    """Drop every baseline entry whose finding no longer exists — the
    file was deleted, the line was fixed, or its content changed (any of
    which breaks the fingerprint). Keeps the committed baseline honest:
    entries only ever describe debt that is still real. Returns
    (kept, dropped); the file is rewritten only when something dropped
    (and never created when absent — an empty baseline has nothing to
    prune)."""
    if not path.is_file():
        return 0, 0
    baseline = load_baseline(path)
    _, stale = apply_baseline(findings, baseline)
    entries = baseline.get("findings", {})
    for fp in stale:
        entries.pop(fp, None)
    if stale:
        path.write_text(
            json.dumps(
                {"version": BASELINE_VERSION, "findings": entries},
                indent=2, sort_keys=True,
            ) + "\n",
            encoding="utf-8",
        )
    return len(entries), len(stale)


def apply_baseline(
    findings: list[Finding], baseline: dict
) -> tuple[list[Finding], list[str]]:
    """Mark baselined findings; returns (findings, stale fingerprints) —
    stale entries are baseline lines whose finding no longer exists."""
    from dataclasses import replace

    entries = baseline.get("findings", {})
    matched: set[str] = set()
    out: list[Finding] = []
    for fp, f in _with_fingerprints(findings):
        if f.blocking and fp in entries:
            matched.add(fp)
            out.append(replace(f, baselined=True))
        else:
            out.append(f)
    stale = sorted(set(entries) - matched)
    return out, stale
