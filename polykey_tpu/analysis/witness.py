"""Runtime lock-order witness: the dynamic half of racelint's CL001.

Static lock-order analysis (analysis/concurrency.py) is necessarily
approximate — its call graph both misses edges (dynamic dispatch,
callbacks) and invents them (over-eager name resolution). This module
records what actually happened: with ``POLYKEY_LOCK_WITNESS=1`` in the
environment, every ``threading.Lock()`` / ``threading.RLock()`` created
from code under this repo is wrapped in an instrumented proxy that
maintains a per-thread held-lock stack and, on each acquisition, records
an *observed* lock-order edge (held → acquired) with the acquiring
stack. The graph dumps as JSON at process exit (and on demand), one file
per process under ``POLYKEY_LOCK_WITNESS_OUT`` (a directory — the
disagg drill spans several worker processes).

``python -m polykey_tpu.analysis race --witness <file-or-dir>`` merges
these observed edges into the static acquisition graph: a cycle whose
edges are all witnessed is a deadlock with evidence (real stacks from a
real run), and a static-only edge that never appears in any witness run
is a candidate for an annotation rather than a restructuring.

Identity: a lock is named by its creation site (repo-relative
``path:line``), which is exactly how the static tier names the
``self._lock = threading.Lock()`` assignment — the merge key needs no
runtime registry. Locks created by stdlib/third-party code (queue
internals, logging) are deliberately NOT wrapped: the witness answers
questions about THIS repo's locks, and wrapping the world would bury
those answers in noise.

Approximations (documented, same contract as the static rules):

- Locks created before ``install()`` runs are invisible. The hook lives
  in ``polykey_tpu/__init__`` (env-gated), so package-level and
  instance locks are all covered; only a lock created by code imported
  BEFORE polykey_tpu would be missed.
- A process killed with ``os._exit`` (the worker-exit fault's real
  mode) never dumps — the drill's witness comes from the coordinator
  and the surviving workers, which see the same coordinator-side
  ordering.
- ``threading.Condition`` keeps its default (unwrapped) RLock unless
  handed a wrapped lock explicitly; condition waits are a sanctioned
  blocking pattern and not part of the order graph.
"""

from __future__ import annotations

import _thread
import json
import os
import threading
import traceback

WITNESS_VERSION = 1
ENV_FLAG = "POLYKEY_LOCK_WITNESS"
ENV_OUT = "POLYKEY_LOCK_WITNESS_OUT"

# Frames that never name a lock site but sit between the creating code
# and the factory (the factory itself, dataclasses-generated __init__).
_SKIP_BASENAMES = ("witness.py", "dataclasses.py", "<string>")
# An IMMEDIATE creator in these files means the lock belongs to stdlib
# machinery (Thread._started's Event, Queue internals, Condition's
# default RLock) even when the outer call site is repo code — those
# locks stay unwrapped, or every Thread()/Queue() call would mint a
# phantom graph node at its construction line.
_STDLIB_CREATORS = ("threading.py", "queue.py", "socketserver.py",
                    "logging", "concurrent")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


class _Recorder:
    """Process-global edge store. Guarded by a RAW _thread lock so the
    recorder can never recurse into its own instrumentation."""

    def __init__(self) -> None:
        self._guard = _thread.allocate_lock()
        self._held = threading.local()          # per-thread site stack
        # site -> {"path": rel, "line": n, "acquisitions": count}
        self.sites: dict[str, dict] = {}
        # (src, dst) -> {"count": n, "stack": [...first observed...]}
        self.edges: dict[tuple[str, str], dict] = {}

    def register(self, site: str, path: str, line: int) -> None:
        with self._guard:
            # polylint: disable=ML002(keyed by static lock-construction site: bounded by the codebase, not by traffic)
            entry = self.sites.setdefault(
                site, {"path": path, "line": line, "acquisitions": 0}
            )
            entry.setdefault("locks_created", 0)
            entry["locks_created"] += 1

    def _stack(self) -> list[str]:
        frames = []
        for fs in traceback.extract_stack(limit=24)[:-3]:
            name = os.path.basename(fs.filename)
            if name in _SKIP_BASENAMES:
                continue
            frames.append(f"{_relpath(fs.filename)}:{fs.lineno} "
                          f"in {fs.name}")
        return frames[-10:]

    def on_acquired(self, site: str) -> None:
        held = getattr(self._held, "stack", None)
        if held is None:
            held = self._held.stack = []
        new_edges = [
            (h, site) for h in held
            if h != site and (h, site) not in self.edges
        ]
        stack = self._stack() if new_edges else None
        with self._guard:
            self.sites[site]["acquisitions"] += 1
            for h in held:
                if h == site:
                    continue        # RLock re-entry: not an order edge
                edge = self.edges.get((h, site))
                if edge is None:
                    # polylint: disable=ML002(edge keys are pairs of static lock sites: bounded by the codebase squared, not by traffic)
                    self.edges[(h, site)] = {"count": 1, "stack": stack}
                else:
                    edge["count"] += 1
        held.append(site)

    def on_released(self, site: str) -> None:
        held = getattr(self._held, "stack", None)
        if held and site in held:
            # Remove the most recent occurrence — out-of-order releases
            # (hand-over-hand locking) must not corrupt the stack.
            for i in range(len(held) - 1, -1, -1):
                if held[i] == site:
                    del held[i]
                    break

    def snapshot(self) -> dict:
        with self._guard:
            return {
                "version": WITNESS_VERSION,
                "pid": os.getpid(),
                "sites": {k: dict(v) for k, v in self.sites.items()},
                "edges": [
                    {"src": src, "dst": dst, **dict(data)}
                    for (src, dst), data in sorted(self.edges.items())
                ],
            }


_recorder: _Recorder | None = None
_real_lock = threading.Lock
_real_rlock = threading.RLock


def _relpath(filename: str) -> str:
    absolute = os.path.abspath(filename)
    if absolute.startswith(_REPO_ROOT + os.sep):
        return absolute[len(_REPO_ROOT) + 1:].replace(os.sep, "/")
    return absolute.replace(os.sep, "/")


def _creation_site() -> str | None:
    """Repo-relative path:line of the nearest polykey frame creating the
    lock, or None when the creator is stdlib/third-party code."""
    for fs in reversed(traceback.extract_stack(limit=16)[:-2]):
        if fs.filename.startswith("<frozen"):
            return None     # import machinery — never a repo lock
        name = os.path.basename(fs.filename)
        if name in _SKIP_BASENAMES:
            continue
        parts = fs.filename.replace(os.sep, "/").split("/")
        if name in _STDLIB_CREATORS or any(
            p in _STDLIB_CREATORS for p in parts[-3:]
        ):
            return None
        absolute = os.path.abspath(fs.filename)
        if absolute.startswith(_REPO_ROOT + os.sep):
            return f"{_relpath(absolute)}:{fs.lineno}"
        return None
    return None


class WitnessLock:
    """Instrumented proxy over a real lock primitive. Only the surface
    the repo (and threading.Condition's custom-lock fallback) uses:
    acquire/release/locked and the context-manager protocol."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _recorder is not None:
            _recorder.on_acquired(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        if _recorder is not None:
            _recorder.on_released(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name: str):
        # threading.Condition probes the lock for _is_owned /
        # _release_save / _acquire_restore at construction: forward what
        # the inner primitive has (RLock) and raise AttributeError for
        # what it lacks (plain Lock), so Condition picks the same
        # strategy it would for the unwrapped lock. Condition's
        # wait-time release goes through the inner methods directly —
        # the held-stack keeps the site across the wait, which is the
        # conservative reading (the lock IS re-held on wake).
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<WitnessLock {self._site} over {self._inner!r}>"


def _make_factory(real):
    def factory():
        site = _creation_site()
        if site is None or _recorder is None:
            return real()
        path, _, line = site.rpartition(":")
        _recorder.register(site, path, int(line))
        return WitnessLock(real(), site)
    return factory


def install() -> None:
    """Swap threading.Lock/RLock for witnessing factories and register
    the exit-time dump. Idempotent."""
    global _recorder
    if _recorder is not None:
        return
    _recorder = _Recorder()
    threading.Lock = _make_factory(_real_lock)
    threading.RLock = _make_factory(_real_rlock)
    import atexit

    atexit.register(dump)


def maybe_install() -> bool:
    """install() iff POLYKEY_LOCK_WITNESS=1; returns whether installed."""
    if os.environ.get(ENV_FLAG, "") == "1":
        install()
        return True
    return False


def installed() -> bool:
    return _recorder is not None


def snapshot() -> dict:
    if _recorder is None:
        return {"version": WITNESS_VERSION, "pid": os.getpid(),
                "sites": {}, "edges": []}
    return _recorder.snapshot()


def dump(out: str | None = None) -> str | None:
    """Write this process's witness JSON. `out` (or $POLYKEY_LOCK_WITNESS_OUT,
    default /tmp/polykey-lock-witness) is a DIRECTORY; the file is
    lock_witness_<pid>.json so concurrent worker processes never clobber
    each other. Returns the written path (None when not installed)."""
    if _recorder is None:
        return None
    directory = out or os.environ.get(ENV_OUT, "/tmp/polykey-lock-witness")
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory,
                            f"lock_witness_{os.getpid()}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(snapshot(), f, indent=2, sort_keys=True)
            f.write("\n")
        return path
    except OSError:
        return None  # a failed witness dump must never fail the run


def load_witness(path: str) -> dict:
    """Load one witness file, or merge every lock_witness_*.json in a
    directory (the multi-process drill). Returns the merged snapshot
    shape; raises ValueError on an unreadable/mismatched file."""
    files: list[str]
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, name) for name in os.listdir(path)
            if name.startswith("lock_witness_") and name.endswith(".json")
        )
        if not files:
            raise ValueError(f"no lock_witness_*.json files under {path}")
    else:
        files = [path]
    sites: dict[str, dict] = {}
    edges: dict[tuple[str, str], dict] = {}
    pids: list[int] = []
    for name in files:
        with open(name, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != WITNESS_VERSION:
            raise ValueError(
                f"witness file {name} has version {data.get('version')!r}, "
                f"expected {WITNESS_VERSION}"
            )
        pids.append(int(data.get("pid", 0)))
        for site, info in data.get("sites", {}).items():
            existing = sites.get(site)
            if existing is None:
                sites[site] = dict(info)
            else:
                existing["acquisitions"] = (
                    existing.get("acquisitions", 0)
                    + info.get("acquisitions", 0)
                )
        for edge in data.get("edges", []):
            key = (edge["src"], edge["dst"])
            existing = edges.get(key)
            if existing is None:
                edges[key] = {
                    "count": edge.get("count", 1),
                    "stack": edge.get("stack"),
                }
            else:
                existing["count"] += edge.get("count", 1)
                if not existing.get("stack"):
                    existing["stack"] = edge.get("stack")
    return {
        "version": WITNESS_VERSION,
        "pids": pids,
        "sites": sites,
        "edges": [
            {"src": src, "dst": dst, **data}
            for (src, dst), data in sorted(edges.items())
        ],
    }
