"""Runtime heap witness: the dynamic half of memlint's growth rules.

Static unbounded-growth analysis (analysis/memory.py, ML002) reasons
about container *shape* — it cannot see how fast a deliberately
unbounded structure actually grows, and it cannot see growth hiding in
C extensions or closures. This module records what actually happened:
with ``POLYKEY_HEAP_WITNESS=1`` in the environment, ``tracemalloc``
starts at import and soak harnesses (plus the engine block loop) call
:func:`checkpoint` at round boundaries. Each checkpoint snapshots the
traced Python heap (current/peak), the top allocating files, and —
when the caller passes them — the ledger-declared pool occupancies
(device KV pages, host-tier pages, prefix-store batches), so observed
pool usage can be checked against the static ledger's declared
capacity. The series dumps as JSON at process exit (and on demand),
one file per process under ``POLYKEY_HEAP_WITNESS_OUT`` (a directory —
the disagg drill spans several worker processes).

``python -m polykey_tpu.analysis mem --witness <file-or-dir>`` merges
these series into the static findings: sustained heap growth after
warmup becomes an ML006 finding carrying the top-growing allocation
sites (real evidence from a real run), and a pool observed above its
declared capacity becomes an ML006 capacity violation.

Approximations (documented, same contract as the lock witness):

- tracemalloc sees Python allocations only. Device HBM is the static
  ledger's job (ML001); native buffers (numpy data, jax executables)
  appear as a single opaque allocation at their Python call site,
  which is exactly the attribution the finding needs.
- A process killed with ``os._exit`` (the worker-exit fault's real
  mode) never dumps — the drill's witness comes from the coordinator
  and the surviving workers.
- The first checkpoints of a process include import/compile warmup;
  the merge analysis discards the warmup prefix before fitting growth
  (see memory.py's ``_witness_growth``).
"""

from __future__ import annotations

import json
import os
import sys
import time
import tracemalloc

HEAP_WITNESS_VERSION = 1
ENV_FLAG = "POLYKEY_HEAP_WITNESS"
ENV_OUT = "POLYKEY_HEAP_WITNESS_OUT"
DEFAULT_OUT = "/tmp/polykey-heap-witness"

# The witness itself must obey the discipline it audits: the checkpoint
# series is a hard ring (oldest dropped), and the per-checkpoint top-site
# list is truncated.
_MAX_CHECKPOINTS = 4096
_TOP_SITES = 12
# Engine-loop checkpoints (heartbeat()) self-throttle so an idle spin
# can't flood the ring with identical samples.
_MIN_HEARTBEAT_S = 1.0

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _relpath(filename: str) -> str:
    absolute = os.path.abspath(filename)
    if absolute.startswith(_REPO_ROOT + os.sep):
        return absolute[len(_REPO_ROOT) + 1:].replace(os.sep, "/")
    return absolute.replace(os.sep, "/")


class _Recorder:
    def __init__(self) -> None:
        self.t0 = time.monotonic()
        self.checkpoints: list[dict] = []
        self.dropped = 0
        self._last_heartbeat = 0.0

    def checkpoint(self, label: str, pools: dict | None = None) -> dict:
        current, peak = tracemalloc.get_traced_memory()
        top: list[dict] = []
        try:
            stats = tracemalloc.take_snapshot().statistics("filename")
            for st in stats[:_TOP_SITES]:
                frame = st.traceback[0]
                top.append({
                    "file": _relpath(frame.filename),
                    "bytes": int(st.size),
                    "blocks": int(st.count),
                })
        except Exception:
            pass  # a failed snapshot must never fail the run
        entry = {
            "label": label,
            "elapsed_s": round(time.monotonic() - self.t0, 3),
            "traced_current": int(current),
            "traced_peak": int(peak),
            "top": top,
        }
        if pools:
            entry["pools"] = dict(pools)
        self.checkpoints.append(entry)
        if len(self.checkpoints) > _MAX_CHECKPOINTS:
            del self.checkpoints[0]
            self.dropped += 1
        return entry

    def snapshot(self) -> dict:
        return {
            "version": HEAP_WITNESS_VERSION,
            "pid": os.getpid(),
            "argv0": _relpath(sys.argv[0]) if sys.argv else "",
            "checkpoints": list(self.checkpoints),
            "dropped_checkpoints": self.dropped,
        }


_recorder: _Recorder | None = None


def install() -> None:
    """Start tracemalloc and register the exit-time dump. Idempotent."""
    global _recorder
    if _recorder is not None:
        return
    if not tracemalloc.is_tracing():
        tracemalloc.start()
    _recorder = _Recorder()
    import atexit

    atexit.register(dump)


def maybe_install() -> bool:
    """install() iff POLYKEY_HEAP_WITNESS=1; returns whether installed."""
    if os.environ.get(ENV_FLAG, "") == "1":
        install()
        return True
    return False


def installed() -> bool:
    return _recorder is not None


def checkpoint(label: str, pools: dict | None = None) -> None:
    """Record one labeled heap sample (no-op unless installed). `pools`
    carries observed allocator occupancies keyed by pool name, each a
    ``{"used": n, "capacity": n}`` pair in the pool's native unit
    (pages, batches) so the merge can compare against the declared cap."""
    if _recorder is not None:
        _recorder.checkpoint(label, pools)


def heartbeat(label: str = "engine-block") -> None:
    """Throttled checkpoint for hot loops: records at most one sample
    per _MIN_HEARTBEAT_S, so the engine block loop can call this
    unconditionally when the witness is armed."""
    rec = _recorder
    if rec is None:
        return
    now = time.monotonic()
    if now - rec._last_heartbeat >= _MIN_HEARTBEAT_S:
        rec._last_heartbeat = now
        rec.checkpoint(label)


def snapshot() -> dict:
    if _recorder is None:
        return {"version": HEAP_WITNESS_VERSION, "pid": os.getpid(),
                "argv0": "", "checkpoints": [], "dropped_checkpoints": 0}
    return _recorder.snapshot()


def dump(out: str | None = None) -> str | None:
    """Write this process's witness JSON. `out` (or
    $POLYKEY_HEAP_WITNESS_OUT, default /tmp/polykey-heap-witness) is a
    DIRECTORY; the file is heap_witness_<pid>.json so concurrent worker
    processes never clobber each other. Returns the written path (None
    when not installed)."""
    if _recorder is None:
        return None
    directory = out or os.environ.get(ENV_OUT, DEFAULT_OUT)
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"heap_witness_{os.getpid()}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(snapshot(), f, indent=2, sort_keys=True)
            f.write("\n")
        return path
    except OSError:
        return None  # a failed witness dump must never fail the run


def load_witness(path: str) -> list[dict]:
    """Load one witness file, or every heap_witness_*.json in a
    directory (the multi-process drill). Returns a list of per-process
    snapshots; raises ValueError on an unreadable/mismatched file."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, name) for name in os.listdir(path)
            if name.startswith("heap_witness_") and name.endswith(".json")
        )
        if not files:
            raise ValueError(f"no heap_witness_*.json files under {path}")
    else:
        files = [path]
    out: list[dict] = []
    for name in files:
        with open(name, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != HEAP_WITNESS_VERSION:
            raise ValueError(
                f"heap witness file {name} has version "
                f"{data.get('version')!r}, expected {HEAP_WITNESS_VERSION}"
            )
        out.append(data)
    return out
