"""polylint CLI: ``python -m polykey_tpu.analysis``.

Exit codes: 0 clean (suppressed/baselined findings allowed), 1 blocking
findings, 2 usage error. ``--json`` emits one machine-readable object
(findings + summary) for CI annotation tooling.

``python -m polykey_tpu.analysis graph`` dispatches to the second
analysis tier (graphlint, analysis/graph.py): compiled-graph contract
checks that need jax, traced on a CPU backend. The AST tier here stays
stdlib-only — the dispatch imports graph lazily so the dependency-free
CI lint job is unaffected.

``python -m polykey_tpu.analysis race`` dispatches to the third tier
(racelint, analysis/concurrency.py): concurrency and cross-process
protocol contracts — lock-order cycles, unguarded shared state,
lock-scope escapes, interprocedural blocking-under-lock, and
coordinator/worker protocol conformance. Stdlib-only like this tier.

``python -m polykey_tpu.analysis mem`` dispatches to the fourth tier
(memlint, analysis/memory.py): memory & capacity contracts — the
analytic byte ledger vs chip HBM, unbounded-growth AST rules, knob
documentation/ship contracts, and the runtime heap-witness merge.
Stdlib-only like this tier.

``python -m polykey_tpu.analysis sched`` dispatches to the fifth tier
(schedlint, analysis/sched.py): scheduler liveness & fairness contracts
— progress floors on budget-bounded dispatch loops, round-robin cursor
discipline, frontier ordering, bounded-wait queues, ragged quota
conservation, and the runtime starvation-witness merge. Stdlib-only
like this tier.

``python -m polykey_tpu.analysis all`` runs all five tiers with one
aggregate exit code (and one merged JSON object under ``--json``).

Shared CLI plumbing (``--only`` typo rejection, ``--prune``/
``--write-baseline`` partial-run refusal, ``--witness`` loading) lives
in core.py (parse_only / require_full_run / load_witness_arg raising
UsageError) so the five tiers cannot drift on the refusal semantics.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .baseline import (
    DEFAULT_BASELINE,
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import (
    DEFAULT_TARGETS,
    UsageError,
    all_rules,
    require_full_run,
    run_paths,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.analysis",
        description="polylint: project-invariant static analysis for the "
                    "TPU serving stack",
    )
    parser.add_argument(
        "targets", nargs="*", default=None,
        help=f"files/directories to lint (default: {' '.join(DEFAULT_TARGETS)})",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root paths are reported relative to (default: cwd)",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE, metavar="FILE",
        help="grandfathering baseline file (missing file = empty baseline)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file entirely",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather every current blocking finding into --baseline",
    )
    parser.add_argument(
        "--prune", action="store_true",
        help="drop baseline entries whose finding no longer exists "
             "(deleted file / fixed line / changed content), then exit",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings + summary as one JSON object",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    return parser


def run_all(argv: list[str]) -> int:
    """``python -m polykey_tpu.analysis all [--json]``: polylint +
    racelint + graphlint + memlint + schedlint as one gate. Each tier runs its full
    default sweep against its own committed baseline; the exit code is
    clean only when every tier is. Tier-specific flags (--only, --prune,
    --write-baseline, targets) are refused — partial aggregate runs
    would report 'all clean' while skipping debt (the graphlint --only
    precedent, applied across tiers)."""
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.analysis all",
        description="run every analysis tier (polylint + racelint + "
                    "graphlint + memlint + schedlint) with one "
                    "aggregate exit code",
    )
    parser.add_argument("--root", default=".",
                        help="repo root for every tier (default: cwd)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="one merged JSON object over all tiers")
    args = parser.parse_args(argv)

    import contextlib
    import io

    from . import concurrency, graph, memory, sched

    tiers = (
        ("polylint", main),
        ("racelint", concurrency.main),
        ("graphlint", graph.main),
        ("memlint", memory.main),
        ("schedlint", sched.main),
    )
    results: dict[str, dict] = {}
    codes: dict[str, int] = {}
    for name, tier_main in tiers:
        tier_argv = ["--root", args.root]
        if args.as_json:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                codes[name] = tier_main(tier_argv + ["--json"])
            try:
                results[name] = json.loads(buf.getvalue())
            except ValueError:
                results[name] = {"error": buf.getvalue()[-2000:]}
        else:
            print(f"== {name} ==")
            codes[name] = tier_main(tier_argv)
    aggregate = max(codes.values(), default=0)
    if args.as_json:
        print(json.dumps({
            "tiers": results,
            "summary": {
                "exit_codes": codes,
                "blocking": sum(
                    r.get("summary", {}).get("blocking", 0)
                    for r in results.values()
                ),
                "all_clean": aggregate == 0,
            },
        }, indent=2))
    else:
        status = ", ".join(f"{name}={code}"
                           for name, code in codes.items())
        print(f"analysis all: {status} -> "
              f"{'CLEAN' if aggregate == 0 else 'FAILING'}")
    return aggregate


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "graph":
        # The graph tier needs jax; import only on explicit request so
        # the AST tier keeps running in dependency-free environments.
        from . import graph

        return graph.main(argv[1:])
    if argv and argv[0] == "race":
        from . import concurrency

        return concurrency.main(argv[1:])
    if argv and argv[0] == "mem":
        # memlint is stdlib-only but imports engine.config/roofline for
        # the byte ledger; keep it off the base tier's import path.
        from . import memory

        return memory.main(argv[1:])
    if argv and argv[0] == "sched":
        from . import sched

        return sched.main(argv[1:])
    if argv and argv[0] == "all":
        return run_all(argv[1:])
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<26} {rule.description}")
        return 0

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"polylint: --root {args.root} is not a directory",
              file=sys.stderr)
        return 2
    targets = args.targets or None
    try:
        # A partial run can't tell "fixed" from "not scanned"; pruning
        # against it would drop live baseline entries for every file
        # outside the target list (shared refusal semantics, core.py).
        require_full_run(partial=bool(targets), prune=args.prune,
                         write_baseline=False)
    except UsageError as e:
        print(f"polylint: {e}", file=sys.stderr)
        return 2
    try:
        findings = run_paths(root, targets)
    except FileNotFoundError as e:
        print(f"polylint: {e}", file=sys.stderr)
        return 2

    baseline_path = root / args.baseline
    if args.prune:
        kept, dropped = prune_baseline(baseline_path, findings)
        print(f"polylint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} from {baseline_path} "
              f"({kept} kept)")
        return 0
    if args.write_baseline:
        count = write_baseline(baseline_path, findings)
        print(f"polylint: wrote {count} baseline entr"
              f"{'y' if count == 1 else 'ies'} to {baseline_path}")
        return 0

    stale: list[str] = []
    if not args.no_baseline:
        findings, stale = apply_baseline(findings, load_baseline(baseline_path))

    blocking = [f for f in findings if f.blocking]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "summary": {
                "blocking": len(blocking),
                "suppressed": suppressed,
                "baselined": baselined,
                "stale_baseline_entries": stale,
                "files_clean": not blocking,
            },
        }, indent=2))
    else:
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
            if f.blocking:
                print(f.render())
        parts = [f"{len(blocking)} blocking"]
        if suppressed:
            parts.append(f"{suppressed} suppressed")
        if baselined:
            parts.append(f"{baselined} baselined")
        print(f"polylint: {', '.join(parts)}")
        if stale:
            print(
                f"polylint: {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) — "
                "re-run with --write-baseline to prune",
            )
    return 1 if blocking else 0
