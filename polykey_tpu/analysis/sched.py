"""schedlint — the fifth analysis tier: scheduler liveness & fairness.

The engine loop's scheduling invariants — the interleaved-prefill
progress floor, the starved-first round-robin cursors, the
restore→prefill→decode frontier order, deadline-disciplined queues,
ragged token-range quotas — were enforced only by scattered regression
tests and comments. ROADMAP item 1 (SLO-class-weighted scheduling) is
about to multiply every one of them by a traffic-class dimension, so
this tier turns them into contracts in the ``SL`` namespace alongside
PL/GL/CL/ML, with the same committed-empty baseline
(``schedlint-baseline.json``) and the same line-suppression syntax
(``# polylint: disable=SL002(reason)``). Stdlib-only AST.

``SL001`` progress floor
    A budget- or quota-bounded dispatch loop (an accumulator compared
    against a name containing ``budget``/``quota`` or ending in
    ``_slots``, guarding a break/return) must carry a statically
    provable at-least-one-dispatch conjunct: ``and spent > 0`` or a
    non-empty work-list truthiness test (``and ranges``). The "budget
    waived with no live lanes" and "one chunk regardless of budget"
    disciplines stop being comments and become checked shape.

``SL002`` cursor discipline
    Every modulo-N round-robin cursor (the ``_rr`` naming convention,
    or an ``_RRCursor`` instance) must be advanced or re-anchored on
    EVERY exit path of every consuming method — a cursor read whose
    path can return without a write means the same slot scans first
    forever. The cursor must stay bounded (no un-modded increment),
    and a sweep with an early exit (budget/skip path) must re-anchor
    starved-first somewhere in the method.

``SL003`` frontier ordering
    Inside one engine-loop iteration (the ``while not
    self._stop.is_set()`` loop), restores issue before chunked
    prefills, which issue before the decode dispatch — verified from
    first-call order in the loop body. The ragged batch builder and the
    chunk advancer must skip faulting slots (``restore_pages is not
    None`` → continue): a faulting lane joins no dispatch until the
    restore frontier owns it.

``SL004`` bounded wait
    Every queue/deque a long-lived (lock-holding / serve-loop) class
    consumes must pair with an admission bound (bounded constructor or
    a ``len()``/``qsize()`` comparison) or a shed/deadline-drop path in
    a consuming method — no unboundedly deferrable work class.

``SL005`` quota conservation
    ``_build_ragged_batch`` must clip every range to the remaining
    dispatch width (a ``W - spent`` term inside ``min``), charge the
    budget with exactly the appended range width, and exit on ``>=``
    (overshoot bounded by one range); ``_ragged_prefill_operands`` must
    advance its write offset, its useful-token count, and the per-range
    length vector by the SAME width, so the ranges sum exactly to the
    dispatch offset.

``SL006`` observed starvation (``--witness``)
    Merges runtime starvation-witness summaries
    (analysis/schedwitness.py, ``POLYKEY_SCHED_WITNESS=1``) into the
    static verdict: a slot whose dispatch-boundary wait age exceeded
    the max-starvation-age gate, or whose consecutive-skip count
    exceeded the skip gate, is a finding carrying the frontier, slot,
    and observed numbers. The occupancy/disagg/autopilot smokes run
    under the witness and gate on zero.

``SL000`` is the meta rule (suppression hygiene, unparseable inputs,
stale contract anchors); like the other tiers' ``*000`` it refuses
--prune and --write-baseline while present.
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from pathlib import Path
from typing import Iterable, Iterator, Optional

from .baseline import (
    apply_baseline,
    load_baseline,
    prune_baseline,
    write_baseline,
)
from .core import (
    DEFAULT_TARGETS,
    FileContext,
    Finding,
    Rule,
    UsageError,
    iter_py_files,
    load_witness_arg,
    parse_only,
    require_full_run,
)

SCHED_BASELINE = "schedlint-baseline.json"

# Repo root of the PACKAGE (contract anchors name this repo's engine;
# the scanned --root may be elsewhere, but the frontier contract is
# about the code that actually runs).
_PKG_ROOT = Path(__file__).resolve().parents[2]

ENGINE_REL = "polykey_tpu/engine/engine.py"

# The engine-loop methods whose first-call order IS the frontier
# contract: restores ride ahead of chunked prefills, which ride ahead
# of the decode dispatch (in ragged mode the prefill frontier lives
# inside _dispatch_step's batch builder — after restores, before the
# decode lanes of the same flat dispatch, by construction).
ORDERED_FRONTIERS = (
    "_issue_restores", "_advance_chunked_prefills", "_dispatch_step",
)

# Functions whose existence the SL003/SL005 contracts anchor on; if the
# engine renames them the contract is STALE (SL000), not silently green.
_CONTRACT_ANCHORS = ORDERED_FRONTIERS + (
    "_build_ragged_batch", "_ragged_prefill_operands",
)

# SL006 gates. Engine-loop iterations are milliseconds; the progress
# floor + round-robin bound any eligible slot's wait to ~B iterations,
# so multi-second wait ages mean a lane genuinely aged out. The skip
# gate is the fast-spin backstop: a hot idle loop can rack thousands of
# boundaries per second, so it only fires far beyond fair-share skips.
WITNESS_MAX_WAIT_AGE_S = 5.0
WITNESS_MAX_SKIPS = 100_000


def _anchor(rel: str, needle: str) -> tuple[str, int]:
    """(rel, line) of the first source line containing `needle` in a
    package file — witness findings anchor at the frontier whose
    dispatch boundary observed the starvation."""
    try:
        text = (_PKG_ROOT / rel).read_text(encoding="utf-8")
        for i, line in enumerate(text.splitlines(), 1):
            if needle in line:
                return rel, i
    except OSError:
        pass
    return rel, 1


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def _terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


def _functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------------------
# SL001: progress floor on budget-bounded dispatch loops
# ---------------------------------------------------------------------------


def _budget_like(name: str) -> bool:
    low = name.lower()
    return "budget" in low or "quota" in low or low.endswith("_slots")


def _budget_exit_compare(test: ast.AST, accs: set,
                         ) -> Optional[tuple[str, str]]:
    """(accumulator, budget name) when `test` contains `acc >= budget`
    (either operand order) against a budget-like name; else None."""
    nodes = test.values if isinstance(test, ast.BoolOp) else [test]
    for node in nodes:
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
            continue
        left, op, right = node.left, node.ops[0], node.comparators[0]
        if (isinstance(op, (ast.Gt, ast.GtE))
                and isinstance(left, ast.Name) and left.id in accs
                and _budget_like(_terminal(right))):
            return left.id, _terminal(right)
        if (isinstance(op, (ast.Lt, ast.LtE))
                and isinstance(right, ast.Name) and right.id in accs
                and _budget_like(_terminal(left))):
            return right.id, _terminal(left)
    return None


def _has_progress_conjunct(test: ast.AST, accs: set, grown: set) -> bool:
    """True when the budget exit's own test proves at least one unit
    already dispatched: `and acc > 0`-shaped, or a truthiness test of a
    collection the loop appends dispatched work to (`and ranges`)."""
    if not (isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And)):
        return False
    for v in test.values:
        if (isinstance(v, ast.Compare) and len(v.ops) == 1
                and isinstance(v.left, ast.Name) and v.left.id in accs
                and isinstance(v.ops[0], (ast.Gt, ast.GtE))
                and isinstance(v.comparators[0], ast.Constant)
                and isinstance(v.comparators[0].value, (int, float))
                and (v.comparators[0].value > 0
                     or isinstance(v.ops[0], ast.Gt))):
            return True
        if isinstance(v, (ast.Name, ast.Attribute)) \
                and _terminal(v) in grown:
            return True
    return False


def _body_exits(stmts: list) -> bool:
    """A break/return reachable in this statement list WITHOUT entering
    a nested loop (whose break would not exit the budgeted loop)."""
    for s in stmts:
        if isinstance(s, (ast.Break, ast.Return)):
            return True
        if isinstance(s, ast.If):
            if _body_exits(s.body) or _body_exits(s.orelse):
                return True
        if isinstance(s, ast.With):
            if _body_exits(s.body):
                return True
    return False


class ProgressFloorRule(Rule):
    id = "SL001"
    name = "progress-floor"
    description = ("budget-bounded dispatch loop must prove at least "
                   "one dispatch before the budget exit can fire")

    def applies(self, rel: str) -> bool:
        return rel.startswith("polykey_tpu/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in _functions(ctx.tree):
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                accs = {
                    n.target.id for n in ast.walk(loop)
                    if isinstance(n, ast.AugAssign)
                    and isinstance(n.op, ast.Add)
                    and isinstance(n.target, ast.Name)
                }
                if not accs:
                    continue
                grown = {
                    _terminal(n.func.value) for n in ast.walk(loop)
                    if isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("append", "add")
                }
                for sub in ast.walk(loop):
                    if not isinstance(sub, ast.If):
                        continue
                    hit = _budget_exit_compare(sub.test, accs)
                    if hit is None or not _body_exits(sub.body):
                        continue
                    acc, budget = hit
                    if _has_progress_conjunct(sub.test, accs, grown):
                        continue
                    yield ctx.finding(
                        "SL001", sub,
                        f"budget exit `{acc} >= {budget}` has no progress "
                        f"floor — it can fire before the first dispatch, "
                        f"wedging the frontier when the budget is 0 or "
                        f"mis-tuned; add `and {acc} > 0` (or a non-empty "
                        "work-list conjunct) so one unit always proceeds, "
                        "or annotate SL001(reason)")


# ---------------------------------------------------------------------------
# SL002: round-robin cursor discipline
# ---------------------------------------------------------------------------


def _cursor_attrs(cls: ast.ClassDef) -> dict:
    """Map of cursor attribute name -> idiom ('int' | 'helper'),
    recognized by the `_rr` naming convention (the convention is part
    of the contract) or construction from an *RRCursor* factory."""
    attrs: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and _is_self_attr(node.targets[0]):
            name = node.targets[0].attr
            v = node.value
            if isinstance(v, ast.Call) \
                    and "rrcursor" in _terminal(v.func).lower().replace("_", ""):
                attrs[name] = "helper"
            elif name.endswith("_rr"):
                attrs.setdefault(name, "int")
    return attrs


def _expr_cursor_read(node: ast.AST, attr: str) -> bool:
    """A read form: `(self.X + e) % n` or `self.X.scan(...)`."""
    for n in ast.walk(node):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod) \
                and any(_is_self_attr(s, attr) for s in ast.walk(n.left)):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "scan" \
                and _is_self_attr(n.func.value, attr):
            return True
    return False


def _stmt_cursor_write(node: ast.AST, attr: str,
                       ) -> tuple[bool, Optional[int], bool]:
    """(writes, unbounded_line, reanchors) for one statement: any
    assignment to self.X or .advance()/.reanchor() call counts as a
    write; `self.X = self.X + c` with no modulo is the unbounded form;
    an assignment from a bare Name (the scan loop variable) or a
    .reanchor() call is the starved-first re-anchor form."""
    writes, unbounded, reanchors = False, None, False
    for n in ast.walk(node):
        if isinstance(n, ast.Assign) \
                and any(_is_self_attr(t, attr) for t in n.targets):
            writes = True
            if isinstance(n.value, ast.BinOp) \
                    and isinstance(n.value.op, ast.Add):
                unbounded = n.lineno
            if isinstance(n.value, ast.Name):
                reanchors = True
        if isinstance(n, ast.AugAssign) and _is_self_attr(n.target, attr):
            writes = True
            if isinstance(n.op, ast.Add):
                unbounded = n.lineno
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and _is_self_attr(n.func.value, attr):
            if n.func.attr in ("advance", "reanchor"):
                writes = True
            if n.func.attr == "reanchor":
                reanchors = True
    return writes, unbounded, reanchors


def _check_cursor_exits(fn: ast.FunctionDef, attr: str) -> list[int]:
    """Line numbers of exits reachable after a cursor read but before
    any cursor write — the "same slot scans first forever" paths. A
    conservative path-sensitive walk: branch joins keep `read` if any
    side read and keep `written` only if every surviving side wrote;
    loop bodies are analyzed as one symbolic iteration and never
    guarantee a write (they may run zero times)."""
    violations: list[int] = []

    def visit(stmts: list, read: bool, written: bool,
              ) -> tuple[bool, bool, bool]:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Return):
                if read and not written:
                    violations.append(s.lineno)
                return read, written, True
            if isinstance(s, ast.If):
                if _expr_cursor_read(s.test, attr):
                    read = True
                r1, w1, e1 = visit(s.body, read, written)
                r2, w2, e2 = visit(s.orelse, read, written)
                if e1 and e2:
                    return read, written, True
                if e1:
                    read, written = r2, w2
                elif e2:
                    read, written = r1, w1
                else:
                    read, written = (r1 or r2), (w1 and w2)
                continue
            if isinstance(s, (ast.For, ast.While)):
                header = s.iter if isinstance(s, ast.For) else s.test
                if _expr_cursor_read(header, attr):
                    read = True
                r1, _w1, _e1 = visit(s.body, read, written)
                read = read or r1
                continue
            if isinstance(s, ast.Try):
                r1, w1, _e1 = visit(s.body, read, written)
                read = read or r1
                for h in s.handlers:
                    rh, _wh, _eh = visit(h.body, read, written)
                    read = read or rh
                if s.finalbody:
                    read, written, _ = visit(s.finalbody, read,
                                             written and w1)
                continue
            if isinstance(s, ast.With):
                read, written, exited = visit(s.body, read, written)
                if exited:
                    return read, written, True
                continue
            w, _ub, _re = _stmt_cursor_write(s, attr)
            if w:
                written = True
            if _expr_cursor_read(s, attr):
                read = True
        return read, written, False

    read, written, exited = visit(fn.body, False, False)
    if not exited and read and not written and fn.body:
        violations.append(fn.body[-1].lineno)
    return violations


class CursorRule(Rule):
    id = "SL002"
    name = "cursor-discipline"
    description = ("modulo-N round-robin cursor must advance or "
                   "re-anchor (starved-first) on every consumption path "
                   "and stay bounded")

    def applies(self, rel: str) -> bool:
        return rel.startswith("polykey_tpu/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            cursors = _cursor_attrs(cls)
            for attr in sorted(cursors):
                for fn in (n for n in cls.body
                           if isinstance(n, ast.FunctionDef)):
                    reads = _expr_cursor_read(fn, attr)
                    _w, unbounded, has_reanchor = _stmt_cursor_write(
                        fn, attr)
                    if unbounded is not None and fn.name != "__init__":
                        yield ctx.finding(
                            "SL002", unbounded,
                            f"cursor `{attr}` is advanced without a "
                            "modulo bound — it grows forever and the "
                            "`% n` consumers drift; write "
                            "`(cursor + 1) % n` or use the shared "
                            "_RRCursor helper")
                    if not reads:
                        continue
                    for line in _check_cursor_exits(fn, attr):
                        yield ctx.finding(
                            "SL002", line,
                            f"round-robin cursor `{attr}` is consumed in "
                            f"{fn.name}() but this exit path neither "
                            "advances nor re-anchors it — the same slot "
                            "scans first forever (starvation); advance "
                            "past the anchor on a completed sweep or "
                            "re-anchor on the starved slot")
                    # A sweep with an early exit (budget/skip path) must
                    # re-anchor starved-first SOMEWHERE in the method —
                    # always advancing past the anchor would be fair in
                    # shape but starve the skipped slot of its turn.
                    for loop in ast.walk(fn):
                        if not isinstance(loop, (ast.For, ast.While)):
                            continue
                        header = (loop.iter if isinstance(loop, ast.For)
                                  else loop.test)
                        in_loop = _expr_cursor_read(header, attr) or any(
                            _expr_cursor_read(s, attr) for s in loop.body)
                        if not in_loop:
                            continue
                        early = any(
                            isinstance(n, (ast.Break, ast.Return))
                            for n in ast.walk(loop))
                        if early and not has_reanchor:
                            yield ctx.finding(
                                "SL002", loop,
                                f"cursor `{attr}` sweep in {fn.name}() "
                                "has an early exit but the method never "
                                "re-anchors — the starved slot loses its "
                                "turn to the advance; re-anchor the "
                                "cursor ON the first slot the exit "
                                "skipped")


# ---------------------------------------------------------------------------
# SL003: frontier ordering inside the engine loop
# ---------------------------------------------------------------------------


def _is_engine_loop(node: ast.While) -> bool:
    """`while not self._stop.is_set()` (any attribute spelling that
    calls is_set on a *stop*-named event)."""
    for n in ast.walk(node.test):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "is_set" \
                and "stop" in _terminal(n.func.value).lower():
            return True
    return False


class FrontierOrderRule(Rule):
    id = "SL003"
    name = "frontier-ordering"
    description = ("restore -> prefill -> decode issue order per "
                   "engine-loop iteration; ragged builder and chunk "
                   "advancer skip faulting slots")

    def applies(self, rel: str) -> bool:
        return rel.startswith("polykey_tpu/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, ast.While) or not _is_engine_loop(loop):
                continue
            first_call: dict[str, int] = {}
            for n in ast.walk(loop):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in ORDERED_FRONTIERS:
                    first_call.setdefault(n.func.attr, n.lineno)
            present = [f for f in ORDERED_FRONTIERS if f in first_call]
            for a, b in zip(present, present[1:]):
                if first_call[a] >= first_call[b]:
                    yield ctx.finding(
                        "SL003", first_call[a],
                        f"frontier order violated in the engine loop: "
                        f"{a}() first issues at line {first_call[a]}, "
                        f"after {b}() at line {first_call[b]} — restores "
                        "must ride ahead of prefills ahead of the decode "
                        "dispatch so a faulting lane's pages land before "
                        "anything can read them")
        # The faulting-slot skip guard: only meaningful in modules that
        # have the host-KV restore tier at all (mention restore_pages).
        mentions_restore = any(
            isinstance(n, ast.Attribute) and n.attr == "restore_pages"
            for n in ast.walk(ctx.tree))
        if not mentions_restore:
            return
        for fn in _functions(ctx.tree):
            if fn.name not in ("_build_ragged_batch",
                               "_advance_chunked_prefills"):
                continue
            guarded = False
            for n in ast.walk(fn):
                if isinstance(n, ast.If) and any(
                        isinstance(c, ast.Attribute)
                        and c.attr == "restore_pages"
                        for c in ast.walk(n.test)) \
                        and any(isinstance(b, ast.Continue)
                                for b in n.body):
                    guarded = True
            if not guarded:
                yield ctx.finding(
                    "SL003", fn,
                    f"{fn.name}() does not skip faulting slots "
                    "(`restore_pages is not None` -> continue) — a slot "
                    "whose pages are still on host must not join any "
                    "dispatch until the restore frontier issues its "
                    "scatter")


# ---------------------------------------------------------------------------
# SL004: bounded wait on consumed work queues
# ---------------------------------------------------------------------------

_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "deque"}
_CONSUME_ATTRS = {"get", "get_nowait", "popleft", "pop"}
_SHED_TOKENS = ("deadline", "expire", "shed", "drop")


def _ctor_bounded(call: ast.Call) -> bool:
    name = _terminal(call.func)
    if name == "deque":
        return len(call.args) >= 2 or any(
            k.arg == "maxlen" and not (isinstance(k.value, ast.Constant)
                                       and k.value.value is None)
            for k in call.keywords)
    return bool(call.args) or any(
        k.arg == "maxsize" for k in call.keywords)


def _class_long_lived(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        if _terminal(base) == "Thread":
            return True
    for n in ast.walk(cls):
        if isinstance(n, ast.While):
            if isinstance(n.test, ast.Constant) and n.test.value is True:
                return True
            if any(isinstance(c, ast.Call)
                   and isinstance(c.func, ast.Attribute)
                   and c.func.attr == "is_set"
                   for c in ast.walk(n.test)):
                return True
        if isinstance(n, ast.Call) \
                and _terminal(n.func) in ("Lock", "RLock", "Condition"):
            return True
    return False


class BoundedWaitRule(Rule):
    id = "SL004"
    name = "bounded-wait"
    description = ("queue/deque consumed by a long-lived loop needs an "
                   "admission bound, shed path, or deadline drop")

    def applies(self, rel: str) -> bool:
        return rel.startswith("polykey_tpu/")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef) \
                    or not _class_long_lived(cls):
                continue
            queues: dict[str, tuple[int, bool]] = {}
            for n in ast.walk(cls):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and _is_self_attr(n.targets[0]) \
                        and isinstance(n.value, ast.Call) \
                        and _terminal(n.value.func) in _QUEUE_CTORS:
                    queues.setdefault(
                        n.targets[0].attr,
                        (n.lineno, _ctor_bounded(n.value)))
            if not queues:
                continue
            consumed: dict[str, set[str]] = {}
            sized: set[str] = set()
            for fn in (n for n in cls.body
                       if isinstance(n, ast.FunctionDef)):
                shed_here = any(
                    isinstance(n, ast.Call)
                    and any(t in _terminal(n.func).lower()
                            for t in _SHED_TOKENS)
                    for n in ast.walk(fn))
                for n in ast.walk(fn):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and n.func.attr in _CONSUME_ATTRS \
                            and _is_self_attr(n.func.value) \
                            and n.func.value.attr in queues:
                        consumed.setdefault(n.func.value.attr, set())
                        if shed_here:
                            consumed[n.func.value.attr].add("shed")
                    if isinstance(n, ast.Compare):
                        for side in [n.left] + list(n.comparators):
                            if isinstance(side, ast.Call):
                                f = side.func
                                if isinstance(f, ast.Name) \
                                        and f.id == "len" and side.args \
                                        and _is_self_attr(side.args[0]) \
                                        and side.args[0].attr in queues:
                                    sized.add(side.args[0].attr)
                                if isinstance(f, ast.Attribute) \
                                        and f.attr == "qsize" \
                                        and _is_self_attr(f.value) \
                                        and f.value.attr in queues:
                                    sized.add(f.value.attr)
            for attr, discipline in sorted(consumed.items()):
                line, bounded = queues[attr]
                if bounded or "shed" in discipline or attr in sized:
                    continue
                yield ctx.finding(
                    "SL004", line,
                    f"{cls.name}.{attr} is consumed by a long-lived loop "
                    "with no admission bound, shed path, or deadline "
                    "drop — work queued here can defer unboundedly; "
                    "bound the constructor, compare its length against "
                    "a cap, or drop expired entries at dequeue")


# ---------------------------------------------------------------------------
# SL005: ragged quota conservation
# ---------------------------------------------------------------------------


class QuotaRule(Rule):
    id = "SL005"
    name = "quota-conservation"
    description = ("ragged builder charges the budget with exactly the "
                   "appended range widths; operand builder sums ranges "
                   "to the dispatch offset")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in _functions(ctx.tree):
            if fn.name == "_build_ragged_batch":
                yield from self._check_builder(ctx, fn)
            if fn.name == "_ragged_prefill_operands":
                yield from self._check_operands(ctx, fn)

    def _check_builder(self, ctx: FileContext,
                       fn: ast.FunctionDef) -> Iterator[Finding]:
        # The accumulator: `spent += take` where `take` is also the
        # appended range width — budget charge == dispatched width.
        charge: Optional[tuple[str, str]] = None  # (acc, width)
        for n in ast.walk(fn):
            if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add) \
                    and isinstance(n.target, ast.Name) \
                    and isinstance(n.value, ast.Name):
                charge = (n.target.id, n.value.id)
        if charge is None:
            yield ctx.finding(
                "SL005", fn,
                "_build_ragged_batch does not charge an accumulator "
                "with the range width — the budget cannot conserve "
                "tokens it never counts")
            return
        acc, width = charge
        appended = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "append"
            and any(isinstance(e, ast.Name) and e.id == width
                    for a in n.args for e in ast.walk(a))
            for n in ast.walk(fn))
        if not appended:
            yield ctx.finding(
                "SL005", fn,
                f"_build_ragged_batch charges `{acc} += {width}` but "
                f"never appends `{width}` to the range list — charged "
                "tokens and dispatched tokens drift apart")
        clipped = any(
            isinstance(n, ast.Call) and _terminal(n.func) == "min"
            and any(isinstance(e, ast.BinOp) and isinstance(e.op, ast.Sub)
                    and isinstance(e.right, ast.Name) and e.right.id == acc
                    for a in n.args for e in ast.walk(a))
            for n in ast.walk(fn))
        if not clipped:
            yield ctx.finding(
                "SL005", fn,
                f"_build_ragged_batch does not clip the range width to "
                f"the remaining dispatch width (no `W - {acc}` term "
                "inside min) — the last range can overflow the stream")
        # The budget exit must compare with >= so the overshoot is
        # bounded by ONE range (the progress floor's worth), never two.
        strict_only = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.left, ast.Name) and n.left.id == acc:
                if isinstance(n.ops[0], ast.GtE):
                    strict_only = False
                    break
                if isinstance(n.ops[0], ast.Gt):
                    strict_only = True
        if strict_only:
            yield ctx.finding(
                "SL005", fn,
                f"_build_ragged_batch's budget exit uses `{acc} >` "
                "instead of `>=` — tokens dispatched per iteration can "
                "exceed budget + floor by a full extra range")

    def _check_operands(self, ctx: FileContext,
                        fn: ast.FunctionDef) -> Iterator[Finding]:
        # One width name must advance the write offset, the useful
        # count, and the per-range length vector — the identity that
        # makes sum(rng_len) == final offset == dispatched width.
        aug: dict[str, set[str]] = {}
        sub_assigned: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add) \
                    and isinstance(n.target, ast.Name) \
                    and isinstance(n.value, ast.Name):
                aug.setdefault(n.value.id, set()).add(n.target.id)
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Subscript) \
                    and isinstance(n.value, ast.Name):
                sub_assigned.add(n.value.id)
        ok = any(len(targets) >= 2 and width in sub_assigned
                 for width, targets in aug.items())
        if not ok:
            yield ctx.finding(
                "SL005", fn,
                "_ragged_prefill_operands must advance its write "
                "offset, its useful-token count, and a per-range length "
                "row by the SAME width variable — otherwise the token "
                "ranges no longer sum to the dispatch offset and a "
                "range silently under/over-writes the stream")


# ---------------------------------------------------------------------------
# SL006: observed starvation (runtime witness merge)
# ---------------------------------------------------------------------------

_FRONTIER_ANCHORS = {
    "restore": "def _issue_restores",
    "prefill": "def _build_ragged_batch",
    "decode": "def _dispatch_step",
}


def witness_findings(processes: list[dict],
                     max_wait_age_s: Optional[float] = None,
                     max_skips: Optional[int] = None) -> list[Finding]:
    """SL006: per-process, per-frontier starvation gate over merged
    sched-witness summaries. The wait-age gate is primary (wall-clock
    starvation is what an SLO sees); the consecutive-skip gate is the
    fast-spin backstop."""
    age_gate = WITNESS_MAX_WAIT_AGE_S if max_wait_age_s is None \
        else max_wait_age_s
    skip_gate = WITNESS_MAX_SKIPS if max_skips is None else max_skips
    findings: list[Finding] = []
    for proc in processes:
        pid = proc.get("pid", "?")
        for frontier, st in sorted(proc.get("frontiers", {}).items()):
            rel, line = _anchor(
                ENGINE_REL,
                _FRONTIER_ANCHORS.get(frontier, "def _dispatch_step"))
            age = float(st.get("max_wait_age_s", 0.0))
            if age > age_gate:
                findings.append(Finding(
                    rule="SL006", path=rel, line=line,
                    message=f"observed starvation at the {frontier} "
                            f"frontier (pid {pid}): slot "
                            f"{st.get('max_wait_slot')} waited "
                            f"{age:.3f}s across "
                            f"{st.get('max_consecutive_skips', 0)} "
                            f"skipped dispatch boundaries (gate "
                            f"{age_gate:g}s) — a lane aged out under "
                            "real load",
                    snippet=frontier))
            skips = int(st.get("max_consecutive_skips", 0))
            if skips > skip_gate:
                findings.append(Finding(
                    rule="SL006", path=rel, line=line,
                    message=f"observed starvation at the {frontier} "
                            f"frontier (pid {pid}): slot "
                            f"{st.get('max_skip_slot')} was skipped "
                            f"{skips} consecutive dispatch boundaries "
                            f"(gate {skip_gate}) while eligible",
                    snippet=frontier))
    return findings


def witness_verdict(processes: list[dict],
                    max_wait_age_s: Optional[float] = None,
                    max_skips: Optional[int] = None) -> dict:
    """The merged starvation verdict soak artifacts embed: worst wait
    age and skip count per frontier across every process, the gates,
    and whether the run was starvation-free."""
    frontiers: dict[str, dict] = {}
    for proc in processes:
        for name, st in proc.get("frontiers", {}).items():
            agg = frontiers.setdefault(name, {
                "notes": 0, "serves": 0, "max_wait_age_s": 0.0,
                "max_wait_slot": -1, "max_consecutive_skips": 0,
                "max_skip_slot": -1,
            })
            agg["notes"] += int(st.get("notes", 0))
            agg["serves"] += int(st.get("serves", 0))
            age = float(st.get("max_wait_age_s", 0.0))
            if age > agg["max_wait_age_s"]:
                agg["max_wait_age_s"] = age
                agg["max_wait_slot"] = st.get("max_wait_slot", -1)
            skips = int(st.get("max_consecutive_skips", 0))
            if skips > agg["max_consecutive_skips"]:
                agg["max_consecutive_skips"] = skips
                agg["max_skip_slot"] = st.get("max_skip_slot", -1)
    findings = witness_findings(processes, max_wait_age_s, max_skips)
    worst_age = max(
        (f["max_wait_age_s"] for f in frontiers.values()), default=0.0)
    return {
        "processes": len(processes),
        "gate_max_wait_age_s": (WITNESS_MAX_WAIT_AGE_S
                                if max_wait_age_s is None
                                else max_wait_age_s),
        "gate_max_consecutive_skips": (WITNESS_MAX_SKIPS
                                       if max_skips is None
                                       else max_skips),
        "frontiers": {k: dict(v) for k, v in sorted(frontiers.items())},
        "max_wait_age_s": round(worst_age, 3),
        "findings": [f.message for f in findings],
        "starvation_free": not findings,
    }


# ---------------------------------------------------------------------------
# Rule registry (for --list-rules and namespace validation)
# ---------------------------------------------------------------------------


class _ProjectRule(Rule):
    """Project-scope rule: implemented as a cross-file/witness check,
    present here so the SL namespace validates suppressions and --only
    ids."""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        return iter(())


class WitnessStarvationRule(_ProjectRule):
    id = "SL006"
    name = "observed-starvation"
    description = ("sched witness observed a slot's wait age or "
                   "consecutive skips above the gate (--witness)")


SCHED_RULES: list[Rule] = [
    ProgressFloorRule(), CursorRule(), FrontierOrderRule(),
    BoundedWaitRule(), QuotaRule(), WitnessStarvationRule(),
]


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def _stale_contract_findings(ctx: FileContext) -> list[Finding]:
    """SL000 when the engine no longer carries the anchors SL003/SL005
    verify against — a renamed frontier method must fail loud, not let
    the contract silently stop checking anything."""
    have = {n.name for n in _functions(ctx.tree)}
    findings: list[Finding] = []
    for name in _CONTRACT_ANCHORS:
        if name not in have:
            findings.append(Finding(
                rule="SL000", path=ctx.rel, line=1,
                message=f"frontier contract anchor {name}() is gone "
                        "from the engine — the scheduler contract is "
                        "stale; update ORDERED_FRONTIERS/"
                        "_CONTRACT_ANCHORS in analysis/sched.py"))
    if not any(isinstance(n, ast.While) and _is_engine_loop(n)
               for n in ast.walk(ctx.tree)):
        findings.append(Finding(
            rule="SL000", path=ctx.rel, line=1,
            message="no `while not self._stop.is_set()` engine loop "
                    "found — SL003 has nothing to order; the scheduler "
                    "contract is stale"))
    return findings


def run_sched(root: Path, targets: Optional[Iterable[str]] = None,
              only: Optional[set[str]] = None,
              witness: Optional[list[dict]] = None,
              max_wait_age_s: Optional[float] = None,
              max_skips: Optional[int] = None) -> list[Finding]:
    """Run the sched tier. `only` restricts to the named SL rules
    (already validated); `witness` is the loaded per-process snapshot
    list (SL006). Findings come back sorted with per-file suppressions
    applied (a partial run refuses --prune, so skipping can't drop
    debt)."""
    if targets is None:
        targets = [t for t in DEFAULT_TARGETS if (root / t).exists()]
        if not targets:
            raise FileNotFoundError(
                f"none of the default lint targets "
                f"({', '.join(DEFAULT_TARGETS)}) exist under {root}")
    want = (lambda rid: only is None or rid in only)

    contexts: dict[str, FileContext] = {}
    findings: list[Finding] = []
    for path in iter_py_files(root, targets):
        rel = path.resolve().relative_to(root.resolve()).as_posix()
        if rel.startswith("polykey_tpu/proto/"):
            continue
        source = path.read_text(encoding="utf-8")
        try:
            contexts[rel] = FileContext(path, rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                rule="SL000", path=rel, line=e.lineno or 1,
                message=f"syntax error: {e.msg}"))

    by_path: dict[str, list[Finding]] = {rel: [] for rel in contexts}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)

    for rule in SCHED_RULES:
        if not want(rule.id):
            continue
        for rel, ctx in contexts.items():
            if rule.applies(rel):
                by_path[rel].extend(rule.check(ctx))

    if ENGINE_REL in contexts:
        by_path[ENGINE_REL].extend(
            _stale_contract_findings(contexts[ENGINE_REL]))

    if want("SL006") and witness is not None:
        for f in witness_findings(witness, max_wait_age_s, max_skips):
            by_path.setdefault(f.path, []).append(f)

    out: list[Finding] = []
    for rel in sorted(by_path):
        ctx = contexts.get(rel)
        fs = by_path[rel]
        if ctx is not None:
            fs = ctx.apply_suppressions(fs, rules=SCHED_RULES)
        out.extend(fs)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.analysis sched",
        description="schedlint: scheduler liveness & fairness contract "
                    "analysis (progress floors, cursor discipline, "
                    "frontier order, quota conservation, starvation "
                    "witness)",
    )
    parser.add_argument(
        "targets", nargs="*", default=None,
        help=f"files/directories to scan (default: "
             f"{' '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--root", default=".",
                        help="repo root (default: cwd)")
    parser.add_argument("--baseline", default=SCHED_BASELINE,
                        metavar="FILE",
                        help="grandfathering baseline file")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline file entirely")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather current blocking findings")
    parser.add_argument("--prune", action="store_true",
                        help="drop stale baseline entries, then exit")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings + summary as JSON")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--only", metavar="IDS",
                        help="comma-separated rule ids to run "
                             "(e.g. SL002,SL006)")
    parser.add_argument("--witness", metavar="PATH",
                        help="sched-witness JSON file or directory to "
                             "merge (SL006)")
    parser.add_argument("--max-wait-age", type=float, default=None,
                        metavar="SECONDS",
                        help=f"SL006 wait-age gate (default "
                             f"{WITNESS_MAX_WAIT_AGE_S:g}s)")
    return parser


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        print("SL000  meta                       suppression hygiene, "
              "unparseable inputs, stale contract anchors")
        for rule in SCHED_RULES:
            print(f"{rule.id}  {rule.name:<26} {rule.description}")
        return 0

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"schedlint: --root {args.root} is not a directory",
              file=sys.stderr)
        return 2

    targets = args.targets or None
    try:
        only = parse_only(args.only, {r.id for r in SCHED_RULES})
        require_full_run(partial=bool(targets) or only is not None,
                         prune=args.prune,
                         write_baseline=args.write_baseline)
        from . import schedwitness

        witness = load_witness_arg(args.witness,
                                   schedwitness.load_witness)
    except UsageError as e:
        print(f"schedlint: {e}", file=sys.stderr)
        return 2

    try:
        findings = run_sched(root, targets, only, witness,
                             args.max_wait_age)
    except FileNotFoundError as e:
        print(f"schedlint: {e}", file=sys.stderr)
        return 2

    partial = bool(targets) or only is not None
    if partial:
        # Unused-suppression and stale-baseline signals need the full
        # sweep; a partial run must neither report nor act on them.
        findings = [f for f in findings
                    if not (f.rule == "SL000"
                            and "unused suppression" in f.message)]

    meta = [f for f in findings if f.rule == "SL000" and f.blocking]
    baseline_path = root / args.baseline
    if args.prune:
        if meta:
            print("schedlint: refusing --prune while SL000 findings "
                  "exist (a broken check is a partial run in disguise):",
                  file=sys.stderr)
            for f in meta:
                print(f"  {f.render()}", file=sys.stderr)
            return 2
        kept, dropped = prune_baseline(baseline_path, findings)
        print(f"schedlint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} from {baseline_path} "
              f"({kept} kept)")
        return 0
    if args.write_baseline:
        if meta:
            print("schedlint: refusing --write-baseline while SL000 "
                  "findings exist — fix the infrastructure first:",
                  file=sys.stderr)
            for f in meta:
                print(f"  {f.render()}", file=sys.stderr)
            return 2
        count = write_baseline(baseline_path, findings)
        print(f"schedlint: wrote {count} baseline entr"
              f"{'y' if count == 1 else 'ies'} to {baseline_path}")
        return 0

    stale: list[str] = []
    if not args.no_baseline:
        findings, stale = apply_baseline(
            findings, load_baseline(baseline_path))
        if partial:
            stale = []      # partial runs can't call entries stale

    blocking = [f for f in findings if f.blocking]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)

    if args.as_json:
        payload = {
            "findings": [f.to_json() for f in findings],
            "summary": {
                "blocking": len(blocking),
                "suppressed": suppressed,
                "baselined": baselined,
                "stale_baseline_entries": stale,
                "witness_processes": len(witness) if witness else 0,
                "sched_clean": not blocking,
            },
        }
        if witness:
            payload["witness_verdict"] = witness_verdict(
                witness, args.max_wait_age)
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            if f.blocking:
                print(f.render())
        parts = [f"{len(blocking)} blocking"]
        if suppressed:
            parts.append(f"{suppressed} suppressed")
        if baselined:
            parts.append(f"{baselined} baselined")
        if witness:
            verdict = witness_verdict(witness, args.max_wait_age)
            parts.append(
                f"{len(witness)} witness process"
                f"{'' if len(witness) == 1 else 'es'} merged "
                f"(max wait age {verdict['max_wait_age_s']:g}s)")
        print(f"schedlint: {', '.join(parts)}")
        if stale and not partial:
            print(f"schedlint: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) "
                  "— re-run with --prune")
    return 1 if blocking else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
