"""polylint core: file model, rule registry, suppressions, runner.

Rules operate on a ``FileContext`` — parsed AST plus a tokenize-derived
comment map (comments matter here: a justification comment is part of
the ``except`` contract, and suppressions live in comments). Everything
is stdlib-only so the CLI runs in the dependency-free CI lint job.

Suppression syntax (shown here in the docstring because a literal
example in a comment would parse as a live suppression)::

    x = np.asarray(d)  # polylint: disable=PL001(deliberate resolve point)

A suppression on a comment-only line applies to the next code line (for
statements too long to carry a trailing comment). Reasons are mandatory;
multiple rules separate with commas::

    # polylint: disable=PL001(sync ok), PL003(error surfaces via queue)
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, replace
from pathlib import Path
from typing import Iterable, Iterator, Optional

SUPPRESS_RE = re.compile(r"polylint:\s*disable=(?P<entries>.+)$")
# The reason may itself contain one level of balanced parentheses
# ("async copy (D2H) landed"); deeper nesting is not supported.
# The rule id's two-letter prefix names the tier that owns it: PL = the
# AST tier here, CL = racelint (analysis/concurrency.py), ML = memlint
# (analysis/memory.py), SL = schedlint (analysis/sched.py). One comment
# syntax serves every line-anchored tier; each tier validates only the
# suppressions in its own namespace, so a CL004 annotation in engine
# code is invisible to a plain polylint run instead of an "unknown
# rule" finding.
ENTRY_RE = re.compile(
    r"(?P<rule>[A-Z]{2}\d{3})\s*"
    r"(?:\((?P<reason>[^()]*(?:\([^()]*\)[^()]*)*)\))?"
)
# Every namespace a line-comment suppression can legally target. An
# entry outside this set (a typo'd prefix, or GL — the graph tier
# suppresses via class-level SUPPRESSIONS, not comments) suppresses
# nothing; the base PL tier reports it so it can't sit dead forever.
LINE_TIER_PREFIXES = frozenset({"PL", "CL", "ML", "SL"})


@dataclass
class Suppression:
    rule: str
    reason: str
    target_line: int      # code line this suppression covers
    comment_line: int     # where the comment physically sits
    used: bool = False


@dataclass(frozen=True)
class Finding:
    rule: str             # "PL003"
    path: str             # repo-relative posix path
    line: int             # 1-based
    message: str
    snippet: str = ""     # stripped source line (feeds the baseline hash)
    suppressed: bool = False
    reason: str = ""      # suppression reason when suppressed
    baselined: bool = False

    @property
    def blocking(self) -> bool:
        return not (self.suppressed or self.baselined)

    def render(self) -> str:
        tag = ""
        if self.suppressed:
            tag = f"  [suppressed: {self.reason}]"
        elif self.baselined:
            tag = "  [baselined]"
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tag}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }


class FileContext:
    """One parsed source file: AST, raw lines, comment map, suppressions."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        # line -> comment text (without '#'), via tokenize so '#' inside
        # string literals can't masquerade as comments.
        self.comments: dict[int, str] = {}
        # lines carrying at least one non-comment, non-NL token — used to
        # distinguish trailing comments from comment-only lines.
        self.code_lines: set[int] = set()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
                elif tok.type not in (
                    tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                    tokenize.DEDENT, tokenize.ENDMARKER,
                ):
                    self.code_lines.add(tok.start[0])
        except tokenize.TokenError:
            pass  # partial comment map; the AST parse already succeeded
        self.suppressions: list[Suppression] = []
        # (comment line, rule id or None, detail) — rendered into
        # meta-findings by apply_suppressions, which knows the running
        # tier's namespace (a reasonless CL entry is racelint's problem,
        # not polylint's).
        self.bad_suppressions: list[tuple[int, Optional[str], str]] = []
        self._parse_suppressions()

    # -- helpers rules use ---------------------------------------------------

    def finding(self, rule: str, node, message: str) -> Finding:
        line = node if isinstance(node, int) else node.lineno
        snippet = self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        return Finding(rule=rule, path=self.rel, line=line,
                       message=message, snippet=snippet)

    def has_justification(self, start: int, end: int) -> bool:
        """A non-suppression comment anywhere on lines [start, end]."""
        for line in range(start, end + 1):
            text = self.comments.get(line)
            if text is not None and not SUPPRESS_RE.search(text):
                return True
        return False

    # -- suppressions --------------------------------------------------------

    def _parse_suppressions(self) -> None:
        for line, text in sorted(self.comments.items()):
            m = SUPPRESS_RE.search(text)
            if m is None:
                continue
            target = line
            if line not in self.code_lines:
                # Comment-only line: covers the next code line.
                nxt = line + 1
                while nxt <= len(self.lines) and nxt not in self.code_lines:
                    nxt += 1
                target = nxt
            entries = m.group("entries")
            matched_spans: list[tuple[int, int]] = []
            for em in ENTRY_RE.finditer(entries):
                matched_spans.append(em.span())
                rule, reason = em.group("rule"), (em.group("reason") or "").strip()
                if not reason:
                    self.bad_suppressions.append((
                        line, rule,
                        f"suppression for {rule} is missing its "
                        f"(reason) — write disable={rule}(why this is safe)",
                    ))
                    continue
                self.suppressions.append(Suppression(
                    rule=rule, reason=reason,
                    target_line=target, comment_line=line,
                ))
            leftover = "".join(
                entries[i] for i in range(len(entries))
                if not any(a <= i < b for a, b in matched_spans)
            ).strip(" ,")
            if leftover:
                self.bad_suppressions.append((
                    line, None,
                    f"malformed suppression entry {leftover!r} "
                    "(expected PLxxx(reason))",
                ))

    def apply_suppressions(self, findings: list[Finding],
                           rules: Optional[list["Rule"]] = None,
                           ) -> list[Finding]:
        """Mark suppressed findings and surface suppression hygiene
        problems — for ONE tier's namespace. `rules` is the rule set the
        run used (polylint's full registry when None); only suppressions
        whose id shares a prefix with those rules are validated here, so
        each tier polices its own comments. Rule-less malformed entries
        are attributed to the base PL tier (the one that always runs)."""
        tier_rules = rules if rules is not None else all_rules()
        known = {r.id for r in tier_rules}
        prefixes = {rule_id[:2] for rule_id in known} or {"PL"}
        meta = min(prefixes) + "000"
        out: list[Finding] = []
        for f in findings:
            hit: Optional[Suppression] = None
            for s in self.suppressions:
                if s.rule == f.rule and s.target_line == f.line:
                    hit = s
                    break
            if hit is not None:
                hit.used = True
                out.append(replace(f, suppressed=True, reason=hit.reason))
            else:
                out.append(f)
        for s in self.suppressions:
            if s.rule[:2] not in prefixes:
                # Another LINE tier's namespace validates its own
                # entries; a prefix no line tier owns would otherwise
                # be invisible to every run — the always-running base
                # tier claims it.
                if "PL" in prefixes and s.rule[:2] not in LINE_TIER_PREFIXES:
                    out.append(self.finding(
                        meta, s.comment_line,
                        f"suppression names rule {s.rule} in a "
                        "namespace no line tier owns (valid prefixes: "
                        f"{', '.join(sorted(LINE_TIER_PREFIXES))}) — "
                        "it suppresses nothing",
                    ))
                continue
            if s.rule not in known:
                out.append(self.finding(
                    meta, s.comment_line,
                    f"suppression names unknown rule {s.rule}",
                ))
            elif not s.used:
                out.append(self.finding(
                    meta, s.comment_line,
                    f"unused suppression for {s.rule} — the rule no longer "
                    "fires here; delete the comment",
                ))
        for line, rule, message in self.bad_suppressions:
            if rule is None:
                if "PL" in prefixes:
                    out.append(self.finding(meta, line, message))
            elif rule[:2] in prefixes:
                out.append(self.finding(meta, line, message))
        return out


# -- rule registry ------------------------------------------------------------


class Rule:
    """Base rule. Subclasses set id/name/description and implement check();
    applies() scopes by repo-relative path."""

    id: str = "PL000"
    name: str = "unnamed"
    description: str = ""

    def applies(self, rel: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    inst = cls()
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> list[Rule]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# -- shared CLI plumbing ------------------------------------------------------
#
# Every line-anchored tier's main() repeats the same three safety
# behaviors: --only typo rejection (a typo'd id silently running zero
# rules reads as a clean repo), --prune/--write-baseline refusal on
# partial runs (a partial run can't tell "fixed" from "not scanned"),
# and --witness load-error handling. One implementation here; each tier
# catches UsageError, prints it under its own prog name, and exits 2.


class UsageError(Exception):
    """CLI usage error (exit code 2). The tier main prints str(e) to
    stderr prefixed with its own tier name."""


def parse_only(raw: Optional[str], known: set,
               noun: str = "rule") -> Optional[set]:
    """Parse a --only value against the tier's known ids. Returns the
    selected id set (None = full run); raises UsageError on a typo'd
    id — it must not silently run zero rules."""
    if not raw:
        return None
    only = {t.strip().upper() for t in raw.split(",") if t.strip()}
    unknown = only - set(known)
    if unknown:
        raise UsageError(
            f"unknown {noun} id(s) for --only: {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(known))})"
        )
    return only


def require_full_run(*, partial: bool, prune: bool,
                     write_baseline: bool) -> None:
    """Refuse baseline mutation on a partial run: pruning against it
    drops live entries for everything outside the selection, and
    write-baseline is worse — it rewrites the file from only the run
    rules' findings, silently discarding every other rule's debt."""
    if (prune or write_baseline) and partial:
        flag = "--prune" if prune else "--write-baseline"
        raise UsageError(
            f"{flag} requires a full run (drop --only and explicit targets)"
        )


def load_witness_arg(path: Optional[str], loader):
    """Load a --witness file-or-directory via the tier's loader
    (witness/heapwitness/schedwitness .load_witness). Returns the
    per-process snapshot list, or None when no path was given; raises
    UsageError on unreadable or version-mismatched dumps."""
    if not path:
        return None
    try:
        return loader(path)
    except (OSError, ValueError) as e:
        raise UsageError(f"cannot load witness {path}: {e}") from e


# -- runner -------------------------------------------------------------------

DEFAULT_TARGETS = ("polykey_tpu", "bench.py", "scripts")
_EXCLUDE_DIRS = {"__pycache__"}
# Generated protobuf stubs and this package's test fixtures are not ours
# to lint.
_EXCLUDE_PREFIXES = ("polykey_tpu/proto/",)


def iter_py_files(root: Path, targets: Iterable[str]) -> Iterator[Path]:
    for target in targets:
        p = root / target
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if _EXCLUDE_DIRS.isdisjoint(sub.parts):
                    yield sub
        else:
            # A typo'd target must not let the gate pass with 0 files
            # linted ("0 blocking" on nothing looks like success).
            raise FileNotFoundError(
                f"lint target {target!r} is neither a .py file nor a "
                f"directory under {root}"
            )


def check_file(path: Path, root: Path,
               rules: Optional[list[Rule]] = None) -> list[Finding]:
    rel = path.resolve().relative_to(root.resolve()).as_posix()
    if rel.startswith(_EXCLUDE_PREFIXES):
        return []
    source = path.read_text(encoding="utf-8")
    try:
        ctx = FileContext(path, rel, source)
    except SyntaxError as e:
        return [Finding(rule="PL000", path=rel, line=e.lineno or 1,
                        message=f"syntax error: {e.msg}")]
    findings: list[Finding] = []
    for rule in (rules if rules is not None else all_rules()):
        if rule.applies(rel):
            findings.extend(rule.check(ctx))
    findings = ctx.apply_suppressions(findings, rules=rules)
    return sorted(findings, key=lambda f: (f.line, f.rule))


def run_paths(root: Path, targets: Optional[Iterable[str]] = None,
              rules: Optional[list[Rule]] = None) -> list[Finding]:
    """Lint every .py file under `targets` (repo defaults when None).
    Explicit targets must exist (FileNotFoundError otherwise — a typo'd
    path must not pass as '0 findings'); defaults tolerate absentees so
    partial trees (tests, subprojects) still lint."""
    if targets is None:
        targets = [t for t in DEFAULT_TARGETS if (root / t).exists()]
        if not targets:
            raise FileNotFoundError(
                f"none of the default lint targets "
                f"({', '.join(DEFAULT_TARGETS)}) exist under {root}"
            )
    findings: list[Finding] = []
    for path in iter_py_files(root, targets):
        findings.extend(check_file(path, root, rules))
    return findings
