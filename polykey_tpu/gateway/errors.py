"""Typed RPC failures + per-RPC deadline propagation.

The reference maps every service error to code Unknown (a bare Go error
through the grpc-go machinery). Overload-safe serving needs a richer
contract, and this module is the one place it lives so the handler layer
(server.py), the TPU backend (tpu_service.py), and the resilient client
(client.py) can't drift apart:

- ``RpcStatusError`` subclasses carry the gRPC status code the handler
  should abort with, plus optional trailing metadata
  (``ResourceExhaustedError`` ships the ``retry-after-ms`` hint that
  tells well-behaved clients when to come back);
- the RPC deadline rides a thread-local from the handler (which owns the
  ``ServicerContext``) down to the backend (which doesn't — the Service
  seam is context-free by reference parity), as ``current_span`` already
  does for tracing.

Retryability contract (client.py honors it): UNAVAILABLE and
RESOURCE_EXHAUSTED are retryable — the work was never started (shed at
admission) or the backend is restarting; DEADLINE_EXCEEDED is never
retryable — the budget is gone by definition.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import grpc

RETRY_AFTER_MS_KEY = "retry-after-ms"

# Replica-tier trailer contract (ISSUE 9). On successful LLM RPCs the
# backend stamps which replica served (`replica`) and whether the stream
# was resumed on another replica mid-flight (`restarted` — greedy
# resumes are bit-identical; sampled streams on a speculative engine are
# only distributionally equivalent, which is why the flag exists). On a
# mid-stream UNAVAILABLE the backend attaches `resume-supported` +
# `resume-tokens` so a client can re-issue the request with
# `received_tokens` and get only the missing suffix (client.py).
REPLICA_KEY = "replica"
RESTARTED_KEY = "restarted"
# Disaggregated-tier trailer (ISSUE 13): which prefill/decode worker
# pair served the request ("prefill=P,decode=D"), stamped only when the
# backend is a DisaggPool — the per-request routing breadcrumb the
# worker-death runbook starts from.
TIER_KEY = "tier"
RESUME_SUPPORTED_KEY = "resume-supported"
RESUME_TOKENS_KEY = "resume-tokens"
# Device-time attribution (ISSUE 10): successful LLM RPCs carry the
# request's accumulated device milliseconds — each decode block's
# device-busy window (dispatch gap minus host stall) split across its
# live lanes — so a client can separate "the model was slow" from "the
# server was busy" without scraping anything.
DEVICE_MS_KEY = "device-ms"


class RpcStatusError(RuntimeError):
    """A service failure with an explicit gRPC status code. server.py
    aborts with `code` (and any `trailing_metadata`) instead of the
    default Unknown mapping."""

    code = grpc.StatusCode.UNKNOWN

    def trailing_metadata(self) -> tuple[tuple[str, str], ...]:
        return ()


class DeadlineExceededError(RpcStatusError):
    """The request's deadline passed before the work finished (or could
    start). Never retryable: the client's budget is spent."""

    code = grpc.StatusCode.DEADLINE_EXCEEDED


class ResourceExhaustedError(RpcStatusError):
    """Admission shed the request (queue bound or estimated-delay check).
    Retryable after `retry_after_ms` — shipped as trailing metadata so
    clients that can't parse details still get the hint."""

    code = grpc.StatusCode.RESOURCE_EXHAUSTED

    def __init__(self, message: str, retry_after_ms: Optional[int] = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms

    def trailing_metadata(self) -> tuple[tuple[str, str], ...]:
        if self.retry_after_ms is None:
            return ()
        return ((RETRY_AFTER_MS_KEY, str(int(self.retry_after_ms))),)


class UnavailableError(RpcStatusError):
    """The backend cannot take work right now (engine dead / restarting /
    shut down). Retryable: a supervised restart usually brings it back.
    `trailers` lets the backend attach the mid-stream resume contract
    (resume-supported / resume-tokens) so a well-behaved client re-issues
    with `received_tokens` instead of replaying the whole stream."""

    code = grpc.StatusCode.UNAVAILABLE

    def __init__(self, message: str,
                 trailers: tuple[tuple[str, str], ...] = ()):
        super().__init__(message)
        self._trailers = tuple(trailers)

    def trailing_metadata(self) -> tuple[tuple[str, str], ...]:
        return self._trailers


# -- RPC deadline propagation (handler thread-local) -------------------------

_local = threading.local()


def deadline_from_context(context) -> Optional[float]:
    """Absolute monotonic deadline from a ServicerContext, or None when
    the client set no deadline (gRPC's time_remaining() is None then)."""
    try:
        remaining = context.time_remaining()
    except Exception:
        return None  # in-process stubs/doubles without time_remaining
    if remaining is None:
        return None
    return time.monotonic() + remaining


def set_rpc_deadline(deadline: Optional[float]) -> None:
    """Publish the current RPC's absolute monotonic deadline for the
    backend (handler entry sets it, handler exit clears it — threads are
    pooled, so a missed clear would leak one RPC's deadline into the
    next; both handlers clear in ``finally``)."""
    _local.deadline = deadline


def rpc_deadline() -> Optional[float]:
    return getattr(_local, "deadline", None)


# -- response trailers (handler thread-local) ---------------------------------
# The Service seam is context-free (reference parity), so a backend that
# wants to attach SUCCESS-path trailing metadata (replica id, restarted
# flag) stashes pairs here; the handler (server.py) flushes them into
# the ServicerContext after the service call and clears in `finally`
# (threads are pooled — a missed clear would leak one RPC's trailers
# into the next).


def add_rpc_trailers(*pairs: tuple[str, str]) -> None:
    stash = getattr(_local, "trailers", None)
    if stash is None:
        stash = []
        _local.trailers = stash
    stash.extend(pairs)


def pop_rpc_trailers() -> tuple[tuple[str, str], ...]:
    stash = getattr(_local, "trailers", None)
    _local.trailers = None
    return tuple(stash) if stash else ()
