"""gRPC server reflection (v1 + v1alpha), backed by the default descriptor
pool.

Hand-rolled because grpcio-reflection is not in the image; the reference gets
this from grpc-go (/root/reference/cmd/polykey/main.go:80), whose
reflection.Register serves BOTH grpc.reflection.v1.ServerReflection and the
v1alpha name — modern grpcurl tries v1 first. The v1 protocol is a pure
rename of v1alpha (identical message shapes and field numbers), so one
handler serves both service names with the same wire bytes. Supports the
queries grpcurl issues: list_services, file_containing_symbol, and
file_by_filename (each file response includes transitive imports).
"""

from __future__ import annotations

import grpc
from google.protobuf import descriptor_pool

from ..proto import reflection_v1alpha_pb2 as refl_pb
# Imported for its side effect: registering the v1 file in the default
# descriptor pool, so describing the advertised v1 service name resolves
# (grpc-go registers descriptors for both names).
from ..proto import reflection_v1_pb2 as _refl_v1_pb  # noqa: F401

from ..proto.health_v1_grpc import SERVICE_NAME as _HEALTH_SERVICE
from ..proto.polykey_v2_grpc import SERVICE_NAME as _POLYKEY_SERVICE

SERVICE_NAME = "grpc.reflection.v1alpha.ServerReflection"
SERVICE_NAME_V1 = "grpc.reflection.v1.ServerReflection"

# Services this server exposes, as registered in gateway.server.
_EXPOSED_SERVICES = (
    _POLYKEY_SERVICE, _HEALTH_SERVICE, SERVICE_NAME_V1, SERVICE_NAME,
)


def _file_with_deps(pool, file_desc) -> list[bytes]:
    """A file's serialized FileDescriptorProto plus transitive dependencies."""
    out, seen, stack = [], set(), [file_desc]
    while stack:
        fd = stack.pop()
        if fd.name in seen:
            continue
        seen.add(fd.name)
        out.append(fd.serialized_pb)
        stack.extend(fd.dependencies)
    return out


class ReflectionService:
    def __init__(self, services=_EXPOSED_SERVICES, pool=None):
        self._services = list(services)
        self._pool = pool or descriptor_pool.Default()

    def ServerReflectionInfo(self, request_iterator, context):
        for request in request_iterator:
            response = refl_pb.ServerReflectionResponse(
                valid_host=request.host, original_request=request
            )
            which = request.WhichOneof("message_request")
            try:
                if which == "list_services":
                    response.list_services_response.service.extend(
                        refl_pb.ServiceResponse(name=s) for s in self._services
                    )
                elif which == "file_containing_symbol":
                    fd = self._pool.FindFileContainingSymbol(
                        request.file_containing_symbol
                    )
                    response.file_descriptor_response.file_descriptor_proto.extend(
                        _file_with_deps(self._pool, fd)
                    )
                elif which == "file_by_filename":
                    fd = self._pool.FindFileByName(request.file_by_filename)
                    response.file_descriptor_response.file_descriptor_proto.extend(
                        _file_with_deps(self._pool, fd)
                    )
                else:
                    response.error_response.error_code = (
                        grpc.StatusCode.UNIMPLEMENTED.value[0]
                    )
                    response.error_response.error_message = (
                        f"unsupported reflection request: {which}"
                    )
            except KeyError:
                response.error_response.error_code = (
                    grpc.StatusCode.NOT_FOUND.value[0]
                )
                response.error_response.error_message = "not found"
            yield response


def add_reflection_to_server(servicer: ReflectionService, server) -> None:
    handler = grpc.stream_stream_rpc_method_handler(
        servicer.ServerReflectionInfo,
        request_deserializer=refl_pb.ServerReflectionRequest.FromString,
        response_serializer=refl_pb.ServerReflectionResponse.SerializeToString,
    )
    # Same handler under both names: v1 is wire-identical to v1alpha
    # (grpc-go parity — reflection.Register serves both).
    server.add_generic_rpc_handlers(
        tuple(
            grpc.method_handlers_generic_handler(
                name, {"ServerReflectionInfo": handler}
            )
            for name in (SERVICE_NAME_V1, SERVICE_NAME)
        )
    )
