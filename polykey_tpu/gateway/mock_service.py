"""Mock backend — behavior parity with the reference's MockService.

Reproduces /root/reference/internal/service/mock.go:22-66 exactly:

- every response carries Status{code: 200, message: "Tool executed
  successfully"} (mock.go:24-29);
- ``example_tool`` → "Mock execution of <name> at <RFC3339>" (mock.go:33-36);
- ``struct_tool`` → {result, timestamp, data:{processed, count:42}}
  (mock.go:37-51);
- ``file_tool``   → File{example.txt, text/plain, fixed bytes} (mock.go:52-59);
- unknown tools   → "Unknown tool: <name>" as a *successful* string output —
  NOT an error (mock.go:60-63).

This is also the framework's CPU-only test double: the whole gRPC stack runs
against it with zero TPU involvement, the same role the mock plays in the
reference's integration tier (SURVEY.md §4).
"""

from __future__ import annotations

import datetime
import time
from typing import Iterator, Optional

from ..proto import common_v2_pb2 as cmn
from ..proto import polykey_v2_pb2 as pk
from .service import Service
from google.protobuf import struct_pb2


def _rfc3339_now() -> str:
    # Go's time.RFC3339: second precision with numeric zone offset.
    return datetime.datetime.now().astimezone().isoformat(timespec="seconds")


class MockService(Service):
    def execute_tool(
        self,
        tool_name: str,
        parameters: Optional[struct_pb2.Struct],
        secret_id: Optional[str],
        metadata: Optional[cmn.Metadata],
    ) -> pk.ExecuteToolResponse:
        response = pk.ExecuteToolResponse(
            status=cmn.Status(code=200, message="Tool executed successfully")
        )

        if tool_name == "example_tool":
            response.string_output = (
                f"Mock execution of {tool_name} at {_rfc3339_now()}"
            )
        elif tool_name == "struct_tool":
            response.struct_output.update(
                {
                    "result": "success",
                    "timestamp": int(time.time()),
                    "data": {"processed": True, "count": 42},
                }
            )
        elif tool_name == "file_tool":
            response.file_output.CopyFrom(
                cmn.File(
                    file_name="example.txt",
                    mime_type="text/plain",
                    content=b"This is mock file content",
                )
            )
        else:
            response.string_output = f"Unknown tool: {tool_name}"

        return response

    def execute_tool_stream(
        self,
        tool_name: str,
        parameters: Optional[struct_pb2.Struct],
        secret_id: Optional[str],
        metadata: Optional[cmn.Metadata],
    ) -> Iterator[pk.ExecuteToolStreamChunk]:
        """Deterministic word-by-word stream, for exercising the streaming
        path without a TPU (the engine's mock-engine analog of mock.go)."""
        resp = self.execute_tool(tool_name, parameters, secret_id, metadata)
        if resp.WhichOneof("output") == "string_output":
            words = resp.string_output.split(" ")
            for i, word in enumerate(words):
                yield pk.ExecuteToolStreamChunk(
                    delta=word if i == 0 else " " + word
                )
        yield pk.ExecuteToolStreamChunk(final=True, status=resp.status)
