"""gRPC server wiring — parity with the reference server binary.

Reproduces /root/reference/cmd/polykey/main.go end to end:

- listen address from ``LISTEN_ADDR``, default ``:50051`` (main.go:57-59);
- keepalive: MaxConnectionIdle 5m, Time 2h, Timeout 20s (main.go:68-72);
- unary logging interceptor that skips health checks (main.go:25-52);
- health service with SERVING for ``polykey.v2.PolykeyService`` and ``""``
  (main.go:82-94), plus server reflection (main.go:80);
- startup log of the registered service/method table (main.go:97-103);
- graceful drain on SIGINT/SIGTERM: health shutdown first, then server stop
  (main.go:113-120).

The RPC handler mirrors internal/server/server.go: log the request shape, then
delegate to the Service seam, passing errors through unchanged (a plain
service error surfaces as code Unknown, as a bare Go error does).
"""

from __future__ import annotations

import os
import signal
import threading
from concurrent import futures
from typing import Optional

import grpc

from ..proto import health_v1_pb2 as health_pb
from ..proto import polykey_v2_pb2 as pk
from ..proto.health_v1_grpc import add_HealthServicer_to_server
from ..proto.polykey_v2_grpc import (
    SERVICE_NAME,
    PolykeyServiceServicer,
    add_PolykeyServiceServicer_to_server,
)
from ..obs import DebugSurface, MetricsHTTPServer, Observability
from . import errors
from .health import HealthService
from .interceptor import LoggingInterceptor
from .jsonlog import Logger
from .reflection import SERVICE_NAME as REFLECTION_SERVICE_NAME
from .reflection import SERVICE_NAME_V1 as REFLECTION_SERVICE_NAME_V1
from .reflection import ReflectionService, add_reflection_to_server
from .service import Service
from ..proto.health_v1_grpc import SERVICE_NAME as HEALTH_SERVICE_NAME

_KEEPALIVE_OPTIONS = [
    ("grpc.max_connection_idle_ms", 5 * 60 * 1000),   # MaxConnectionIdle 5m
    ("grpc.keepalive_time_ms", 2 * 60 * 60 * 1000),   # Time 2h
    ("grpc.keepalive_timeout_ms", 20 * 1000),         # Timeout 20s
    # Fail loudly when the port is taken (Go's net.Listen behavior) instead
    # of silently sharing it via Linux SO_REUSEPORT.
    ("grpc.so_reuseport", 0),
]

class PolykeyServer(PolykeyServiceServicer):
    """RPC handler layer (reference: internal/server/server.go:12-43)."""

    def __init__(self, service: Service, logger: Optional[Logger] = None):
        self.service = service
        self.logger = logger or Logger()

    def _log_call(self, rpc: str, request: pk.ExecuteToolRequest) -> None:
        self.logger.info(
            f"{rpc} called",
            tool_name=request.tool_name,
            has_parameters=request.HasField("parameters"),
            has_secret_id=request.HasField("secret_id"),
            has_metadata=request.HasField("metadata"),
        )

    @staticmethod
    def _unpack(request: pk.ExecuteToolRequest):
        return (
            request.tool_name,
            request.parameters if request.HasField("parameters") else None,
            request.secret_id if request.HasField("secret_id") else None,
            request.metadata if request.HasField("metadata") else None,
        )

    def _abort_status(self, rpc: str, context, e: errors.RpcStatusError):
        """Abort with the typed error's code + trailing metadata (the
        retry-after-ms contract rides the ResourceExhaustedError
        trailer; the interceptor's recording context merges it with the
        x-trace-id echo). Sheds and deadline expiries are EXPECTED
        flow-control outcomes that spike exactly when the server is
        overloaded — they log at warn so the O(1) fast-reject path can't
        drown real errors in ERROR-level log volume."""
        expected = e.code in (
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            grpc.StatusCode.DEADLINE_EXCEEDED,
        )
        log = self.logger.warn if expected else self.logger.error
        log(f"Service {rpc} failed", error=str(e), code=e.code.name)
        metadata = e.trailing_metadata()
        if metadata:
            try:
                context.set_trailing_metadata(metadata)
            except Exception:
                pass  # in-process doubles without trailer support
        context.abort(e.code, str(e))

    @staticmethod
    def _flush_trailers(context) -> None:
        """Success-path trailing metadata the backend stashed through
        errors.add_rpc_trailers (replica id, restarted flag): set it on
        the context, where the interceptor's recording proxy merges it
        with the x-trace-id echo. Error paths carry their trailers on
        the typed error instead (_abort_status)."""
        trailers = errors.pop_rpc_trailers()
        if trailers:
            try:
                context.set_trailing_metadata(trailers)
            except Exception:
                pass  # in-process doubles without trailer support

    def ExecuteTool(self, request, context):
        self._log_call("ExecuteTool", request)
        # Deadline propagation (ISSUE 3): the Service seam is
        # context-free (reference parity), so the RPC's remaining budget
        # rides a thread-local the backend stamps onto GenRequest.
        errors.set_rpc_deadline(errors.deadline_from_context(context))
        try:
            response = self.service.execute_tool(*self._unpack(request))
            self._flush_trailers(context)
            return response
        except errors.RpcStatusError as e:
            self._abort_status("ExecuteTool", context, e)
        except Exception as e:
            self.logger.error("Service ExecuteTool failed", error=str(e))
            context.abort(grpc.StatusCode.UNKNOWN, str(e))
        finally:
            errors.set_rpc_deadline(None)  # handler threads are pooled
            errors.pop_rpc_trailers()      # drop any stash an abort left

    def ExecuteToolStream(self, request, context):
        self._log_call("ExecuteToolStream", request)
        errors.set_rpc_deadline(errors.deadline_from_context(context))
        try:
            yield from self.service.execute_tool_stream(*self._unpack(request))
            self._flush_trailers(context)
        except errors.RpcStatusError as e:
            self._abort_status("ExecuteToolStream", context, e)
        except Exception as e:
            self.logger.error("Service ExecuteToolStream failed", error=str(e))
            context.abort(grpc.StatusCode.UNKNOWN, str(e))
        finally:
            errors.set_rpc_deadline(None)
            errors.pop_rpc_trailers()


def normalize_address(addr: str) -> str:
    """Accept Go-style ':50051' (bind all interfaces) as well as host:port."""
    if addr.startswith(":"):
        return "[::]" + addr
    return addr


def build_server(
    service: Service,
    logger: Optional[Logger] = None,
    address: str = ":50051",
    max_workers: int = 32,
    health: Optional[HealthService] = None,
    obs: Optional[Observability] = None,
):
    """Assemble the fully-wired gRPC server; returns (server, health, port).

    An existing HealthService may be passed in so backends created before the
    server (the engine + its watchdog) can flip serving status. Passing an
    `Observability` bundle turns on request tracing (root spans in the
    interceptor, children from the backend) and RPC counters; the same
    bundle should be shared with the backend (TpuService) and the /metrics
    exposition server so all three see one registry and one recorder.
    """
    logger = logger or Logger()
    server = grpc.server(
        futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="polykey-rpc"
        ),
        interceptors=[LoggingInterceptor(logger, obs=obs)],
        options=_KEEPALIVE_OPTIONS,
    )

    add_PolykeyServiceServicer_to_server(PolykeyServer(service, logger), server)

    if health is None:
        health = HealthService()
    add_HealthServicer_to_server(health, server)
    health.set_serving_status(SERVICE_NAME, health_pb.HealthCheckResponse.SERVING)
    health.set_serving_status("", health_pb.HealthCheckResponse.SERVING)

    add_reflection_to_server(ReflectionService(), server)

    try:
        port = server.add_insecure_port(normalize_address(address))
    except RuntimeError as e:  # grpc raises on bind failure
        raise OSError(f"failed to listen on {address}: {e}") from e
    if port == 0:
        raise OSError(f"failed to listen on {address}")

    return server, health, port


_SERVICE_TABLE = {
    SERVICE_NAME: ["ExecuteTool", "ExecuteToolStream"],
    HEALTH_SERVICE_NAME: ["Check", "Watch"],
    REFLECTION_SERVICE_NAME_V1: ["ServerReflectionInfo"],
    REFLECTION_SERVICE_NAME: ["ServerReflectionInfo"],
}


def _log_service_table(logger: Logger) -> None:
    # Parity with the startup service/method table (main.go:97-103).
    logger.info("Registered services:")
    for name, methods in _SERVICE_TABLE.items():
        logger.info("Service registered", name=name, methods=len(methods))
        for method in methods:
            logger.info("Method available", service=name, method=method)


def serve(service: Optional[Service] = None, address: Optional[str] = None) -> None:
    """Process entry point (reference: cmd/polykey/main.go:54-121)."""
    logger = Logger(level=os.environ.get("POLYKEY_LOG_LEVEL", "info"))

    if address is None:
        address = os.environ.get("LISTEN_ADDR") or ":50051"

    obs = Observability()
    health = HealthService()
    if service is None:
        try:
            service = _default_service(logger, health, obs)
        except Exception as e:
            logger.error("failed to initialize backend", error=str(e))
            raise SystemExit(1)

    try:
        server, health, _ = build_server(
            service, logger, address, health=health, obs=obs
        )
    except OSError as e:
        logger.error("failed to listen", error=str(e))
        raise SystemExit(1)

    metrics_server = _start_metrics_server(obs, logger, service=service)

    _log_service_table(logger)

    quit_event = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: quit_event.set())

    server.start()
    logger.info("server starting", address=address)

    quit_event.wait()
    logger.info("server shutting down")
    health.shutdown()
    server.stop(grace=10).wait()
    service.close()
    if metrics_server is not None:
        metrics_server.stop()
    logger.info("server stopped")


def _start_metrics_server(
    obs: Observability, logger: Logger, service=None
) -> Optional[MetricsHTTPServer]:
    """Prometheus exposition sidecar thread. POLYKEY_METRICS_PORT picks
    the port (default 9464, the conventional exporter port); 0 disables.
    A bind failure degrades to no endpoint rather than killing the
    gateway — the gRPC metrics_text view still works.

    When the backend is engine-shaped (TpuService) the flight-deck
    debug surface mounts alongside /metrics — still a 404 unless
    POLYKEY_DEBUG_ENDPOINTS=1 (obs.exposition.DebugSurface). The
    engine provider follows `service.engine` so supervised restarts
    and replica pools stay visible without rewiring."""
    port_raw = os.environ.get("POLYKEY_METRICS_PORT", "9464")
    try:
        port = int(port_raw)
    except ValueError:
        logger.warn("invalid POLYKEY_METRICS_PORT; metrics disabled",
                    value=port_raw)
        return None
    if port <= 0:
        return None
    debug = None
    if service is not None and hasattr(service, "engine"):
        debug = DebugSurface(
            engine_provider=lambda: service.engine,
            obs=obs,
            profiler=getattr(service, "profiler", None),
        )
    try:
        metrics_server = MetricsHTTPServer(
            obs.registry, port=port, debug=debug
        ).start()
    except OSError as e:
        logger.warn("metrics endpoint failed to bind; continuing without",
                    port=port, error=str(e))
        return None
    logger.info("metrics endpoint listening", port=metrics_server.port,
                path="/metrics")
    return metrics_server


def _default_service(
    logger: Logger,
    health: Optional[HealthService] = None,
    obs: Optional[Observability] = None,
) -> Service:
    """Select the backend: TPU engine when requested, mock otherwise.

    The reference hard-wires its mock (main.go:85). Here POLYKEY_BACKEND=tpu
    mounts the serving engine; the default remains the dependency-free mock so
    the gateway runs anywhere.
    """
    backend = os.environ.get("POLYKEY_BACKEND", "mock").lower()
    if backend in ("tpu", "engine"):
        # Honor JAX_PLATFORMS=cpu before any backend init: some images pin a
        # TPU plugin via sitecustomize, so the env alone is ignored and the
        # documented CPU mode (compose.yml, tests) would silently try TPU.
        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            import jax

            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                pass  # backend already initialized

        # Multi-host bootstrap BEFORE the engine initializes the backend:
        # under POLYKEY_COORDINATOR/NUM_PROCESSES/PROCESS_ID (or a TPU
        # pod runtime) every host's chips join one global device list, so
        # the engine's mesh can span hosts. Single-host no-op.
        from ..parallel.distributed import initialize_from_env

        initialize_from_env(logger)

        from .tpu_service import TpuService

        return TpuService.from_env(health=health, logger=logger, obs=obs)
    from .mock_service import MockService

    return MockService()


if __name__ == "__main__":
    serve()
