"""AES-256-GCM secret cipher + encrypted-at-rest secret store.

Behavioral parity with the reference's security adapter
(/root/reference/internal/adapters/security/cipher.go:92-141): key must be
exactly 32 bytes (cipher.go:15-23), encryption uses a random 12-byte GCM
nonce prepended to the sealed ciphertext (cipher.go:25-56), decryption
splits the nonce back off and authenticates (cipher.go:61-83), and the
batch APIs are sequential loops over the unary ones (cipher.go:110-141).

Where the reference leaves the adapter as dead code (nothing imports it —
SURVEY.md §2 "Security cipher"), this framework actually consumes it: the
`secret_id` field the contract plumbs end-to-end (server.go:31) resolves
through a SecretStore whose values live encrypted at rest, and the gateway
mounts the store from POLYKEY_SECRET_KEY / POLYKEY_SECRETS_FILE
(tpu_service.py). Resolution never fails a request — unknown ids behave
exactly as the reference (which ignores secret_id entirely).
"""

from __future__ import annotations

import base64
import json
import os
from typing import Optional

NONCE_SIZE = 12  # GCM standard nonce size, matches Go's gcm.NonceSize()
KEY_SIZE = 32    # AES-256 (cipher.go:15-23 rejects anything else)


class CipherError(ValueError):
    pass


class SecretCipher:
    """AES-256-GCM with nonce-prepended framing."""

    def __init__(self, key: bytes):
        if len(key) != KEY_SIZE:
            raise CipherError(
                f"key must be exactly {KEY_SIZE} bytes, got {len(key)}"
            )
        try:
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        except ImportError as e:
            # Gate, don't crash opaquely: some images omit the optional
            # `cryptography` wheel. The import is lazy (here, not module
            # top) precisely so a deployment that never configures a
            # secret key pays nothing and never sees this; one that DOES
            # gets an actionable error instead of a bare ImportError
            # from deep inside a request path.
            raise CipherError(
                "the 'cryptography' package is not installed; the "
                "AES-256-GCM secret store is unavailable in this "
                "environment (install cryptography>=41 to enable "
                "POLYKEY_SECRET_KEY / POLYKEY_SECRETS_FILE)"
            ) from e

        self._aead = AESGCM(key)

    @classmethod
    def from_hex(cls, hex_key: str) -> "SecretCipher":
        try:
            key = bytes.fromhex(hex_key.strip())
        except ValueError as e:
            raise CipherError(f"key is not valid hex: {e}") from None
        return cls(key)

    def encrypt(self, plaintext: bytes) -> bytes:
        """nonce || ciphertext || tag (the reference's Seal framing)."""
        nonce = os.urandom(NONCE_SIZE)
        return nonce + self._aead.encrypt(nonce, plaintext, None)

    def decrypt(self, blob: bytes) -> bytes:
        if len(blob) < NONCE_SIZE + 16:  # nonce + GCM tag minimum
            raise CipherError("ciphertext too short")
        from cryptography.exceptions import InvalidTag

        nonce, sealed = blob[:NONCE_SIZE], blob[NONCE_SIZE:]
        try:
            return self._aead.decrypt(nonce, sealed, None)
        except InvalidTag:
            raise CipherError("decryption failed: authentication tag mismatch")

    # Sequential loops, matching BatchEncrypt/BatchDecrypt (cipher.go:110-141).
    def encrypt_batch(self, plaintexts: list[bytes]) -> list[bytes]:
        return [self.encrypt(p) for p in plaintexts]

    def decrypt_batch(self, blobs: list[bytes]) -> list[bytes]:
        return [self.decrypt(b) for b in blobs]


class SecretStore:
    """secret_id → plaintext, held encrypted at rest.

    File format: JSON object of {secret_id: base64(nonce||ct||tag)}.
    """

    def __init__(self, cipher: SecretCipher):
        self._cipher = cipher
        self._blobs: dict[str, bytes] = {}

    def put(self, secret_id: str, plaintext: str) -> None:
        self._blobs[secret_id] = self._cipher.encrypt(plaintext.encode())

    def resolve(self, secret_id: str) -> Optional[str]:
        blob = self._blobs.get(secret_id)
        if blob is None:
            return None
        return self._cipher.decrypt(blob).decode()

    def __contains__(self, secret_id: str) -> bool:
        return secret_id in self._blobs

    def __len__(self) -> int:
        return len(self._blobs)

    def save(self, path: str) -> None:
        payload = {
            sid: base64.b64encode(blob).decode()
            for sid, blob in self._blobs.items()
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)

    def load(self, path: str) -> None:
        with open(path) as f:
            payload = json.load(f)
        for sid, b64 in payload.items():
            self._blobs[sid] = base64.b64decode(b64)

    @classmethod
    def from_env(cls, logger=None) -> Optional["SecretStore"]:
        """POLYKEY_SECRET_KEY (64 hex chars) turns the store on;
        POLYKEY_SECRETS_FILE optionally preloads encrypted secrets."""
        hex_key = os.environ.get("POLYKEY_SECRET_KEY")
        if not hex_key:
            return None
        store = cls(SecretCipher.from_hex(hex_key))
        path = os.environ.get("POLYKEY_SECRETS_FILE")
        if path and os.path.exists(path):
            store.load(path)
            if logger is not None:
                logger.info("secret store loaded", path=path,
                            secrets=len(store))
        return store


def _main() -> int:
    """Operator helper: seed an encrypted secrets file.

    usage: python -m polykey_tpu.gateway.security put <file> <id> <value>
           (POLYKEY_SECRET_KEY must hold the 64-hex-char key)
    """
    import sys

    if len(sys.argv) != 5 or sys.argv[1] != "put":
        print(_main.__doc__, file=sys.stderr)
        return 2
    _, _, path, sid, value = sys.argv
    store = SecretStore(SecretCipher.from_hex(os.environ["POLYKEY_SECRET_KEY"]))
    if os.path.exists(path):
        store.load(path)
    store.put(sid, value)
    store.save(path)
    print(f"stored {sid!r} in {path} ({len(store)} secrets)")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
