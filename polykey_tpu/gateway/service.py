"""The service seam — the framework's business-logic contract.

Mirrors the reference's one-method `Service` interface
(/root/reference/internal/service/service.go:12-15):

    ExecuteTool(ctx, toolName, parameters, secretId, metadata) → response

This seam is where backends mount (SURVEY.md §3.2): the reference hard-wires a
mock (cmd/polykey/main.go:85); this framework additionally provides
`polykey_tpu.gateway.tpu_service.TpuService`, which routes LLM tools into the
continuous-batching engine. `execute_tool_stream` is the streaming extension;
the default adapter turns a unary response into a single terminal chunk so
non-streaming backends work over the streaming RPC too.
"""

from __future__ import annotations

import abc
from typing import Iterator, Optional

from ..proto import common_v2_pb2 as cmn
from ..proto import polykey_v2_pb2 as pk
from google.protobuf import struct_pb2


class Service(abc.ABC):
    @abc.abstractmethod
    def execute_tool(
        self,
        tool_name: str,
        parameters: Optional[struct_pb2.Struct],
        secret_id: Optional[str],
        metadata: Optional[cmn.Metadata],
    ) -> pk.ExecuteToolResponse:
        """Execute one tool call and return the full response."""

    def execute_tool_stream(
        self,
        tool_name: str,
        parameters: Optional[struct_pb2.Struct],
        secret_id: Optional[str],
        metadata: Optional[cmn.Metadata],
    ) -> Iterator[pk.ExecuteToolStreamChunk]:
        """Streaming variant; default adapts the unary path."""
        resp = self.execute_tool(tool_name, parameters, secret_id, metadata)
        delta = resp.string_output if resp.WhichOneof("output") == "string_output" else ""
        if delta:
            yield pk.ExecuteToolStreamChunk(delta=delta)
        yield pk.ExecuteToolStreamChunk(final=True, status=resp.status)

    def close(self) -> None:
        """Release backend resources (engine shutdown); default no-op."""
