"""Structured JSON logging in the style of Go's log/slog JSONHandler.

The reference emits one JSON object per line with keys ``time``, ``level``,
``msg`` plus free-form attributes (e.g. /root/reference/cmd/polykey/main.go:55,
cmd/dev_client/main.go:108-111). Both beautifiers key on the exact ``msg``
strings, so this module reproduces the format: level names DEBUG/INFO/WARN/
ERROR, RFC3339 timestamps, attributes flattened into the top-level object.
"""

from __future__ import annotations

import datetime
import io
import json
import sys
import threading

_LEVELS = {"DEBUG": 10, "INFO": 20, "WARN": 30, "ERROR": 40}


def _now_rfc3339() -> str:
    return datetime.datetime.now().astimezone().isoformat()


class Logger:
    """Thread-safe line-per-record JSON logger.

    ``stream`` may be any writable text stream; the dev client points it at an
    in-memory buffer so the run can be re-rendered as a Jest-style report
    afterwards (reference: dev_client/main.go:108-111, 128-129).
    """

    def __init__(self, stream=None, level: str = "INFO"):
        self.stream = stream if stream is not None else sys.stdout
        self.level = _LEVELS.get(level.upper(), 20)
        self._lock = threading.Lock()

    def log(self, level: str, msg: str, **attrs) -> None:
        if _LEVELS.get(level, 20) < self.level:
            return
        record = {"time": _now_rfc3339(), "level": level, "msg": msg}
        for k, v in attrs.items():
            record[k] = _jsonable(v)
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            self.stream.write(line + "\n")
            try:
                self.stream.flush()
            except (ValueError, io.UnsupportedOperation):
                pass

    def debug(self, msg: str, **attrs) -> None:
        self.log("DEBUG", msg, **attrs)

    def info(self, msg: str, **attrs) -> None:
        self.log("INFO", msg, **attrs)

    def warn(self, msg: str, **attrs) -> None:
        self.log("WARN", msg, **attrs)

    def error(self, msg: str, **attrs) -> None:
        self.log("ERROR", msg, **attrs)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


def go_duration(seconds: float) -> str:
    """Render a duration the way Go's time.Duration.String() does (roughly).

    The server's per-RPC log line carries this (main.go:44-46); nothing parses
    it back, so magnitude+unit fidelity is what matters.
    """
    ns = seconds * 1e9
    if ns < 1e3:
        return f"{ns:.0f}ns"
    if ns < 1e6:
        return _trim(ns / 1e3) + "µs"
    if ns < 1e9:
        return _trim(ns / 1e6) + "ms"
    if seconds < 60:
        return _trim(seconds) + "s"
    m, s = divmod(seconds, 60.0)
    if m < 60:
        return f"{int(m)}m" + _trim(s) + "s"
    h, m = divmod(int(m), 60)
    return f"{h}h{m}m" + _trim(s) + "s"


def _trim(x: float) -> str:
    out = f"{x:.3f}".rstrip("0").rstrip(".")
    return out if out else "0"
