"""grpc.health.v1 server implementation.

Reproduces the semantics of grpc-go's bundled health server, which the
reference wires in at /root/reference/cmd/polykey/main.go:82-94 and shuts down
on SIGTERM (main.go:118): per-service serving status, NOT_FOUND on Check for
unknown services, streaming Watch with SERVICE_UNKNOWN for unregistered names,
and Shutdown() forcing every current and future status to NOT_SERVING.

The engine watchdog (polykey_tpu.engine.watchdog) flips statuses here when the
TPU step loop stalls, which is the serving-tier liveness story the reference
delegates to container healthchecks (compose.yml:17-22).
"""

from __future__ import annotations

import threading

import grpc

from ..proto import health_v1_pb2 as health_pb
from ..proto.health_v1_grpc import HealthServicer

SERVING = health_pb.HealthCheckResponse.SERVING
NOT_SERVING = health_pb.HealthCheckResponse.NOT_SERVING
SERVICE_UNKNOWN = health_pb.HealthCheckResponse.SERVICE_UNKNOWN


class HealthService(HealthServicer):
    def __init__(self):
        self._cond = threading.Condition()
        self._statuses: dict[str, int] = {}
        self._shutdown = False

    def set_serving_status(self, service: str, status: int) -> None:
        with self._cond:
            if self._shutdown:
                return
            # polylint: disable=ML002(keyed by registered service name: a handful of static strings, not per-request data)
            self._statuses[service] = status
            self._cond.notify_all()

    def shutdown(self) -> None:
        """Force every present and future status to NOT_SERVING."""
        with self._cond:
            self._shutdown = True
            for service in self._statuses:
                self._statuses[service] = NOT_SERVING
            self._cond.notify_all()

    def resume(self) -> None:
        with self._cond:
            self._shutdown = False

    def resume_serving(self) -> None:
        """Un-latch shutdown and flip every registered status back to
        SERVING — the supervised-restart recovery path (ISSUE 3): the
        engine supervisor calls this once a fresh engine is ready, so
        orchestration resumes routing without a process restart. Watch
        streams see the NOT_SERVING → SERVING transition."""
        with self._cond:
            self._shutdown = False
            for service in self._statuses:
                self._statuses[service] = SERVING
            self._cond.notify_all()

    # -- RPC methods --------------------------------------------------------

    def Check(self, request, context):
        with self._cond:
            if request.service not in self._statuses:
                context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
            return health_pb.HealthCheckResponse(
                status=self._statuses[request.service]
            )

    def Watch(self, request, context):
        last_sent = None
        while context.is_active():
            with self._cond:
                status = self._statuses.get(request.service, SERVICE_UNKNOWN)
                if status == last_sent:
                    # Wake periodically to notice client disconnect.
                    self._cond.wait(timeout=1.0)
                    continue
                last_sent = status
            yield health_pb.HealthCheckResponse(status=status)


def probe(target: str, service: str = "", timeout: float = 5.0) -> int:
    """grpc_health_probe equivalent (the reference ships the Go binary in
    both runtime images, /root/reference/Dockerfile:30-36; container
    healthchecks exec it, compose.yml:17-22). Returns a process exit code:
    0 SERVING, 1 anything else/unreachable."""
    from ..proto.health_v1_grpc import HealthStub

    try:
        with grpc.insecure_channel(target) as channel:
            resp = HealthStub(channel).Check(
                health_pb.HealthCheckRequest(service=service), timeout=timeout
            )
        return 0 if resp.status == SERVING else 1
    except grpc.RpcError:
        return 1


if __name__ == "__main__":
    import sys

    _target = sys.argv[1] if len(sys.argv) > 1 else "localhost:50051"
    _service = sys.argv[2] if len(sys.argv) > 2 else ""
    sys.exit(probe(_target, _service))
