"""Client/engine configuration with the reference's precedence model.

Parity with /root/reference/internal/config/config.go:

- precedence: defaults → CLI flags → env vars (env wins over flags, matching
  Load()'s call order) → runtime-based server-address auto-detection
  (config.go Load());
- flags: -server / -timeout / -log-level / -env (loadFromFlags);
- env: POLYKEY_SERVER_ADDR / POLYKEY_TIMEOUT / POLYKEY_LOG_LEVEL / POLYKEY_ENV
  (loadFromEnv);
- runtime detection order: kubernetes → podman → containerd → docker → local
  (DetectRuntime), probing the serviceaccount dir / KUBERNETES_SERVICE_HOST,
  the ``container`` env var, /.dockerenv, and /proc/1/cgroup;
- detected addresses: kubernetes → polykey-service:50051, any container
  runtime → polykey-server:50051, local → localhost:50051.

Extended beyond the reference with engine settings (model, mesh shape, batch
and KV-page geometry) under the same precedence discipline — see EngineConfig
in polykey_tpu.engine.config, which layers on top of this loader.
"""

from __future__ import annotations

import argparse
import enum
import os
import re
import socket
from dataclasses import dataclass, field
from typing import Optional, Sequence


class RuntimeEnvironment(enum.Enum):
    LOCAL = "local"
    DOCKER = "docker"
    KUBERNETES = "kubernetes"
    CONTAINERD = "containerd"
    PODMAN = "podman"

    def __str__(self) -> str:
        return self.value


_K8S_SERVICEACCOUNT = "/var/run/secrets/kubernetes.io/serviceaccount"
_DOCKERENV = "/.dockerenv"
_CGROUP_FILE = "/proc/1/cgroup"


class RuntimeDetector:
    """Detects where the process is running (config.go DetectRuntime)."""

    def detect_runtime(self) -> RuntimeEnvironment:
        if self._is_kubernetes():
            return RuntimeEnvironment.KUBERNETES
        if self._is_podman():
            return RuntimeEnvironment.PODMAN
        if self._is_containerd():
            return RuntimeEnvironment.CONTAINERD
        if self._is_docker():
            return RuntimeEnvironment.DOCKER
        return RuntimeEnvironment.LOCAL

    def _is_kubernetes(self) -> bool:
        return os.path.exists(_K8S_SERVICEACCOUNT) or bool(
            os.environ.get("KUBERNETES_SERVICE_HOST")
        )

    def _is_podman(self) -> bool:
        return os.environ.get("container") == "podman" or self._cgroup_has("podman")

    def _is_containerd(self) -> bool:
        return self._cgroup_has("containerd")

    def _is_docker(self) -> bool:
        return os.path.exists(_DOCKERENV) or self._cgroup_has("docker")

    @staticmethod
    def _cgroup_has(runtime: str) -> bool:
        try:
            with open(_CGROUP_FILE, encoding="utf-8") as f:
                content = f.read()
        except OSError:
            return False
        return runtime in content


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}


def parse_duration(text: str) -> float:
    """Parse a Go-style duration ('5s', '1m30s', '500ms') into seconds.

    Bare numbers are accepted as seconds for convenience.
    """
    text = text.strip()
    if not text:
        raise ValueError("empty duration")
    try:
        return float(text)
    except ValueError:
        pass
    pos, total = 0, 0.0
    for m in _DURATION_RE.finditer(text):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {text!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(text):
        raise ValueError(f"invalid duration: {text!r}")
    return total


@dataclass
class Config:
    server_address: str = ""
    timeout: float = 5.0       # seconds (default: config.go Load())
    log_level: str = "info"
    environment: str = "development"
    detected_runtime: RuntimeEnvironment = field(default=RuntimeEnvironment.LOCAL)


class ConfigLoader:
    def __init__(self, detector: Optional[RuntimeDetector] = None):
        self.detector = detector or RuntimeDetector()

    def load(self, argv: Optional[Sequence[str]] = None) -> Config:
        config = Config()
        self._load_from_flags(config, argv)
        self._load_from_env(config)
        config.detected_runtime = self.detector.detect_runtime()
        if not config.server_address:
            config.server_address = self._detect_server_address(
                config.detected_runtime
            )
        return config

    def _load_from_flags(self, config: Config, argv) -> None:
        parser = argparse.ArgumentParser(add_help=False)
        parser.add_argument("-server", "--server", default="")
        parser.add_argument("-timeout", "--timeout", default=None)
        parser.add_argument("-log-level", "--log-level", dest="log_level", default=None)
        parser.add_argument("-env", "--env", default=None)
        args, _ = parser.parse_known_args(argv)
        if args.server:
            config.server_address = args.server
        if args.timeout is not None:
            try:
                config.timeout = parse_duration(args.timeout)
            except ValueError as e:
                # Go's flag.DurationVar exits with a usage message on a bad
                # value; a raw traceback here would be the un-parity.
                raise SystemExit(f"invalid value for -timeout: {e}")
        if args.log_level is not None:
            config.log_level = args.log_level
        if args.env is not None:
            config.environment = args.env

    def _load_from_env(self, config: Config) -> None:
        if addr := os.environ.get("POLYKEY_SERVER_ADDR"):
            config.server_address = addr
        if timeout := os.environ.get("POLYKEY_TIMEOUT"):
            try:
                config.timeout = parse_duration(timeout)
            except ValueError:
                pass  # malformed env value keeps the prior setting, as in Go
        if level := os.environ.get("POLYKEY_LOG_LEVEL"):
            config.log_level = level
        if env := os.environ.get("POLYKEY_ENV"):
            config.environment = env

    @staticmethod
    def _detect_server_address(runtime: RuntimeEnvironment) -> str:
        if runtime is RuntimeEnvironment.KUBERNETES:
            return "polykey-service:50051"
        if runtime in (
            RuntimeEnvironment.DOCKER,
            RuntimeEnvironment.CONTAINERD,
            RuntimeEnvironment.PODMAN,
        ):
            return "polykey-server:50051"
        return "localhost:50051"


class NetworkTester:
    """Raw TCP reachability probe before the gRPC dial (config.go
    TestConnection: 3s dial timeout)."""

    def test_connection(self, address: str, timeout: float = 3.0) -> None:
        host, _, port = address.rpartition(":")
        if not host:
            raise ValueError(f"address missing port: {address!r}")
        host = host.strip("[]")  # bracketed IPv6 literals ([::1]:50051)
        try:
            with socket.create_connection((host, int(port)), timeout=timeout):
                pass
        except OSError as e:
            raise ConnectionError(f"failed to connect to {address}: {e}") from e
