"""Per-RPC structured logging + tracing interceptor.

Parity with the reference's unary logging interceptor
(/root/reference/cmd/polykey/main.go:25-52): health checks are not logged,
every other RPC gets a "gRPC call received" line on entry and a
"gRPC call finished" line with Go-style duration and status-code name on exit
(ERROR level when the RPC failed). Extended to server-streaming methods, which
the reference does not have.

Beyond the reference (ISSUE 1): every logged RPC carries a ``trace_id`` —
honored from the client's ``x-trace-id`` request metadata when present,
minted otherwise — which is echoed back in trailing metadata so clients can
quote it in bug reports and correlate their logs with ours. When the
interceptor is built with an `Observability` bundle it also opens the
request's ROOT span, publishes it thread-locally for the service layer to
attach engine child spans to, and files the finished tree in the flight
recorder; per-method, per-code RPC counters feed the /metrics endpoint.
"""

from __future__ import annotations

import re
import time

import grpc

from ..obs import Counter, new_trace_id, set_current_span
from .jsonlog import Logger, go_duration

_TRACE_ID_KEY = "x-trace-id"

_SKIP_METHODS = frozenset({"/grpc.health.v1.Health/Check"})

# gRPC status-code names as Go's codes.Code.String() renders them — the
# log-beautifier treats anything but "OK" as a failure
# (/root/reference/cmd/utils/log-beautifier/main.go:70-73).
_GO_CODE_NAMES = {
    grpc.StatusCode.OK: "OK",
    grpc.StatusCode.CANCELLED: "Canceled",
    grpc.StatusCode.UNKNOWN: "Unknown",
    grpc.StatusCode.INVALID_ARGUMENT: "InvalidArgument",
    grpc.StatusCode.DEADLINE_EXCEEDED: "DeadlineExceeded",
    grpc.StatusCode.NOT_FOUND: "NotFound",
    grpc.StatusCode.ALREADY_EXISTS: "AlreadyExists",
    grpc.StatusCode.PERMISSION_DENIED: "PermissionDenied",
    grpc.StatusCode.RESOURCE_EXHAUSTED: "ResourceExhausted",
    grpc.StatusCode.FAILED_PRECONDITION: "FailedPrecondition",
    grpc.StatusCode.ABORTED: "Aborted",
    grpc.StatusCode.OUT_OF_RANGE: "OutOfRange",
    grpc.StatusCode.UNIMPLEMENTED: "Unimplemented",
    grpc.StatusCode.INTERNAL: "Internal",
    grpc.StatusCode.UNAVAILABLE: "Unavailable",
    grpc.StatusCode.DATA_LOSS: "DataLoss",
    grpc.StatusCode.UNAUTHENTICATED: "Unauthenticated",
}


class _RecordingContext:
    """ServicerContext proxy that remembers the status code the handler
    set, and MERGES trailing metadata across callers: grpc's
    set_trailing_metadata replaces wholesale, so the handler layer
    attaching a retry-after-ms hint must not clobber the interceptor's
    x-trace-id echo (or vice versa). Last value per key wins."""

    def __init__(self, context):
        self._ctx = context
        self.recorded_code = None
        self._trailing: dict[str, str] = {}

    def set_trailing_metadata(self, metadata):
        for key, value in metadata:
            self._trailing[key] = value
        return self._ctx.set_trailing_metadata(tuple(self._trailing.items()))

    def set_code(self, code):
        self.recorded_code = code
        return self._ctx.set_code(code)

    def abort(self, code, details):
        self.recorded_code = code
        return self._ctx.abort(code, details)

    def abort_with_status(self, status):
        self.recorded_code = status.code
        return self._ctx.abort_with_status(status)

    def __getattr__(self, name):
        return getattr(self._ctx, name)


def _code_name(rec: _RecordingContext, error: BaseException | None) -> str:
    if rec.recorded_code is not None:
        return _GO_CODE_NAMES.get(rec.recorded_code, str(rec.recorded_code))
    if error is not None:
        return "Unknown"
    return "OK"


_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def _incoming_trace_id(context) -> str | None:
    """Client-supplied trace id, validated: it fans out to trailers,
    every log line, and every span of the recorded tree, so a hostile or
    buggy client must not get to inject multi-KB blobs or log-breaking
    characters — anything outside 1-64 [A-Za-z0-9_-] chars is ignored
    and a fresh id minted instead."""
    try:
        metadata = context.invocation_metadata() or ()
    except Exception:
        # In-process stubs and test doubles may not implement
        # invocation_metadata; a fresh trace id is minted downstream.
        return None
    for key, value in metadata:
        if key == _TRACE_ID_KEY and isinstance(value, str) \
                and _TRACE_ID_RE.match(value):
            return value
    return None


class LoggingInterceptor(grpc.ServerInterceptor):
    def __init__(self, logger: Logger, obs=None):
        self._logger = logger
        self._obs = obs
        self._rpc_counter: Counter | None = None
        if obs is not None:
            # Shared registries (one obs across several servers in-process,
            # as tests do) reuse the existing family instead of colliding.
            self._rpc_counter, _ = obs.registry.get_or_create(
                Counter,
                "polykey_rpcs_total",
                "RPCs handled, by method and status code.",
                ("method", "code"),
            )

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        method = handler_call_details.method
        if handler is None or method in _SKIP_METHODS:
            return handler

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                self._wrap_unary(handler.unary_unary, method),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream is not None:
            return grpc.unary_stream_rpc_method_handler(
                self._wrap_stream(handler.unary_stream, method),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler

    def _begin(self, method: str, context):
        """Common RPC entry: resolve the trace id (client-supplied or
        minted), echo it in trailing metadata, open + publish the root
        span, log the received line. Returns (trace_id, span)."""
        trace_id = _incoming_trace_id(context) or new_trace_id()
        try:
            context.set_trailing_metadata(((_TRACE_ID_KEY, trace_id),))
        except Exception:
            pass  # context may not support trailers (in-process stubs)
        span = None
        if self._obs is not None:
            span = self._obs.tracer.start(method, trace_id=trace_id)
            set_current_span(span)
        self._logger.info(
            "gRPC call received", method=method, trace_id=trace_id
        )
        return trace_id, span

    def _finish(self, method: str, start: float, code: str,
                trace_id: str, span) -> None:
        level = "INFO" if code == "OK" else "ERROR"
        self._logger.log(
            level,
            "gRPC call finished",
            method=method,
            duration=go_duration(time.monotonic() - start),
            code=code,
            trace_id=trace_id,
        )
        if self._rpc_counter is not None:
            self._rpc_counter.inc(method=method, code=code)
        if span is not None:
            span.set(code=code)
            span.finish()
            # Record only traces that carry structure (engine child
            # spans) or a failure: a dashboard polling engine_stats every
            # few seconds would otherwise fill the recorder's ring and
            # evict the llm_generate trees a postmortem needs — the
            # moment the tool is used would be the moment it destroys
            # its own data. Childless OK RPCs still get counters, log
            # lines, and the trailing trace-id echo.
            if span.children or code != "OK":
                self._obs.tracer.finish_and_record(span)
            set_current_span(None)

    def _wrap_unary(self, behavior, method):
        def wrapped(request, context):
            start = time.monotonic()
            # The recording proxy wraps BEFORE _begin so the trace-id
            # trailer lands in its merge map; handler-set trailers
            # (retry-after-ms) then add to it instead of replacing it.
            rec = _RecordingContext(context)
            trace_id, span = self._begin(method, rec)
            try:
                response = behavior(request, rec)
            except BaseException as e:
                self._finish(method, start, _code_name(rec, e), trace_id, span)
                raise
            self._finish(method, start, _code_name(rec, None), trace_id, span)
            return response

        return wrapped

    def _wrap_stream(self, behavior, method):
        def wrapped(request, context):
            start = time.monotonic()
            rec = _RecordingContext(context)
            trace_id, span = self._begin(method, rec)
            try:
                yield from behavior(request, rec)
            except BaseException as e:
                self._finish(method, start, _code_name(rec, e), trace_id, span)
                raise
            self._finish(method, start, _code_name(rec, None), trace_id, span)

        return wrapped
