"""Per-RPC structured logging interceptor.

Parity with the reference's unary logging interceptor
(/root/reference/cmd/polykey/main.go:25-52): health checks are not logged,
every other RPC gets a "gRPC call received" line on entry and a
"gRPC call finished" line with Go-style duration and status-code name on exit
(ERROR level when the RPC failed). Extended to server-streaming methods, which
the reference does not have.
"""

from __future__ import annotations

import time

import grpc

from .jsonlog import Logger, go_duration

_SKIP_METHODS = frozenset({"/grpc.health.v1.Health/Check"})

# gRPC status-code names as Go's codes.Code.String() renders them — the
# log-beautifier treats anything but "OK" as a failure
# (/root/reference/cmd/utils/log-beautifier/main.go:70-73).
_GO_CODE_NAMES = {
    grpc.StatusCode.OK: "OK",
    grpc.StatusCode.CANCELLED: "Canceled",
    grpc.StatusCode.UNKNOWN: "Unknown",
    grpc.StatusCode.INVALID_ARGUMENT: "InvalidArgument",
    grpc.StatusCode.DEADLINE_EXCEEDED: "DeadlineExceeded",
    grpc.StatusCode.NOT_FOUND: "NotFound",
    grpc.StatusCode.ALREADY_EXISTS: "AlreadyExists",
    grpc.StatusCode.PERMISSION_DENIED: "PermissionDenied",
    grpc.StatusCode.RESOURCE_EXHAUSTED: "ResourceExhausted",
    grpc.StatusCode.FAILED_PRECONDITION: "FailedPrecondition",
    grpc.StatusCode.ABORTED: "Aborted",
    grpc.StatusCode.OUT_OF_RANGE: "OutOfRange",
    grpc.StatusCode.UNIMPLEMENTED: "Unimplemented",
    grpc.StatusCode.INTERNAL: "Internal",
    grpc.StatusCode.UNAVAILABLE: "Unavailable",
    grpc.StatusCode.DATA_LOSS: "DataLoss",
    grpc.StatusCode.UNAUTHENTICATED: "Unauthenticated",
}


class _RecordingContext:
    """ServicerContext proxy that remembers the status code the handler set."""

    def __init__(self, context):
        self._ctx = context
        self.recorded_code = None

    def set_code(self, code):
        self.recorded_code = code
        return self._ctx.set_code(code)

    def abort(self, code, details):
        self.recorded_code = code
        return self._ctx.abort(code, details)

    def abort_with_status(self, status):
        self.recorded_code = status.code
        return self._ctx.abort_with_status(status)

    def __getattr__(self, name):
        return getattr(self._ctx, name)


def _code_name(rec: _RecordingContext, error: BaseException | None) -> str:
    if rec.recorded_code is not None:
        return _GO_CODE_NAMES.get(rec.recorded_code, str(rec.recorded_code))
    if error is not None:
        return "Unknown"
    return "OK"


class LoggingInterceptor(grpc.ServerInterceptor):
    def __init__(self, logger: Logger):
        self._logger = logger

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        method = handler_call_details.method
        if handler is None or method in _SKIP_METHODS:
            return handler

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                self._wrap_unary(handler.unary_unary, method),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream is not None:
            return grpc.unary_stream_rpc_method_handler(
                self._wrap_stream(handler.unary_stream, method),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler

    def _finish(self, method: str, start: float, code: str) -> None:
        level = "INFO" if code == "OK" else "ERROR"
        self._logger.log(
            level,
            "gRPC call finished",
            method=method,
            duration=go_duration(time.monotonic() - start),
            code=code,
        )

    def _wrap_unary(self, behavior, method):
        def wrapped(request, context):
            start = time.monotonic()
            self._logger.info("gRPC call received", method=method)
            rec = _RecordingContext(context)
            try:
                response = behavior(request, rec)
            except BaseException as e:
                self._finish(method, start, _code_name(rec, e))
                raise
            self._finish(method, start, _code_name(rec, None))
            return response

        return wrapped

    def _wrap_stream(self, behavior, method):
        def wrapped(request, context):
            start = time.monotonic()
            self._logger.info("gRPC call received", method=method)
            rec = _RecordingContext(context)
            try:
                yield from behavior(request, rec)
            except BaseException as e:
                self._finish(method, start, _code_name(rec, e))
                raise
            self._finish(method, start, _code_name(rec, None))

        return wrapped
