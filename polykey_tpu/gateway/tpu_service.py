"""TPU backend for the Service seam: tool calls → inference engine.

What the north star describes as the `tpu` provider: the gRPC contract stays
exactly the reference's (tool_name + Struct parameters in, oneof output out —
internal/service/service.go:13-15), but `llm_generate` runs on the co-located
serving engine instead of proxying to an external API. Zero external calls.

Tools:
- ``llm_generate`` (alias ``generate``) — params: prompt (string, required),
  max_tokens, temperature, top_p, top_k, seed, stop (string or list of strings:
  generation cuts BEFORE the earliest match, which is never emitted; the
  engine request is cancelled so no further compute is spent). Unary
  returns the full completion as string_output; the streaming RPC emits
  incremental UTF-8-safe deltas and a terminal chunk with Usage (TTFT,
  tok/s).
- ``engine_stats`` — struct_output snapshot of engine metrics and pool state,
  including TTFT/ITL percentiles and the most recent traced request's span
  tree. ``view: "metrics_text"`` returns the Prometheus text page as
  string_output (same bytes as the HTTP /metrics endpoint — scrapeable over
  gRPC when no sidecar port is exposed); ``view: "trace"`` returns the
  recent span trees + flight-recorder events for postmortems.
- the reference's mock tools (example_tool / struct_tool / file_tool) keep
  their exact semantics via delegation to MockService, so a client of the
  reference sees no behavior change for non-LLM tools (including the
  unknown-tool-is-success contract, mock.go:60-63).
"""

from __future__ import annotations

import math
import os
import queue
import time
from typing import Iterator, Optional

from ..engine.config import EngineConfig, enable_persistent_compile_cache
from ..engine.engine import (
    DEADLINE_MSG,
    EngineDeadError,
    EngineOverloadedError,
    GenRequest,
    InferenceEngine,
)
from ..engine.replica_pool import ReplicaPool
from ..engine.supervisor import EngineSupervisor
from ..engine.tokenizer import ByteTokenizer, IncrementalDetokenizer
from ..engine.watchdog import Watchdog
from ..obs import Observability, current_span, engine_collector
from ..obs.profiler import ProfilerCapture
from ..proto import common_v2_pb2 as cmn
from ..proto import polykey_v2_pb2 as pk
from . import errors
from .mock_service import MockService
from .service import Service
from google.protobuf import struct_pb2

_LLM_TOOLS = frozenset({"llm_generate", "generate"})


class TpuService(Service):
    def __init__(
        self,
        engine: InferenceEngine,
        watchdog: Optional[Watchdog] = None,
        secrets=None,
        logger=None,
        obs: Optional[Observability] = None,
    ):
        self.engine = engine
        self.watchdog = watchdog
        # Set by create() when supervision is on; the supervisor swaps
        # `self.engine` to the fresh instance after every restart.
        self.supervisor: Optional[EngineSupervisor] = None
        # Set by from_env() when POLYKEY_AUTOPILOT=1: the closed-loop
        # controller thread (engine/autopilot.py); close() stops it
        # before the engine so no actuation races the teardown.
        self.autopilot = None
        self.secrets = secrets      # gateway.security.SecretStore or None
        self.logger = logger
        self.obs = obs
        self.stall_counter = None
        self.restart_counter = None
        self._mock = MockService()
        # Single-flight profiler shared by the engine_profile tool AND
        # the /debug/profile HTTP trigger (obs/profiler.py): whichever
        # surface starts a capture, the other sees "busy" — jax's
        # profiler is process-global and two overlapping captures
        # corrupt each other's artifacts.
        self.profiler = ProfilerCapture(
            recorder=obs.recorder if obs is not None else None
        )
        if obs is not None:
            # SLO breach events reach the flight recorder (ISSUE 11):
            # every replica's signal plane gets the shared recorder so
            # breaches sit next to watchdog trips in /debug/flight.
            from ..obs.signals import bind_recorder

            bind_recorder(engine, obs.recorder)
            # Bind the engine into the scrape registry. A registry holds
            # ONE engine's families (the names carry no engine label):
            # first service to register wins, later services sharing the
            # Observability (in-process tests) reuse its families. The
            # stall counter is get-or-created independently so watchdog
            # accounting never depends on who registered the gauge.
            from ..obs import Counter, Gauge

            # Scrape through `self.engine`, not the constructor arg: a
            # supervised restart swaps the attribute, and the collector
            # must follow to the live engine.
            up_gauge, created = obs.registry.get_or_create(
                Gauge,
                "polykey_engine_up",
                "1 while the engine thread is alive.",
                fn=lambda: 0.0 if self.engine.dead else 1.0,
            )
            if created:
                obs.registry.register_collector(
                    engine_collector(lambda: self.engine)
                )
            self.stall_counter, _ = obs.registry.get_or_create(
                Counter,
                "polykey_watchdog_stalls_total",
                "Watchdog trips on a wedged engine step loop.",
            )
            self.restart_counter, _ = obs.registry.get_or_create(
                Counter,
                "polykey_engine_restarts_total",
                "Supervised in-process engine restarts.",
            )

    @classmethod
    def create(
        cls, engine: InferenceEngine, health=None, logger=None,
        secrets=None, obs: Optional[Observability] = None,
        engine_factory=None,
    ) -> "TpuService":
        """Build a service with its watchdog — and, when
        `engine.config.supervise` (the default), its supervisor — fully
        wired. Everything is built after the service so the
        observability hooks (flight-recorder events, stall + restart
        counters) come from the shared bundle — the ONE place this
        wiring lives (from_env and the metrics-smoke probe both call it,
        so they can't drift apart). `engine_factory` overrides how a
        replacement engine is built on supervised restart (default:
        reconstruct from the same config).

        A `ReplicaPool` passes through as-is: the pool already owns a
        watchdog and supervisor PER REPLICA (plus the aggregate-health
        wiring), so the single-engine supervision built here would be
        redundant and wrong (one watchdog cannot watch N engines). A
        `DisaggPool` (ISSUE 13) passes through for the same reason —
        its supervision lives inside each worker process, its liveness
        in the coordinator's heartbeat."""
        from ..engine.disagg_pool import DisaggPool

        service = cls(engine, None, secrets=secrets, logger=logger, obs=obs)
        if isinstance(engine, (ReplicaPool, DisaggPool)):
            return service
        recorder = obs.recorder if obs is not None else None
        watchdog = Watchdog(
            engine, health=health, logger=logger,
            recorder=recorder,
            stall_counter=service.stall_counter,
        )
        watchdog.start()
        service.watchdog = watchdog
        if engine.config.supervise:
            config = engine.config
            # The default factory replays the original constructor inputs
            # (raw params/seed/draft_params captured at engine init): a
            # restart must rebuild the SAME model — silently swapping in
            # a fresh random init would serve garbage with 200s.
            ctor = engine._ctor_args
            factory = engine_factory or (
                lambda: InferenceEngine(
                    config, params=ctor["params"], health=health,
                    logger=logger, seed=ctor["seed"],
                    draft_params=ctor["draft_params"],
                )
            )
            supervisor = EngineSupervisor(
                engine, factory,
                watchdog=watchdog, health=health, logger=logger,
                recorder=recorder,
                restart_counter=service.restart_counter,
                max_restarts=config.max_engine_restarts,
                restart_window_s=config.restart_window_s,
            )
            supervisor.add_restart_listener(
                lambda fresh: setattr(service, "engine", fresh)
            )
            supervisor.start()
            service.supervisor = supervisor
        return service

    @classmethod
    def from_env(
        cls, health=None, logger=None,
        obs: Optional[Observability] = None,
    ) -> "TpuService":
        from .security import SecretStore

        config = EngineConfig.from_env()
        # Durable XLA compile cache at the SERVER entrypoint (not in the
        # engine constructor: embedders and tests shouldn't have global
        # jax config mutated under them). Restarts skip the 20-40 s/step
        # TPU recompiles; POLYKEY_COMPILE_CACHE=0 opts out.
        enable_persistent_compile_cache()
        if config.disagg:
            # Disaggregated tiers (ISSUE 13): POLYKEY_DISAGG="PxD"
            # spawns prefill/decode worker PROCESSES behind the
            # coordinator. Unset (default) never takes this branch — no
            # processes, no pool, single-process paths byte-identical.
            from ..engine.disagg_pool import DisaggPool

            engine = DisaggPool.create(
                config, health=health, logger=logger, obs=obs,
                state_dir=os.environ.get("POLYKEY_DISAGG_STATE_DIR")
                or None,
            )
        elif config.replicas > 1:
            # Replica tier (ISSUE 9): POLYKEY_REPLICAS engines behind
            # the routing pool. POLYKEY_REPLICAS=1 (default) never takes
            # this branch — the single-engine wiring below is unchanged.
            engine = ReplicaPool.create(
                config, health=health, logger=logger, obs=obs,
            )
        else:
            engine = InferenceEngine(config, health=health, logger=logger)
        service = cls.create(
            engine, health=health, logger=logger,
            secrets=SecretStore.from_env(logger), obs=obs,
        )
        # Close the control loop (ISSUE 18): POLYKEY_AUTOPILOT=1 arms
        # the supervised controller thread over whatever target this
        # process serves (bare engine, replica pool, or disagg
        # coordinator). Default off — unset, nothing constructs and
        # every existing path is byte-identical. A start-time refusal
        # (signal plane disabled) propagates: that misconfiguration
        # must fail the boot, not silently serve an inert controller.
        from ..engine.autopilot import maybe_start

        service.autopilot = maybe_start(
            service.engine, supervisor=service.supervisor,
            obs=obs, logger=logger,
        )
        if logger is not None:
            logger.info(
                "engine initialized",
                model=config.model,
                replicas=config.replicas,
                slots=config.max_decode_slots,
                pages=config.num_pages,
                page_size=config.page_size,
            )
        return service

    def _resolve_secret(self, secret_id) -> None:
        """Resolve `secret_id` through the encrypted store (the consumption
        the reference's dead cipher adapter was scaffolding for). Unknown
        ids are NOT errors — the reference ignores secret_id entirely, so
        resolution only adds observability, never failure."""
        if not secret_id or self.secrets is None:
            return
        resolved = self.secrets.resolve(secret_id) is not None
        if self.logger is not None:
            self.logger.info(
                "secret resolved" if resolved else "secret unknown",
                secret_id=secret_id,
            )

    def close(self) -> None:
        if self.autopilot is not None:
            self.autopilot.stop()
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.engine.shutdown()

    # -- request plumbing ---------------------------------------------------

    def _build_request(self, parameters: Optional[struct_pb2.Struct]) -> GenRequest:
        params = dict(parameters) if parameters is not None else {}
        prompt = params.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            raise ValueError("llm_generate requires a non-empty string 'prompt'")
        cfg = self.engine.config
        return GenRequest(
            prompt=prompt,
            # The RPC's remaining budget, published thread-locally by the
            # handler (gateway.errors): the engine drops the request the
            # moment it can no longer finish in time.
            deadline=errors.rpc_deadline(),
            max_new_tokens=int(params.get("max_tokens", cfg.default_max_new_tokens)),
            # Clamp client-supplied knobs into sane ranges rather than letting
            # degenerate values (negative temp, top_p=0) reach the sampler.
            temperature=max(0.0, float(params.get("temperature", 0.0))),
            top_p=min(1.0, max(0.0, float(params.get("top_p", 1.0)))),
            # top_k <= 0 disables; fractional values are client bugs.
            top_k=self._parse_top_k(params),
            # Reproducible sampling: same (prompt, seed, sampling) → same
            # stream regardless of batch composition (engine.GenRequest).
            seed=self._parse_seed(params),
        )

    @staticmethod
    def _parse_top_k(params: dict) -> int:
        kv = params.get("top_k", 0)
        if isinstance(kv, float) and (not math.isfinite(kv) or kv != int(kv)):
            raise ValueError("'top_k' must be a non-negative integer")
        k = int(kv)
        if k < 0:
            raise ValueError("'top_k' must be a non-negative integer")
        return k

    @staticmethod
    def _parse_seed(params: dict):
        if "seed" not in params:
            return None
        sv = params["seed"]
        # Struct numbers are IEEE doubles: beyond 2^53 distinct integers
        # collapse to the same float, silently breaking the documented
        # distinct-seeds-never-collide contract — reject instead.
        if isinstance(sv, float) and (
            not math.isfinite(sv) or sv != int(sv) or abs(sv) > 2 ** 53
        ):
            raise ValueError(
                "'seed' must be an integer with |seed| <= 2**53 (JSON "
                "numbers are doubles; larger seeds would silently collide)"
            )
        return int(sv)

    def _submit(self, request: GenRequest) -> None:
        """Submit with the overload contract mapped to typed RPC errors:
        sheds → RESOURCE_EXHAUSTED (+ retry-after-ms trailer), dead /
        restarting engine → UNAVAILABLE (retryable — the supervisor is
        probably already bringing a fresh engine up)."""
        try:
            self.engine.submit(request)
        except EngineOverloadedError as e:
            raise errors.ResourceExhaustedError(
                str(e), retry_after_ms=e.retry_after_ms
            ) from e
        except EngineDeadError as e:
            # The no-healthy-replica path (replica/disagg pools) carries
            # an estimated-recovery hint: without the trailer, every
            # shed-free client hammers a recovering tier at its own
            # backoff schedule instead of the server's (ISSUE 13 fix).
            trailers: tuple = ()
            retry_after = getattr(e, "retry_after_ms", None)
            if retry_after is not None:
                trailers = (
                    (errors.RETRY_AFTER_MS_KEY, str(int(retry_after))),
                )
            raise errors.UnavailableError(str(e), trailers=trailers) from e

    @staticmethod
    def _engine_error(message: str, delivered: Optional[int] = None) -> Exception:
        """Map an engine failure event to the RPC status contract:
        deadline expiries → DEADLINE_EXCEEDED (never retryable); engine
        lifecycle failures (dead / shut down / restarting — all begin
        "engine") → UNAVAILABLE (retryable); anything else keeps the
        reference's Unknown mapping.

        `delivered` (streaming only) is the count of tokens the client
        has already received: UNAVAILABLE then carries the mid-stream
        resume contract in trailing metadata — `resume-supported` plus
        `resume-tokens` — so a resuming client re-issues the request
        with `received_tokens` and gets only the missing suffix."""
        if message.startswith(DEADLINE_MSG):
            return errors.DeadlineExceededError(message)
        if message.startswith("engine"):
            trailers: tuple = ()
            if delivered is not None:
                trailers = (
                    (errors.RESUME_SUPPORTED_KEY, "1"),
                    (errors.RESUME_TOKENS_KEY, str(int(delivered))),
                )
            return errors.UnavailableError(message, trailers=trailers)
        return RuntimeError(message)

    @staticmethod
    def _parse_received(params: dict) -> int:
        """`received_tokens`: how many tokens this client already holds
        from an interrupted stream (the resume-tokens trailer value).
        The server replays the generation and suppresses that many
        leading tokens — exact for greedy and for seeded sampling on a
        plain engine (position-keyed draws)."""
        rv = params.get("received_tokens", 0)
        if isinstance(rv, float) and (not math.isfinite(rv) or rv != int(rv)):
            raise ValueError("'received_tokens' must be a non-negative integer")
        received = int(rv)
        if received < 0:
            raise ValueError("'received_tokens' must be a non-negative integer")
        return received

    def _stamp_serving_trailers(self, request: GenRequest) -> None:
        """Success-path trailers: the request's attributed device time
        (`device-ms`, any engine) plus the replica-tier pair — which
        replica served, and whether the stream was resumed on another
        replica (`restarted` — the signal that a SAMPLED stream's
        suffix may not extend the delivered prefix bit-exactly on a
        spec engine; replica keys are absent on a bare engine)."""
        trailers = []
        device_ms = request.timings.device_ms
        if device_ms > 0:
            trailers.append((errors.DEVICE_MS_KEY, f"{device_ms:.2f}"))
        replica = getattr(request, "replica", None)
        if replica is not None:
            trailers.append((errors.REPLICA_KEY, str(replica)))
            if getattr(request, "restarted", False):
                trailers.append((errors.RESTARTED_KEY, "1"))
        tier = getattr(request, "tier", None)
        if tier is not None:
            # Disagg tier breadcrumb (ISSUE 13): which prefill/decode
            # worker pair served this request.
            trailers.append((errors.TIER_KEY, str(tier)))
        if trailers:
            errors.add_rpc_trailers(*trailers)

    def _drain(self, request: GenRequest, timeout: float):
        """Yield engine events until done/error; raises on timeout."""
        while True:
            try:
                kind, value = request.out.get(timeout=timeout)
            except queue.Empty:
                request.cancelled.set()
                raise errors.DeadlineExceededError(
                    "generation timed out"
                ) from None
            yield kind, value
            if kind in ("done", "error"):
                return

    @staticmethod
    def _parse_stops(params: dict) -> list[str]:
        stop = params.get("stop")
        if stop is None:
            return []
        if isinstance(stop, str):
            return [stop] if stop else []
        import collections.abc

        if isinstance(stop, (dict, collections.abc.Mapping, struct_pb2.Struct)):
            # A mapping would silently iterate its KEYS as stop strings.
            raise ValueError("'stop' must be a string or a list of strings")
        try:
            stops = [s for s in stop]
        except TypeError:
            raise ValueError(
                "'stop' must be a string or a list of strings"
            ) from None
        if not all(isinstance(s, str) and s for s in stops):
            raise ValueError("'stop' entries must be non-empty strings")
        return stops

    def _text_events(self, request: GenRequest, stops: list[str],
                     skip: int = 0):
        """Decode engine tokens into text deltas, applying stop sequences:
        yields ("delta", str) then ("done", timings | None).

        Stop handling holds back up to max(len(stop))-1 trailing chars so
        a stop string arriving split across deltas is still caught and
        never emitted; on a match the engine request is cancelled (no
        further device work) and the stream ends cleanly at the text
        BEFORE the earliest match. The engine's own "cancelled" error is
        the expected outcome of that cancellation, not a failure.

        `skip` (client resume, `received_tokens`): the first `skip`
        tokens still pass through the detokenizer — incremental decode
        is context-dependent — but their text is discarded, so the
        stream carries only the suffix the client is missing. An engine-
        lifecycle failure raises UNAVAILABLE carrying the resume
        trailers with the total delivered count (skip + this stream's).
        """
        tokenizer = self.engine.tokenizer
        incremental = isinstance(tokenizer, ByteTokenizer)
        utf8_tail = b""
        detok = None if incremental else IncrementalDetokenizer(tokenizer)
        hold = max((len(s) for s in stops), default=1) - 1
        buf = ""
        stopped = False
        skipped = 0
        delivered = 0
        timings = None
        detok_s = 0.0     # cumulative detokenize wall time (trace span)
        for kind, value in self._drain(
            request, self.engine.config.request_timeout_s
        ):
            if kind == "token":
                t0 = time.monotonic()
                if incremental:
                    delta, utf8_tail = tokenizer.decode_incremental(
                        [value], utf8_tail
                    )
                else:
                    # Context-dependent detokenization (BPE/sentencepiece):
                    # bounded-window incremental decode, O(n) total.
                    delta = detok.push(value)
                detok_s += time.monotonic() - t0
                if skipped < skip:
                    skipped += 1
                    continue
                delivered += 1
                if not delta:
                    continue
                if not stops:
                    yield "delta", delta
                    continue
                buf += delta
                cut = min(
                    (i for i in (buf.find(s) for s in stops) if i >= 0),
                    default=-1,
                )
                if cut >= 0:
                    if buf[:cut]:
                        yield "delta", buf[:cut]
                    buf = ""
                    stopped = True
                    request.cancelled.set()
                    break
                if hold and len(buf) > hold:
                    yield "delta", buf[:-hold]
                    buf = buf[-hold:]
                elif not hold:
                    yield "delta", buf
                    buf = ""
            elif kind == "error":
                if buf:
                    # Flush the stop-scanner's held-back tail first: the
                    # resume-tokens trailer counts CONSUMED tokens, so
                    # text still held here would be advertised as
                    # delivered and silently lost across a client
                    # resume. The stream is ending either way; a stop
                    # that would only complete across the resume
                    # boundary is the one remaining (documented) gap.
                    yield "delta", buf
                    buf = ""
                raise self._engine_error(value, delivered=skip + delivered)
            else:
                timings = value
        if stopped:
            # Drain the terminal event the cancellation produces so the
            # engine's queue is not abandoned mid-handshake; the output is
            # already complete, so even a drain timeout must not destroy
            # it. Timings live on the request object (engine._finish fills
            # them for cancelled requests too), so Usage survives the
            # cancellation path.
            try:
                for kind, value in self._drain(
                    request, self.engine.config.request_timeout_s
                ):
                    if kind in ("done", "error"):
                        break
            except errors.DeadlineExceededError:
                pass
            timings = request.timings
        else:
            # End of stream: release held-back text (the incremental
            # detokenizer's window and/or the stop scanner's tail), still
            # honoring a stop that only completes in the final text.
            t0 = time.monotonic()
            tail = detok.flush() if detok is not None else ""
            detok_s += time.monotonic() - t0
            buf += tail
            if buf:
                cut = min(
                    (i for i in (buf.find(s) for s in stops) if i >= 0),
                    default=-1,
                )
                if cut >= 0:
                    buf = buf[:cut]
                if buf:
                    yield "delta", buf
        if request.trace is not None and detok_s > 0:
            # Detokenize work interleaves with decode; record it as one
            # span of its cumulative duration anchored at stream end (the
            # attr marks it as an accumulation, not a contiguous window).
            end = time.monotonic()
            request.trace.child(
                "detokenize", start=end - detok_s, end=end, cumulative=True
            )
        yield "done", timings

    # -- Service interface --------------------------------------------------

    def _engine_profile(self, parameters) -> pk.ExecuteToolResponse:
        """jax.profiler trace capture (SURVEY §5 tracing obligation).

        params: action = start | stop | status; log_dir (start only).
        Captured traces carry the polykey/prefill, polykey/decode and
        polykey/spec_decode annotations around the engine's device steps
        (engine.py) and open in TensorBoard / xprof. Delegates to the
        shared single-flight ProfilerCapture, so a capture started here
        blocks /debug/profile (and vice versa) — ProfilerBusyError is a
        ValueError, preserving the tool's original double-start contract.
        """
        params = dict(parameters) if parameters is not None else {}
        action = params.get("action", "status")
        if action == "start":
            log_dir = params.get("log_dir")
            self.profiler.start(str(log_dir) if log_dir else None)
        elif action == "stop":
            self.profiler.stop()
            if self.logger is not None:
                self.logger.info("profiler trace captured")
        elif action != "status":
            raise ValueError(
                f"unknown profiler action {action!r}; use start/stop/status"
            )
        response = pk.ExecuteToolResponse(
            status=cmn.Status(code=200, message="Tool executed successfully")
        )
        status = self.profiler.status()
        response.struct_output.update({
            "profiling": status["profiling"],
            "log_dir": status["log_dir"],
        })
        return response

    def _engine_stats(self, parameters) -> pk.ExecuteToolResponse:
        """engine_stats views: default counters+percentiles (+ the most
        recent traced request's span tree), `metrics_text` (Prometheus
        page over gRPC), `trace` (flight-recorder dump)."""
        params = dict(parameters) if parameters is not None else {}
        view = params.get("view", "stats")
        response = pk.ExecuteToolResponse(
            status=cmn.Status(code=200, message="Tool executed successfully")
        )
        if view in ("metrics_text", "prometheus"):
            if self.obs is None:
                raise ValueError(
                    "metrics_text needs observability wiring (serve via "
                    "gateway.server or pass obs= to TpuService)"
                )
            response.string_output = self.obs.registry.render()
            return response
        if view == "trace":
            if self.obs is None:
                raise ValueError(
                    "trace view needs observability wiring (serve via "
                    "gateway.server or pass obs= to TpuService)"
                )
            response.struct_output.update({
                "traces": self.obs.recorder.traces(),
                "events": self.obs.recorder.events(),
            })
            return response
        if view != "stats":
            raise ValueError(
                f"unknown engine_stats view {view!r}; "
                "use stats, metrics_text, or trace"
            )
        stats = self.engine.stats()
        if self.supervisor is not None:
            stats["engine_restarts"] = self.supervisor.restarts
            stats["supervisor_gave_up"] = self.supervisor.gave_up
        if self.obs is not None:
            last = self.obs.recorder.last(self._is_llm_trace)
            if last is not None:
                stats["last_trace"] = last
        response.struct_output.update(stats)
        return response

    @staticmethod
    def _is_llm_trace(trace: dict) -> bool:
        return trace.get("attrs", {}).get("tool") in _LLM_TOOLS

    def execute_tool(self, tool_name, parameters, secret_id, metadata):
        self._resolve_secret(secret_id)
        span = current_span()
        if span is not None:
            span.set(tool=tool_name)
        if tool_name == "engine_profile":
            return self._engine_profile(parameters)
        if tool_name == "engine_stats":
            return self._engine_stats(parameters)
        if tool_name not in _LLM_TOOLS:
            return self._mock.execute_tool(tool_name, parameters, secret_id, metadata)

        params = dict(parameters) if parameters is not None else {}
        request = self._build_request(parameters)
        request.trace = span
        stops = self._parse_stops(params)
        skip = self._parse_received(params)
        self._submit(request)

        if not stops:
            # No stop scanning → no per-token decode: collect ids and
            # detokenize once (one decode call beats _text_events'
            # per-token window decodes when no one needs deltas).
            token_ids: list[int] = []
            for kind, value in self._drain(
                request, self.engine.config.request_timeout_s
            ):
                if kind == "token":
                    token_ids.append(value)
                elif kind == "error":
                    raise self._engine_error(value)
            t0 = time.monotonic()
            text = self.engine.tokenizer.decode(token_ids[skip:])
            if request.trace is not None:
                request.trace.child(
                    "detokenize", start=t0, end=time.monotonic(),
                    tokens=len(token_ids),
                )
        else:
            pieces: list[str] = []
            for kind, value in self._text_events(request, stops, skip):
                if kind == "delta":
                    pieces.append(value)
            text = "".join(pieces)

        self._stamp_serving_trailers(request)
        response = pk.ExecuteToolResponse(
            status=cmn.Status(code=200, message="Tool executed successfully"),
            string_output=text,
        )
        return response

    def execute_tool_stream(
        self, tool_name, parameters, secret_id, metadata
    ) -> Iterator[pk.ExecuteToolStreamChunk]:
        self._resolve_secret(secret_id)
        span = current_span()
        if span is not None:
            span.set(tool=tool_name)
        if tool_name not in _LLM_TOOLS:
            yield from self._mock.execute_tool_stream(
                tool_name, parameters, secret_id, metadata
            )
            return

        params = dict(parameters) if parameters is not None else {}
        request = self._build_request(parameters)
        request.trace = span
        stops = self._parse_stops(params)
        skip = self._parse_received(params)
        self._submit(request)

        timings = None
        try:
            for kind, value in self._text_events(request, stops, skip):
                if kind == "delta":
                    yield pk.ExecuteToolStreamChunk(delta=value)
                else:
                    timings = value
        except GeneratorExit:
            request.cancelled.set()  # client went away mid-stream
            if span is not None:
                # Stamp the abort reason NOW: the interceptor freezes the
                # tree into the flight recorder the moment this exception
                # unwinds, before the engine thread reaches its own
                # _finish bookkeeping for the cancelled slot.
                span.set(client_disconnected=True)
            raise

        self._stamp_serving_trailers(request)
        final = pk.ExecuteToolStreamChunk(
            final=True,
            status=cmn.Status(code=200, message="Tool executed successfully"),
        )
        if timings is not None:
            final.usage.prompt_tokens = timings.prompt_tokens
            final.usage.completion_tokens = timings.completion_tokens
            final.usage.ttft_ms = timings.ttft_ms
            final.usage.tokens_per_sec = timings.tokens_per_sec
        yield final
