"""Gateway tier: gRPC server/client, config, health, observability.

Behavior parity with the reference Go gateway (/root/reference/cmd/polykey,
cmd/dev_client, internal/{server,service,config}), with the service seam
(`Service.execute_tool`) as the mount point for the TPU engine.
"""
