"""Jest-style log report renderers.

Parity with the reference's two renderers:

- `print_jest_report` — the in-process reporter the dev client runs over its
  buffered JSON logs (/root/reference/test/utils/beautify.go:30-66): suites
  SETUP / CONNECTION / EXECUTION / ERROR keyed on specific ``msg`` strings,
  green-check PASS steps, and a final PASS/FAIL summary banner.
- `beautify_server_stream` — the stdin pipe filter for *server* logs
  (/root/reference/cmd/utils/log-beautifier/main.go), tolerant of non-JSON
  prefixes, tracking in-flight RPCs by method and rendering FAIL for any
  terminal code other than "OK". Run as
  ``python -m polykey_tpu.gateway.log_beautifier``; a native C++ build of the
  same filter lives in native/log_beautifier.cc.

Where the Go reporter sniffs ``go test -json`` streams, this one sniffs
``pytest --report-log`` JSONL streams (key ``$report_type``) — the analogous
test-runner format for this framework's toolchain.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, Optional, TextIO

GREEN = "\033[0;32m"
RED = "\033[0;31m"
GRAY = "\033[0;90m"
CYAN = "\033[0;36m"
BOLD = "\033[1m"
RESET = "\033[0m"
BG_GREEN = "\033[42;30m"
BG_RED = "\033[41;37m"


class _Report:
    def __init__(self, out: TextIO):
        self.out = out
        self.current_suite: Optional[str] = None
        self.passes = 0
        self.failures: list[str] = []

    def suite(self, name: str) -> None:
        if self.current_suite != name:
            sep = "─" * 10
            self.out.write(f"\n{GRAY}{sep} {BOLD}{name} {sep}{RESET}\n")
            self.current_suite = name

    def step(self, ok: bool, message: str, details: str = "") -> None:
        color, symbol = (GREEN, "✓") if ok else (RED, "✗")
        suffix = f" {GRAY}({details}){RESET}" if details else ""
        self.out.write(f"  {color}{symbol}{RESET} {message}{suffix}\n")
        if ok:
            self.passes += 1
        else:
            self.failures.append(message)

    def note(self, text: str) -> None:
        self.out.write(f"    {GRAY}{text}{RESET}\n")

    def summary(self) -> None:
        self.out.write(GRAY + "\n" + "=" * 40 + RESET + "\n")
        if self.failures:
            self.out.write(
                f" {BG_RED} FAIL {RESET} {len(self.failures)} failed,"
                f" {self.passes} passed\n"
            )
        else:
            self.out.write(
                f" {BG_GREEN} PASS {RESET} All {self.passes} checks passed\n"
            )


def _parse(line: str) -> Optional[dict]:
    line = line.strip()
    if not line:
        return None
    try:
        entry = json.loads(line)
    except ValueError:
        return None
    return entry if isinstance(entry, dict) else None


def print_jest_report(log_lines: Iterable[str], out: TextIO = sys.stdout) -> bool:
    """Render buffered client/test logs; returns True when nothing failed."""
    report = _Report(out)
    mode = None
    out.write("\n")
    for line in log_lines:
        entry = _parse(line)
        if entry is None:
            continue
        if mode is None:
            if "$report_type" in entry:
                mode = "pytest"
                out.write(f"{BOLD}{CYAN} RUNS Pytest Suite{RESET}\n")
            elif "msg" in entry:
                mode = "app"
                out.write(f"{BOLD}{CYAN} RUNS Polykey Dev Client{RESET}\n")
            else:
                continue
        if mode == "app":
            _app_entry(entry, report)
        else:
            _pytest_entry(entry, report)
    report.summary()
    return not report.failures


def _app_entry(entry: dict, report: _Report) -> None:
    msg = entry.get("msg", "")
    if entry.get("level") == "DEBUG":
        report.suite("CONNECTION")
        report.note(f"{msg}...state={entry.get('state')}")
        return
    if msg == "Configuration loaded":
        report.suite("SETUP")
        report.step(True, "Configuration", f"server={entry.get('server')}")
    elif msg == "Network connectivity test passed":
        report.suite("CONNECTION")
        report.step(True, "Network Connectivity")
    elif msg == "gRPC connection established successfully":
        report.suite("CONNECTION")
        report.step(True, "gRPC Connection")
    elif msg == "Executing tool":
        report.suite("EXECUTION")
        report.step(True, "Tool Execution", f"tool={entry.get('tool_name')}")
    elif msg == "Tool execution completed":
        report.suite("EXECUTION")
        report.note(f"└─ Status: '{entry.get('status_message')}'")
    elif msg == "Received struct output":
        report.suite("EXECUTION")
        report.note(f"└─ Received Output (fields={entry.get('field_count')})")
    elif msg == "Streaming completed":
        report.suite("EXECUTION")
        report.note(
            f"└─ Streamed {entry.get('completion_tokens')} tokens"
            f" (ttft={entry.get('ttft_ms')}ms)"
        )
    elif msg == "Application failed":
        report.suite("ERROR")
        details = str(entry.get("error"))
        report.step(False, "Application Run", details)


def _pytest_entry(entry: dict, report: _Report) -> None:
    # pytest --report-log emits TestReport records; count the `call` phase.
    if entry.get("$report_type") != "TestReport" or entry.get("when") != "call":
        return
    nodeid = entry.get("nodeid", "?")
    suite = nodeid.split("::", 1)[0]
    report.suite(suite)
    duration_ms = round(float(entry.get("duration", 0.0)) * 1000)
    report.step(entry.get("outcome") == "passed", nodeid, f"{duration_ms}ms")


def beautify_server_stream(
    stdin: TextIO = sys.stdin, out: TextIO = sys.stdout
) -> None:
    """Pipe filter for server JSON logs (reference: cmd/utils/log-beautifier).

    Non-JSON lines (and compose prefixes before the first '{') pass through
    untouched; recognized server lifecycle and per-RPC lines render as steps.
    """
    report = _Report(out)
    pending: dict[str, int] = {}  # method → in-flight count
    for raw in stdin:
        raw = raw.rstrip("\n")
        start = raw.find("{")
        if start == -1:
            out.write(raw + "\n")
            continue
        entry = _parse(raw[start:])
        if entry is None:
            out.write(raw + "\n")
            continue
        msg = entry.get("msg", "")
        method = str(entry.get("method", ""))
        if msg == "server starting":
            report.suite("SETUP")
            report.step(True, "Server Listening", f"addr={entry.get('address')}")
        elif msg == "gRPC call received":
            report.suite("CONNECTION")
            report.step(True, "gRPC Connection", method)
            report.suite("EXECUTION")
            pending[method] = pending.get(method, 0) + 1
            out.write(f"  ○ {GRAY}{method}{RESET}\n")
        elif msg == "gRPC call finished":
            if pending.get(method, 0) <= 0:
                # No matched "received" (e.g. attached mid-stream): pass the
                # raw line through rather than dropping the RPC's outcome.
                out.write(raw + "\n")
                continue
            pending[method] -= 1
            code = entry.get("code", "OK")
            report.step(code == "OK", method, str(entry.get("duration", "")))
        elif msg in ("server shutting down", "server stopped"):
            report.suite("SHUTDOWN")
            report.step(True, msg)
