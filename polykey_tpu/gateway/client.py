"""Dev client — end-to-end smoke test of the running gateway.

Parity with /root/reference/cmd/dev_client/main.go: logs to an in-memory
buffer, runs config-load → raw TCP probe → gRPC connect (insecure creds,
client keepalive 10s/5s with permit-without-stream, 4 MiB message caps) →
one `example_tool` ExecuteTool call with struct params, secret_id
"secret-123" and request metadata, 30s deadline — then renders the buffered
logs as a Jest-style report. Its four PASS checks (config, TCP, gRPC READY,
tool execution) are the acceptance criterion (reference README.md:84-101).

Extension: ``--tool`` selects the tool and ``--stream`` exercises the
server-streaming RPC (prints tokens as they arrive, then TTFT/throughput).

Resilience (ISSUE 3): calls retry on UNAVAILABLE (engine restarting
under supervision) and RESOURCE_EXHAUSTED (admission shed) with
exponential backoff + full jitter, honoring the server's
``retry-after-ms`` trailing-metadata hint when present. DEADLINE_EXCEEDED
is never retried (the budget is spent by definition), and a stream is
never retried once any chunk has arrived (the server already did work
and partial output was observed — a retry would silently duplicate it).
"""

from __future__ import annotations

import argparse
import io
import random
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import grpc

from .errors import (
    REPLICA_KEY,
    RESTARTED_KEY,
    RESUME_SUPPORTED_KEY,
    RESUME_TOKENS_KEY,
    RETRY_AFTER_MS_KEY,
)

from ..proto import common_v2_pb2 as cmn
from ..proto import polykey_v2_pb2 as pk
from ..proto.polykey_v2_grpc import PolykeyServiceStub
from .beautify import print_jest_report
from .config import Config, ConfigLoader, NetworkTester
from .jsonlog import Logger

_CHANNEL_OPTIONS = [
    ("grpc.keepalive_time_ms", 10_000),
    ("grpc.keepalive_timeout_ms", 5_000),
    ("grpc.keepalive_permit_without_calls", 1),
    ("grpc.max_receive_message_length", 4 * 1024 * 1024),
    ("grpc.max_send_message_length", 4 * 1024 * 1024),
]

RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,        # engine restarting / not up yet
    grpc.StatusCode.RESOURCE_EXHAUSTED,  # admission shed; retry-after hints
})


def trailers_from(obj) -> dict:
    """Trailing metadata of a grpc.Call / RpcError as a dict ({} when
    the object has none — in-process test doubles)."""
    try:
        return dict(obj.trailing_metadata() or ())
    except Exception:
        return {}  # not a grpc.Call (test doubles): no trailers to read


def retry_after_ms_from(err: grpc.RpcError) -> Optional[int]:
    """The server's retry-after-ms trailing-metadata hint, if any."""
    value = trailers_from(err).get(RETRY_AFTER_MS_KEY)
    if value is None:
        return None
    try:
        return int(value)
    except ValueError:
        return None


def resume_tokens_from(err: grpc.RpcError) -> Optional[int]:
    """Mid-stream resume contract (ISSUE 9): when an UNAVAILABLE stream
    failure carries `resume-supported`, its `resume-tokens` trailer is
    the count of tokens the server already delivered — re-issuing the
    request with `received_tokens` set to it streams only the missing
    suffix. Returns None when the server did not offer a resume."""
    trailers = trailers_from(err)
    if trailers.get(RESUME_SUPPORTED_KEY) != "1":
        return None
    try:
        return int(trailers[RESUME_TOKENS_KEY])
    except (KeyError, ValueError):
        return None


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter over the retryable codes.

    The server's retry-after-ms hint, when present, replaces the
    computed backoff (it knows the queue's drain rate; the client
    doesn't) — scaled by a small random factor so a thundering herd of
    shed clients doesn't return in lockstep. `sleep` is injectable so
    tests assert the schedule without real waiting."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    sleep: Callable[[float], None] = field(default=time.sleep)

    def should_retry(self, code: grpc.StatusCode, attempt: int) -> bool:
        return code in RETRYABLE_CODES and attempt + 1 < self.max_attempts

    def delay_s(self, attempt: int, retry_after_ms: Optional[int]) -> float:
        if retry_after_ms is not None:
            return (retry_after_ms / 1000.0) * (1.0 + 0.25 * random.random())
        cap = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** attempt)
        return cap * (0.5 + 0.5 * random.random())


_DEFAULT_RETRY = RetryPolicy()


class Client:
    def __init__(self, cfg: Config, logger: Logger,
                 retry: Optional[RetryPolicy] = _DEFAULT_RETRY):
        self.logger = logger
        # retry=None disables retries entirely (at-most-once semantics
        # for non-idempotent tools); the default policy retries only
        # codes where the server did not start the work.
        self.retry = retry
        self.channel = self._create_channel(cfg)
        self.stub = PolykeyServiceStub(self.channel)

    def _create_channel(self, cfg: Config) -> grpc.Channel:
        self.logger.info("Creating gRPC connection", server=cfg.server_address)
        channel = grpc.insecure_channel(cfg.server_address, options=_CHANNEL_OPTIONS)
        self._wait_for_ready(channel, cfg.timeout)
        self.logger.info("gRPC connection established successfully")
        return channel

    def _wait_for_ready(self, channel: grpc.Channel, timeout: float) -> None:
        # Explicit connectivity state machine (dev_client/main.go:214-236):
        # log transitions at DEBUG, fail on TRANSIENT_FAILURE / SHUTDOWN.
        done = threading.Event()
        failed: list[grpc.ChannelConnectivity] = []
        first = True

        def on_state(state: grpc.ChannelConnectivity) -> None:
            nonlocal first
            if first:
                self.logger.debug("Initial connection state", state=state.name)
                first = False
            else:
                self.logger.debug("Connection state changed", state=state.name)
            if state == grpc.ChannelConnectivity.READY:
                done.set()
            elif state in (
                grpc.ChannelConnectivity.TRANSIENT_FAILURE,
                grpc.ChannelConnectivity.SHUTDOWN,
            ):
                failed.append(state)
                done.set()

        channel.subscribe(on_state, try_to_connect=True)
        if not done.wait(timeout):
            raise TimeoutError("connection timeout")
        if failed:
            raise ConnectionError(f"connection failed with state: {failed[0].name}")

    def close(self) -> None:
        self.channel.close()

    def _backoff(self, e: grpc.RpcError, attempt: int) -> bool:
        """Decide + perform the retry wait for a failed attempt. Returns
        False when the error is terminal (caller re-raises)."""
        code = e.code()
        if self.retry is None or not self.retry.should_retry(code, attempt):
            return False
        delay = self.retry.delay_s(attempt, retry_after_ms_from(e))
        self.logger.warn(
            "gRPC call retrying", code=code.name, attempt=attempt + 1,
            delay_ms=round(delay * 1e3, 1),
        )
        self.retry.sleep(delay)
        return True

    def execute_tool(self, request: pk.ExecuteToolRequest, timeout: float = 30.0):
        self.logger.info(
            "Executing tool",
            tool_name=request.tool_name,
            secret_id=request.secret_id if request.HasField("secret_id") else None,
            has_metadata=request.HasField("metadata"),
        )
        attempt = 0
        while True:
            try:
                resp = self.stub.ExecuteTool(request, timeout=timeout)
                break
            except grpc.RpcError as e:
                if self._backoff(e, attempt):
                    attempt += 1
                    continue
                self.logger.error(
                    "gRPC call failed", code=e.code().name, message=e.details()
                )
                raise
        self._log_response(resp)
        return resp

    def _resume_request(self, request: pk.ExecuteToolRequest,
                        received_tokens: int) -> pk.ExecuteToolRequest:
        """A copy of `request` carrying received_tokens — the caller's
        proto must not be mutated across resume attempts."""
        resumed = pk.ExecuteToolRequest()
        resumed.CopyFrom(request)
        resumed.parameters.update({"received_tokens": received_tokens})
        return resumed

    def execute_tool_stream(self, request: pk.ExecuteToolRequest, timeout: float = 30.0):
        self.logger.info(
            "Executing tool",
            tool_name=request.tool_name,
            secret_id=request.secret_id if request.HasField("secret_id") else None,
            has_metadata=request.HasField("metadata"),
        )
        attempt = 0
        # Accumulated across RESUME attempts (the server only streams the
        # missing suffix); cleared on plain retries, which only happen
        # before any chunk arrived.
        text: list[str] = []
        usage, status, trailers = None, None, {}
        while True:
            usage, status = None, None
            received = False
            try:
                call = self.stub.ExecuteToolStream(request, timeout=timeout)
                for chunk in call:
                    received = True
                    if chunk.delta:
                        text.append(chunk.delta)
                    if chunk.final:
                        if chunk.HasField("status"):
                            status = chunk.status
                        if chunk.HasField("usage"):
                            usage = chunk.usage
                trailers = trailers_from(call)
                break
            except grpc.RpcError as e:
                # Mid-stream resume (ISSUE 9): an UNAVAILABLE failure
                # that carries the resume trailers can be re-issued with
                # received_tokens — the server suppresses what we
                # already hold, so nothing replays. Gated on the same
                # retry budget/backoff as ordinary retries.
                resume_at = (
                    resume_tokens_from(e)
                    if e.code() == grpc.StatusCode.UNAVAILABLE else None
                )
                if (
                    resume_at is not None and self.retry is not None
                    and self.retry.should_retry(e.code(), attempt)
                ):
                    delay = self.retry.delay_s(attempt, retry_after_ms_from(e))
                    self.logger.warn(
                        "stream interrupted; resuming",
                        code=e.code().name, received_tokens=resume_at,
                        attempt=attempt + 1, delay_ms=round(delay * 1e3, 1),
                    )
                    self.retry.sleep(delay)
                    request = self._resume_request(request, resume_at)
                    attempt += 1
                    continue
                # Mid-stream failures without a resume offer are
                # terminal: chunks were already observed, so a blind
                # retry would silently replay output.
                # (text needs no reset here: received is False, so this
                # attempt appended nothing, and text from earlier RESUME
                # attempts must survive — the re-issued request still
                # carries their received_tokens.)
                if not received and self._backoff(e, attempt):
                    attempt += 1
                    continue
                self.logger.error(
                    "gRPC call failed", code=e.code().name, message=e.details()
                )
                raise
        if REPLICA_KEY in trailers:
            # Replica-tier trailers: which replica served, and whether
            # the stream was resumed server-side on a replica failure.
            self.logger.info(
                "Served by replica",
                replica=trailers[REPLICA_KEY],
                restarted=trailers.get(RESTARTED_KEY) == "1",
            )
        if status is not None:
            self.logger.info(
                "Tool execution completed",
                status_code=status.code,
                status_message=status.message,
            )
        if usage is not None:
            self.logger.info(
                "Streaming completed",
                completion_tokens=usage.completion_tokens,
                ttft_ms=round(usage.ttft_ms, 1),
                tokens_per_sec=round(usage.tokens_per_sec, 1),
            )
        return "".join(text)

    def _log_response(self, resp: pk.ExecuteToolResponse) -> None:
        if resp.HasField("status"):
            self.logger.info(
                "Tool execution completed",
                status_code=resp.status.code,
                status_message=resp.status.message,
            )
        arm = resp.WhichOneof("output")
        if arm == "string_output":
            preview = resp.string_output[:100] + (
                "..." if len(resp.string_output) > 100 else ""
            )
            self.logger.info(
                "Received string output",
                output_length=len(resp.string_output),
                output_preview=preview,
            )
        elif arm == "struct_output":
            self.logger.info(
                "Received struct output", field_count=len(resp.struct_output.fields)
            )
        elif arm == "file_output":
            self.logger.info(
                "Received file output",
                file_name=resp.file_output.file_name,
                mime_type=resp.file_output.mime_type,
                size_bytes=len(resp.file_output.content),
            )
        else:
            self.logger.warn("No output returned")


def build_test_request(tool_name: str = "example_tool", prompt: Optional[str] = None):
    request = pk.ExecuteToolRequest(tool_name=tool_name, secret_id="secret-123")
    params: dict = {"example_param": "value", "timestamp": int(time.time())}
    if prompt is not None:
        params["prompt"] = prompt
    request.parameters.update(params)
    request.metadata.CopyFrom(
        cmn.Metadata(
            fields={
                "client_version": "1.0.0",
                "request_source": "dev_client",
                "request_id": f"req-{time.time_ns()}",
            }
        )
    )
    return request


def run(logger: Logger, args: argparse.Namespace) -> None:
    logger.info("Starting polykey client...")

    loader = ConfigLoader()
    cfg = loader.load([])
    if args.server:
        cfg.server_address = args.server
    logger.info(
        "Configuration loaded",
        runtime=str(cfg.detected_runtime),
        server=cfg.server_address,
    )

    logger.info("Testing network connectivity...")
    NetworkTester().test_connection(cfg.server_address)
    logger.info("Network connectivity test passed")

    client = Client(cfg, logger)
    try:
        request = build_test_request(args.tool, args.prompt)
        if args.stream:
            client.execute_tool_stream(request)
        else:
            client.execute_tool(request)
    finally:
        client.close()


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="polykey dev client")
    parser.add_argument("-server", "--server", default="", help="server address")
    parser.add_argument("--tool", default="example_tool")
    parser.add_argument("--prompt", default=None)
    parser.add_argument("--stream", action="store_true")
    parser.add_argument(
        "--raw-logs", action="store_true", help="print JSON logs instead of report"
    )
    args = parser.parse_args(argv)

    buffer = io.StringIO()
    logger = Logger(stream=buffer, level="debug")

    try:
        signal.signal(signal.SIGINT, signal.default_int_handler)
    except ValueError:
        pass  # not on the main thread (tests)

    ok = True
    try:
        run(logger, args)
    except KeyboardInterrupt:
        logger.info("Received shutdown signal")
    except Exception as e:
        logger.error("Application failed", error=str(e))
        ok = False

    lines = buffer.getvalue().splitlines()
    if args.raw_logs:
        sys.stdout.write("\n".join(lines) + "\n")
    else:
        ok = print_jest_report(lines) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
