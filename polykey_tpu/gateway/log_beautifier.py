"""Stdin pipe entry point for the server-log beautifier.

Usage (reference parity: Makefile compose-logs pipes
``docker compose logs -f`` into the Go binary —
/root/reference/Makefile:143-150):

    docker compose logs -f | python -m polykey_tpu.gateway.log_beautifier

A native C++ build of the same filter is available via ``make native``
(native/log_beautifier.cc) for log pipelines where a Python runtime is
unwanted.
"""

from .beautify import beautify_server_stream

if __name__ == "__main__":
    beautify_server_stream()
