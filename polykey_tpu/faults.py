"""Fault injection: named failure points, armed only via POLYKEY_FAULTS.

The resilience layer (deadline drops, load shedding, watchdog trip,
supervised restart) is unreachable by well-behaved CPU tests — a tiny
model never hangs, never exhausts its allocator, never misses a
deadline. This module makes those paths deterministically reachable:
the engine asks for a module-shared `FaultInjector` at construction and
consults it at a handful of *named injection points*; the injector is
None unless `POLYKEY_FAULTS` is set (or a test calls `install()`), so
every call site reduces to one attribute load plus an `is None` check —
no parsing, no dict lookups, no clock reads on the hot path.

Spec grammar (comma- or semicolon-separated entries)::

    POLYKEY_FAULTS="step-stall=1.5@1,slow-step=0.01"
    POLYKEY_FAULTS="step-stall=1.0@1:replica=2"     # target one replica

    entry   := name [ "=" value ] [ "@" count ] [ ":replica=" index ]
    value   := float    seconds for sleep points; ignored by raise points
                        (default 1.0)
    count   := int      how many times the point fires before going
                        inert (default: unlimited)
    index   := int      fire only for the engine replica with this index
                        (replica_pool.py; a single engine is replica 0).
                        Without the suffix the fault fires on every
                        replica — chaos tests that kill ONE replica
                        while the others serve need the targeting.

Points (all consumed by engine/engine.py):

- ``step-stall``   — sleep `value` s inside the decode dispatch (a wedged
                     device call; trips the watchdog when it exceeds
                     `watchdog_timeout_s`).
- ``slow-step``    — same site, meant small and recurring (degraded
                     device / contended tunnel).
- ``alloc-fail``   — raise AllocationError at page allocation
                     (pool exhaustion → admission backpressure).
- ``prefill-error``— raise RuntimeError inside the prefill dispatch
                     (device-side compile/execute failure).
- ``tokenizer-error`` — raise RuntimeError at prompt tokenization
                     (malformed-input handling at admission).

The injector is intentionally module-shared: a supervised restart builds
a *fresh* engine, and a one-shot fault (``@1``) must stay spent across
that restart or the chaos tests could never observe recovery.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

POINTS = frozenset(
    {"step-stall", "slow-step", "alloc-fail", "prefill-error",
     "tokenizer-error"}
)

ENV_VAR = "POLYKEY_FAULTS"


@dataclass
class _Fault:
    value: float = 1.0
    remaining: Optional[int] = None  # None → unlimited
    fired: int = 0
    replica: Optional[int] = None    # None → fires on every replica


class FaultInjector:
    """Parsed POLYKEY_FAULTS spec with thread-safe fire accounting
    (points are consumed from the engine thread AND gRPC handler
    threads)."""

    def __init__(self, spec: str):
        self._lock = threading.Lock()
        # One point can carry SEVERAL entries (e.g. the same fault
        # targeted at two different replicas) — keyed by name alone they
        # would silently overwrite and a two-replica chaos spec would
        # only ever kill one.
        self._faults: dict[str, list[_Fault]] = {}
        for raw in spec.replace(";", ",").split(","):
            entry = raw.strip()
            if not entry:
                continue
            replica: Optional[int] = None
            if ":" in entry:
                # Replica targeting rides a trailing ":replica=N" so chaos
                # tests can kill one pool replica while the others serve.
                entry, target = entry.rsplit(":", 1)
                key, _, index_s = target.partition("=")
                if key.strip() != "replica":
                    raise ValueError(
                        f"unknown fault qualifier {target!r}; only "
                        "':replica=N' is supported"
                    )
                replica = int(index_s)
            count: Optional[int] = None
            if "@" in entry:
                entry, count_s = entry.rsplit("@", 1)
                count = int(count_s)
            value = 1.0
            if "=" in entry:
                entry, value_s = entry.split("=", 1)
                value = float(value_s)
            name = entry.strip()
            if name not in POINTS:
                raise ValueError(
                    f"unknown fault point {name!r}; valid points: "
                    f"{', '.join(sorted(POINTS))}"
                )
            self._faults.setdefault(name, []).append(_Fault(
                value=value, remaining=count, replica=replica
            ))

    def _take(self, point: str, replica: Optional[int] = None) -> Optional[float]:
        """Consume one firing of `point` — the first armed entry whose
        replica target matches; returns its value, or None when the
        point is unarmed, exhausted, or targeted elsewhere (`replica`
        is the caller's replica index; callers that pass None only
        consume untargeted faults)."""
        with self._lock:
            for fault in self._faults.get(point, ()):
                if fault.remaining == 0:
                    continue
                if fault.replica is not None and replica != fault.replica:
                    continue
                if fault.remaining is not None:
                    fault.remaining -= 1
                fault.fired += 1
                return fault.value
            return None

    def maybe_sleep(self, point: str, replica: Optional[int] = None) -> None:
        """Sleep the point's value (seconds) if it fires. Sleeping stands
        in for a wedged/slow device call, so it deliberately blocks the
        calling thread exactly where the real stall would."""
        value = self._take(point, replica)
        if value is not None and value > 0:
            time.sleep(value)

    def maybe_raise(self, point: str, exc_type: type = RuntimeError,
                    replica: Optional[int] = None) -> None:
        if self._take(point, replica) is not None:
            raise exc_type(f"injected fault: {point}")

    def fired(self, point: str) -> int:
        with self._lock:
            return sum(f.fired for f in self._faults.get(point, ()))


_injector: Optional[FaultInjector] = None
_initialized = False
_guard = threading.Lock()


def get_injector() -> Optional[FaultInjector]:
    """The shared injector, lazily built from POLYKEY_FAULTS on first
    call. Returns None (and caches the None) when the env var is unset —
    the zero-overhead guarantee call sites rely on."""
    global _injector, _initialized
    with _guard:
        if not _initialized:
            _initialized = True
            spec = os.environ.get(ENV_VAR, "")
            if spec:
                _injector = FaultInjector(spec)
        return _injector


def install(spec: str) -> FaultInjector:
    """Programmatic arm (tests): replaces the shared injector."""
    global _injector, _initialized
    with _guard:
        _injector = FaultInjector(spec)
        _initialized = True
        return _injector


def clear() -> None:
    """Disarm and forget: the next get_injector() re-reads the env."""
    global _injector, _initialized
    with _guard:
        _injector = None
        _initialized = False
