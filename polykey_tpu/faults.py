"""Fault injection: named failure points, armed only via POLYKEY_FAULTS.

The resilience layer (deadline drops, load shedding, watchdog trip,
supervised restart) is unreachable by well-behaved CPU tests — a tiny
model never hangs, never exhausts its allocator, never misses a
deadline. This module makes those paths deterministically reachable:
the engine asks for a module-shared `FaultInjector` at construction and
consults it at a handful of *named injection points*; the injector is
None unless `POLYKEY_FAULTS` is set (or a test calls `install()`), so
every call site reduces to one attribute load plus an `is None` check —
no parsing, no dict lookups, no clock reads on the hot path.

Spec grammar (comma- or semicolon-separated entries)::

    POLYKEY_FAULTS="step-stall=1.5@1,slow-step=0.01"

    entry   := name [ "=" value ] [ "@" count ]
    value   := float    seconds for sleep points; ignored by raise points
                        (default 1.0)
    count   := int      how many times the point fires before going
                        inert (default: unlimited)

Points (all consumed by engine/engine.py):

- ``step-stall``   — sleep `value` s inside the decode dispatch (a wedged
                     device call; trips the watchdog when it exceeds
                     `watchdog_timeout_s`).
- ``slow-step``    — same site, meant small and recurring (degraded
                     device / contended tunnel).
- ``alloc-fail``   — raise AllocationError at page allocation
                     (pool exhaustion → admission backpressure).
- ``prefill-error``— raise RuntimeError inside the prefill dispatch
                     (device-side compile/execute failure).
- ``tokenizer-error`` — raise RuntimeError at prompt tokenization
                     (malformed-input handling at admission).

The injector is intentionally module-shared: a supervised restart builds
a *fresh* engine, and a one-shot fault (``@1``) must stay spent across
that restart or the chaos tests could never observe recovery.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

POINTS = frozenset(
    {"step-stall", "slow-step", "alloc-fail", "prefill-error",
     "tokenizer-error"}
)

ENV_VAR = "POLYKEY_FAULTS"


@dataclass
class _Fault:
    value: float = 1.0
    remaining: Optional[int] = None  # None → unlimited
    fired: int = 0


class FaultInjector:
    """Parsed POLYKEY_FAULTS spec with thread-safe fire accounting
    (points are consumed from the engine thread AND gRPC handler
    threads)."""

    def __init__(self, spec: str):
        self._lock = threading.Lock()
        self._faults: dict[str, _Fault] = {}
        for raw in spec.replace(";", ",").split(","):
            entry = raw.strip()
            if not entry:
                continue
            count: Optional[int] = None
            if "@" in entry:
                entry, count_s = entry.rsplit("@", 1)
                count = int(count_s)
            value = 1.0
            if "=" in entry:
                entry, value_s = entry.split("=", 1)
                value = float(value_s)
            name = entry.strip()
            if name not in POINTS:
                raise ValueError(
                    f"unknown fault point {name!r}; valid points: "
                    f"{', '.join(sorted(POINTS))}"
                )
            self._faults[name] = _Fault(value=value, remaining=count)

    def _take(self, point: str) -> Optional[float]:
        """Consume one firing of `point`; returns its value, or None when
        the point is unarmed or exhausted."""
        with self._lock:
            fault = self._faults.get(point)
            if fault is None or fault.remaining == 0:
                return None
            if fault.remaining is not None:
                fault.remaining -= 1
            fault.fired += 1
            return fault.value

    def maybe_sleep(self, point: str) -> None:
        """Sleep the point's value (seconds) if it fires. Sleeping stands
        in for a wedged/slow device call, so it deliberately blocks the
        calling thread exactly where the real stall would."""
        value = self._take(point)
        if value is not None and value > 0:
            time.sleep(value)

    def maybe_raise(self, point: str, exc_type: type = RuntimeError) -> None:
        if self._take(point) is not None:
            raise exc_type(f"injected fault: {point}")

    def fired(self, point: str) -> int:
        with self._lock:
            fault = self._faults.get(point)
            return fault.fired if fault is not None else 0


_injector: Optional[FaultInjector] = None
_initialized = False
_guard = threading.Lock()


def get_injector() -> Optional[FaultInjector]:
    """The shared injector, lazily built from POLYKEY_FAULTS on first
    call. Returns None (and caches the None) when the env var is unset —
    the zero-overhead guarantee call sites rely on."""
    global _injector, _initialized
    with _guard:
        if not _initialized:
            _initialized = True
            spec = os.environ.get(ENV_VAR, "")
            if spec:
                _injector = FaultInjector(spec)
        return _injector


def install(spec: str) -> FaultInjector:
    """Programmatic arm (tests): replaces the shared injector."""
    global _injector, _initialized
    with _guard:
        _injector = FaultInjector(spec)
        _initialized = True
        return _injector


def clear() -> None:
    """Disarm and forget: the next get_injector() re-reads the env."""
    global _injector, _initialized
    with _guard:
        _injector = None
        _initialized = False
