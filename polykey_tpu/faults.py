"""Fault injection: named failure points, armed only via POLYKEY_FAULTS.

The resilience layer (deadline drops, load shedding, watchdog trip,
supervised restart) is unreachable by well-behaved CPU tests — a tiny
model never hangs, never exhausts its allocator, never misses a
deadline. This module makes those paths deterministically reachable:
the engine asks for a module-shared `FaultInjector` at construction and
consults it at a handful of *named injection points*; the injector is
None unless `POLYKEY_FAULTS` is set (or a test calls `install()`), so
every call site reduces to one attribute load plus an `is None` check —
no parsing, no dict lookups, no clock reads on the hot path.

Spec grammar (comma- or semicolon-separated entries)::

    POLYKEY_FAULTS="step-stall=1.5@1,slow-step=0.01"
    POLYKEY_FAULTS="step-stall=1.0@1:replica=2"     # target one replica
    POLYKEY_FAULTS="worker-exit=0@1:tier=prefill"   # target one tier

    entry   := name [ "=" value ] [ "@" count ] qualifier*
    qualifier := ":replica=" index | ":tier=" tier
    value   := float    seconds for sleep points; ignored by raise points
                        (default 1.0)
    count   := int      how many times the point fires before going
                        inert (default: unlimited)
    index   := int      fire only for the engine replica with this index
                        (replica_pool.py; a single engine is replica 0).
                        Without the suffix the fault fires on every
                        replica — chaos tests that kill ONE replica
                        while the others serve need the targeting.
    tier    := prefill | decode
                        fire only inside a disaggregated worker of that
                        tier (engine/worker.py; engines pass their
                        config.disagg_tier). A tier-targeted fault is
                        NEVER consumed by an untiered caller, so a
                        single-process engine can't accidentally eat a
                        fault aimed at one worker tier. Qualifiers
                        compose: ":replica=1:tier=decode" targets the
                        second decode-tier worker.

Points (consumed by engine/engine.py unless noted):

- ``step-stall``   — sleep `value` s inside the decode dispatch (a wedged
                     device call; trips the watchdog when it exceeds
                     `watchdog_timeout_s`).
- ``slow-step``    — same site, meant small and recurring (degraded
                     device / contended tunnel).
- ``alloc-fail``   — raise AllocationError at page allocation
                     (pool exhaustion → admission backpressure).
- ``prefill-error``— raise RuntimeError inside the prefill dispatch
                     (device-side compile/execute failure).
- ``tokenizer-error`` — raise RuntimeError at prompt tokenization
                     (malformed-input handling at admission).
- ``kv-handoff-drop`` — engine/worker.py: corrupt the serialized KV
                     handoff payload at ship time (truncate to half),
                     exercising the coordinator's partial-write →
                     clean-re-route path.
- ``handoff-delay``— engine/worker.py: sleep `value` s before shipping a
                     KV handoff payload (a slow/congested transfer link;
                     widens the mid-handoff kill window).
- ``worker-exit``  — engine/worker.py: the worker process dies
                     (os._exit). The VALUE selects the death site, so a
                     drill can target one handoff phase exactly:
                     ``0`` → op intake (queued/mid-prefill death),
                     ``1`` → payload fetch (mid-handoff death),
                     ``>= 2`` → after forwarding `value` tokens of a
                     decode stream (mid-decode death).

The injector is intentionally module-shared: a supervised restart builds
a *fresh* engine, and a one-shot fault (``@1``) must stay spent across
that restart or the chaos tests could never observe recovery.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

POINTS = frozenset(
    {"step-stall", "slow-step", "alloc-fail", "prefill-error",
     "tokenizer-error", "kv-handoff-drop", "handoff-delay", "worker-exit"}
)

# Valid ":tier=" targets (the disaggregated worker tiers, engine/worker.py).
TIERS = ("prefill", "decode")

ENV_VAR = "POLYKEY_FAULTS"


@dataclass
class _Fault:
    value: float = 1.0
    remaining: Optional[int] = None  # None → unlimited
    fired: int = 0
    replica: Optional[int] = None    # None → fires on every replica
    tier: Optional[str] = None       # None → fires on every tier


class FaultInjector:
    """Parsed POLYKEY_FAULTS spec with thread-safe fire accounting
    (points are consumed from the engine thread AND gRPC handler
    threads)."""

    def __init__(self, spec: str):
        self._lock = threading.Lock()
        # One point can carry SEVERAL entries (e.g. the same fault
        # targeted at two different replicas) — keyed by name alone they
        # would silently overwrite and a two-replica chaos spec would
        # only ever kill one.
        self._faults: dict[str, list[_Fault]] = {}
        for raw in spec.replace(";", ",").split(","):
            entry = raw.strip()
            if not entry:
                continue
            replica: Optional[int] = None
            tier: Optional[str] = None
            while ":" in entry:
                # Trailing qualifiers, rightmost first: ":replica=N"
                # targets one pool replica, ":tier=prefill|decode" one
                # disaggregated worker tier; they compose in any order.
                entry, target = entry.rsplit(":", 1)
                key, _, value_s = target.partition("=")
                key = key.strip()
                if key == "replica":
                    replica = int(value_s)
                elif key == "tier":
                    tier = value_s.strip()
                    if tier not in TIERS:
                        raise ValueError(
                            f"unknown fault tier {tier!r}; valid tiers: "
                            f"{', '.join(TIERS)}"
                        )
                else:
                    raise ValueError(
                        f"unknown fault qualifier {target!r}; only "
                        "':replica=N' and ':tier=prefill|decode' are "
                        "supported"
                    )
            count: Optional[int] = None
            if "@" in entry:
                entry, count_s = entry.rsplit("@", 1)
                count = int(count_s)
            value = 1.0
            if "=" in entry:
                entry, value_s = entry.split("=", 1)
                value = float(value_s)
            name = entry.strip()
            if name not in POINTS:
                raise ValueError(
                    f"unknown fault point {name!r}; valid points: "
                    f"{', '.join(sorted(POINTS))}"
                )
            self._faults.setdefault(name, []).append(_Fault(
                value=value, remaining=count, replica=replica, tier=tier
            ))

    def _take(self, point: str, replica: Optional[int] = None,
              tier: Optional[str] = None) -> Optional[float]:
        """Consume one firing of `point` — the first armed entry whose
        replica AND tier targets match; returns its value, or None when
        the point is unarmed, exhausted, or targeted elsewhere (`replica`
        / `tier` are the caller's identity; callers that pass None only
        consume faults untargeted on that axis)."""
        with self._lock:
            for fault in self._faults.get(point, ()):
                if fault.remaining == 0:
                    continue
                if fault.replica is not None and replica != fault.replica:
                    continue
                if fault.tier is not None and tier != fault.tier:
                    continue
                if fault.remaining is not None:
                    fault.remaining -= 1
                fault.fired += 1
                return fault.value
            return None

    def take_if(self, point: str, pred, replica: Optional[int] = None,
                tier: Optional[str] = None) -> Optional[float]:
        """Like `_take`, but only consumes an armed entry whose VALUE
        satisfies `pred` — the worker-exit site selector (a fetch-site
        kill must not be eaten by the intake site it passes first)."""
        with self._lock:
            for fault in self._faults.get(point, ()):
                if fault.remaining == 0:
                    continue
                if fault.replica is not None and replica != fault.replica:
                    continue
                if fault.tier is not None and tier != fault.tier:
                    continue
                if not pred(fault.value):
                    continue
                if fault.remaining is not None:
                    fault.remaining -= 1
                fault.fired += 1
                return fault.value
            return None

    def maybe_sleep(self, point: str, replica: Optional[int] = None,
                    tier: Optional[str] = None) -> None:
        """Sleep the point's value (seconds) if it fires. Sleeping stands
        in for a wedged/slow device call, so it deliberately blocks the
        calling thread exactly where the real stall would."""
        value = self._take(point, replica, tier)
        if value is not None and value > 0:
            time.sleep(value)

    def maybe_raise(self, point: str, exc_type: type = RuntimeError,
                    replica: Optional[int] = None,
                    tier: Optional[str] = None) -> None:
        if self._take(point, replica, tier) is not None:
            raise exc_type(f"injected fault: {point}")

    def fired(self, point: str) -> int:
        with self._lock:
            return sum(f.fired for f in self._faults.get(point, ()))


_injector: Optional[FaultInjector] = None
_initialized = False
_guard = threading.Lock()


def get_injector() -> Optional[FaultInjector]:
    """The shared injector, lazily built from POLYKEY_FAULTS on first
    call. Returns None (and caches the None) when the env var is unset —
    the zero-overhead guarantee call sites rely on."""
    global _injector, _initialized
    with _guard:
        if not _initialized:
            _initialized = True
            spec = os.environ.get(ENV_VAR, "")
            if spec:
                _injector = FaultInjector(spec)
        return _injector


def install(spec: str) -> FaultInjector:
    """Programmatic arm (tests): replaces the shared injector."""
    global _injector, _initialized
    with _guard:
        _injector = FaultInjector(spec)
        _initialized = True
        return _injector


def clear() -> None:
    """Disarm and forget: the next get_injector() re-reads the env."""
    global _injector, _initialized
    with _guard:
        _injector = None
        _initialized = False
