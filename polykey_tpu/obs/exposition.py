"""Prometheus exposition endpoint + the engine scrape collector.

A stdlib ``http.server`` thread on the gateway (no new dependencies, no
asyncio) serving:

- ``GET /metrics`` — the registry's full text page;
- ``GET /healthz`` — 200 "ok" (container-level liveness probes that
  can't speak gRPC health).

The engine collector snapshots `InferenceEngine` state at scrape time —
no background sampler, no per-step bookkeeping beyond what
`EngineMetrics` already does.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .prometheus import (
    CONTENT_TYPE,
    Registry,
    render_counter,
    render_gauge,
    render_header,
    render_histogram,
    render_sample,
)


def engine_collector(engine_or_provider):
    """Scrape-time collector over a live InferenceEngine: counters and
    gauges come from `engine.stats()` (the engine's public surface, so a
    rename of its internals can't 500 the scrape); the latency families
    read `engine.metrics.ttft_hist` / `.itl_hist` directly — those two
    attributes are part of EngineMetrics' public contract (this collector
    and the snapshot percentiles both depend on them). Registered once
    per engine via `Registry.register_collector`.

    Accepts either an engine or a zero-arg provider returning one — a
    supervised restart (engine/supervisor.py) swaps the live engine out
    from under the registry, and the scrape must follow to the fresh
    instance instead of reading the corpse forever."""

    def collect() -> list[str]:
        engine = (
            engine_or_provider()
            if callable(engine_or_provider) else engine_or_provider
        )
        snap = engine.stats()
        lines: list[str] = []
        lines += render_counter(
            "polykey_requests_admitted_total",
            "Requests accepted into the engine queue.",
            snap["requests_admitted"],
        )
        lines += render_counter(
            "polykey_requests_completed_total",
            "Requests finished successfully.", snap["requests_completed"],
        )
        lines += render_counter(
            "polykey_requests_failed_total",
            "Requests finished with an error (includes cancellations: "
            "stop-sequence matches and client disconnects).",
            snap["requests_failed"],
        )
        lines += render_counter(
            "polykey_requests_shed_total",
            "Requests rejected at admission (queue bound or "
            "estimated-delay check) with RESOURCE_EXHAUSTED.",
            snap["requests_shed"],
        )
        # One family, one sample per expiry phase: queued (dropped at
        # dequeue, never prefilled), prefill (mid-chunked-prefill),
        # decode (block-boundary drop).
        lines += render_header(
            "polykey_deadline_expired_total",
            "Requests dropped because their deadline passed, by phase.",
            "counter",
        )
        for phase in ("queued", "prefill", "decode"):
            lines.append(render_sample(
                "polykey_deadline_expired_total", {"phase": phase},
                snap[f"deadline_expired_{phase}"],
            ))
        lines += render_counter(
            "polykey_decode_tokens_total",
            "Tokens emitted by the decode loop.", snap["tokens_generated"],
        )
        lines += render_counter(
            "polykey_decode_steps_total",
            "Decode blocks processed.", snap["decode_steps"],
        )
        lines += render_gauge(
            "polykey_active_requests",
            "Requests currently holding a decode slot.", snap["slots_busy"],
        )
        lines += render_gauge(
            "polykey_queue_depth",
            "Requests waiting for admission.", snap["queued"],
        )
        lines += render_gauge(
            "polykey_pages_free",
            "Free KV pages in the block allocator.", snap["pages_free"],
        )
        lines += render_gauge(
            "polykey_pages_total",
            "Total KV pages in the pool.", snap["pages_total"],
        )
        lines += render_gauge(
            "polykey_tokens_per_sec",
            "Decode throughput over the last ~1s window.",
            snap["tokens_per_sec"],
        )
        # Occupancy tracker (ISSUE 4): measured live-lane accounting —
        # the counters avg_lanes derives from (lane_steps / steps), the
        # EWMA "now" gauge, and the per-block distribution. These are
        # what replaces avg_lanes_source: "assumed_full" in roofline
        # grading.
        lines += render_counter(
            "polykey_dispatched_blocks_total",
            "Decode blocks / spec rounds dispatched.",
            snap["blocks_dispatched"],
        )
        lines += render_counter(
            "polykey_dispatched_steps_total",
            "Device decode steps dispatched (spec rounds weigh gamma+1).",
            snap["steps_dispatched"],
        )
        lines += render_counter(
            "polykey_lane_steps_total",
            "Live-lane-steps dispatched (sum of lanes x steps per block); "
            "divided by polykey_dispatched_steps_total gives measured "
            "average occupancy.",
            snap["lane_steps"],
        )
        lines += render_gauge(
            "polykey_live_lanes",
            "EWMA of live decode lanes per dispatched block.",
            snap["lanes_ewma"],
        )
        lines += render_gauge(
            "polykey_decode_slots",
            "Configured decode slots (occupancy denominator).",
            snap["slots_total"],
        )
        lines += render_counter(
            "polykey_prefill_tokens_total",
            "Prefill tokens dispatched (bucket groups + chunks).",
            snap["prefill_tokens_total"],
        )
        lines += render_gauge(
            "polykey_prefill_interleave_max_tokens",
            "Worst single-iteration prefill injection while decode lanes "
            "were live (bounded by the prefill budget + one dispatch).",
            snap["interleave_max_tokens"],
        )
        # polylint: disable=PL007(lanes are a unitless count, not a ms/bytes quantity)
        lines += render_histogram(
            "polykey_live_lanes_per_block",
            "Live decode lanes at block dispatch.",
            engine.metrics.lanes_hist,
        )
        # Lookahead dispatch pipeline (ISSUE 6): how deep the dispatch
        # frontier runs ahead of the processed frontier, and what the
        # host pays when it fails to — a host_stall_ms p50 near the
        # device roundtrip means decode is host-bound (DEPLOY.md
        # "diagnosing host-bound decode").
        lines += render_gauge(
            "polykey_dispatch_inflight",
            "Decode blocks dispatched but not yet processed (the "
            "in-flight lookahead queue).",
            snap["inflight_blocks"],
        )
        lines += render_gauge(
            "polykey_dispatch_lookahead_depth",
            "Configured lookahead depth (POLYKEY_DISPATCH_LOOKAHEAD; "
            "1 = synchronous dispatch-then-read).",
            snap["lookahead_depth"],
        )
        lines += render_histogram(
            "polykey_host_stall_ms",
            "Time _process_step blocked waiting for a block's D2H "
            "readback to land, ms (~0 when the lookahead pipeline hides "
            "the roundtrip).",
            engine.metrics.host_stall_hist,
        )
        lines += render_histogram(
            "polykey_ttft_ms",
            "Time to first token (enqueue to first emit), ms.",
            engine.metrics.ttft_hist,
        )
        lines += render_histogram(
            "polykey_itl_ms",
            "Inter-token gap, ms (per decode block, amortized per token).",
            engine.metrics.itl_hist,
        )
        if snap.get("drafts_proposed"):
            lines += render_counter(
                "polykey_spec_drafts_proposed_total",
                "Speculative draft tokens proposed.",
                snap["drafts_proposed"],
            )
            lines += render_counter(
                "polykey_spec_drafts_accepted_total",
                "Speculative draft tokens accepted.",
                snap["drafts_accepted"],
            )
        return lines

    return collect


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = None  # set by MetricsHTTPServer subclassing

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            try:
                body = self.registry.render().encode()
            except Exception as e:  # a broken collector must not 500 opaquely
                self.send_response(500)
                self.end_headers()
                self.wfile.write(f"collector error: {e}\n".encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.end_headers()
            self.wfile.write(b"ok\n")
        else:
            self.send_response(404)
            self.end_headers()
            self.wfile.write(b"try /metrics\n")

    def log_message(self, *args) -> None:
        pass  # scrapes are high-frequency noise; the JSON log stays clean


class MetricsHTTPServer:
    """Daemon-thread exposition server. `port=0` binds an ephemeral port
    (tests / smoke); `.port` reports the bound one."""

    def __init__(self, registry: Registry, host: str = "0.0.0.0",
                 port: int = 9464):
        handler = type("BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="polykey-metrics",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
