"""Prometheus exposition endpoint + the engine scrape collector.

A stdlib ``http.server`` thread on the gateway (no new dependencies, no
asyncio) serving:

- ``GET /metrics`` — the registry's full text page;
- ``GET /healthz`` — 200 "ok" (container-level liveness probes that
  can't speak gRPC health).

The engine collector snapshots `InferenceEngine` state at scrape time —
no background sampler, no per-step bookkeeping beyond what
`EngineMetrics` already does.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .prometheus import (
    CONTENT_TYPE,
    Registry,
    render_counter,
    render_gauge,
    render_header,
    render_sample,
)


# The engine metric families, rendered from `engine.stats()` snapshots
# (counters/gauges) and the EngineMetrics histograms. One table serves
# BOTH exposition shapes: a bare engine renders unlabeled samples
# (byte-compatible with the pre-pool page), a replica pool renders one
# sample per replica with a {replica="i"} label — same family names, so
# dashboards survive turning the pool on. kind ∈ {counter, gauge,
# hist}; `key` indexes the stats snapshot, hist entries name the
# EngineMetrics attribute instead.
_ENGINE_FAMILIES: tuple = (
    ("counter", "polykey_requests_admitted_total",
     "Requests accepted into the engine queue.", "requests_admitted"),
    ("counter", "polykey_requests_completed_total",
     "Requests finished successfully.", "requests_completed"),
    ("counter", "polykey_requests_failed_total",
     "Requests finished with an error (includes cancellations: "
     "stop-sequence matches and client disconnects).", "requests_failed"),
    ("counter", "polykey_requests_shed_total",
     "Requests rejected at admission (queue bound or "
     "estimated-delay check) with RESOURCE_EXHAUSTED.", "requests_shed"),
    # One family, one sample per expiry phase: queued (dropped at
    # dequeue, never prefilled), prefill (mid-chunked-prefill),
    # decode (block-boundary drop).
    ("phases", "polykey_deadline_expired_total",
     "Requests dropped because their deadline passed, by phase.", None),
    ("counter", "polykey_decode_tokens_total",
     "Tokens emitted by the decode loop.", "tokens_generated"),
    ("counter", "polykey_decode_steps_total",
     "Decode blocks processed.", "decode_steps"),
    ("gauge", "polykey_active_requests",
     "Requests currently holding a decode slot.", "slots_busy"),
    ("gauge", "polykey_queue_depth",
     "Requests waiting for admission.", "queued"),
    ("gauge", "polykey_pages_free",
     "Free KV pages in the block allocator.", "pages_free"),
    ("gauge", "polykey_pages_total",
     "Total KV pages in the pool.", "pages_total"),
    ("gauge", "polykey_tokens_per_sec",
     "Decode throughput over the last ~1s window.", "tokens_per_sec"),
    # Occupancy tracker (ISSUE 4): measured live-lane accounting — the
    # counters avg_lanes derives from (lane_steps / steps), the EWMA
    # "now" gauge, and the per-block distribution.
    ("counter", "polykey_dispatched_blocks_total",
     "Decode blocks / spec rounds dispatched.", "blocks_dispatched"),
    ("counter", "polykey_dispatched_steps_total",
     "Device decode steps dispatched (spec rounds weigh gamma+1).",
     "steps_dispatched"),
    ("counter", "polykey_lane_steps_total",
     "Live-lane-steps dispatched (sum of lanes x steps per block); "
     "divided by polykey_dispatched_steps_total gives measured "
     "average occupancy.", "lane_steps"),
    ("gauge", "polykey_live_lanes",
     "EWMA of live decode lanes per dispatched block.", "lanes_ewma"),
    ("gauge", "polykey_decode_slots",
     "Configured decode slots (occupancy denominator).", "slots_total"),
    ("counter", "polykey_prefill_tokens_total",
     "Prefill tokens dispatched (bucket groups + chunks).",
     "prefill_tokens_total"),
    ("gauge", "polykey_prefill_interleave_max_tokens",
     "Worst single-iteration prefill injection while decode lanes "
     "were live (bounded by the prefill budget + one dispatch).",
     "interleave_max_tokens"),
    ("hist", "polykey_live_lanes_per_block",
     "Live decode lanes at block dispatch.", "lanes_hist"),
    # Lookahead dispatch pipeline (ISSUE 6): how deep the dispatch
    # frontier runs ahead of the processed frontier, and what the host
    # pays when it fails to (DEPLOY.md "diagnosing host-bound decode").
    ("gauge", "polykey_dispatch_inflight",
     "Decode blocks dispatched but not yet processed (the "
     "in-flight lookahead queue).", "inflight_blocks"),
    ("gauge", "polykey_dispatch_lookahead_depth",
     "Configured lookahead depth (POLYKEY_DISPATCH_LOOKAHEAD; "
     "1 = synchronous dispatch-then-read).", "lookahead_depth"),
    ("hist", "polykey_host_stall_ms",
     "Time _process_step blocked waiting for a block's D2H "
     "readback to land, ms (~0 when the lookahead pipeline hides "
     "the roundtrip).", "host_stall_hist"),
    ("hist", "polykey_ttft_ms",
     "Time to first token (enqueue to first emit), ms.", "ttft_hist"),
    ("hist", "polykey_itl_ms",
     "Inter-token gap, ms (per decode block, amortized per token).",
     "itl_hist"),
)

_SPEC_FAMILIES: tuple = (
    ("polykey_spec_drafts_proposed_total",
     "Speculative draft tokens proposed.", "drafts_proposed"),
    ("polykey_spec_drafts_accepted_total",
     "Speculative draft tokens accepted.", "drafts_accepted"),
)


def _histogram_samples(name: str, labels: dict, hist) -> list[str]:
    """One label-set's samples of a histogram family (header emitted
    once by the caller — the text format forbids repeating it)."""
    snap = hist.snapshot()
    lines = []
    for bound, cumulative in snap["buckets"]:
        lines.append(render_sample(
            f"{name}_bucket", {**labels, "le": f"{bound:g}"}, cumulative
        ))
    lines.append(render_sample(
        f"{name}_bucket", {**labels, "le": "+Inf"}, snap["inf"]
    ))
    lines.append(render_sample(f"{name}_sum", labels, snap["sum"]))
    lines.append(render_sample(f"{name}_count", labels, snap["count"]))
    return lines


def _pool_lines(pool, members: list) -> list[str]:
    """Pool-tier families (ISSUE 9): replica lifecycle states and the
    failover/router counters. `members` is [(labels, engine, snap)]."""
    from ..engine.replica_pool import STATES  # lazy: obs must not import engine at module load

    stats = pool.stats()
    lines = render_header(
        "polykey_replica_state",
        "Replica lifecycle (1 for the replica's current state; states: "
        + ", ".join(STATES) + ").",
        "gauge",
    )
    states = stats.get("replica_states", {})
    for index in sorted(states, key=int):
        for state in STATES:
            lines.append(render_sample(
                "polykey_replica_state",
                {"replica": index, "state": state},
                1 if states[index] == state else 0,
            ))
    lines += render_gauge(
        "polykey_replicas_serving",
        "Replicas currently in SERVING state.",
        stats.get("replicas_serving", 0),
    )
    lines += render_counter(
        "polykey_requests_rerouted_total",
        "Requests moved to another replica after an engine-lifecycle "
        "failure (queued moves are lossless; mid-stream moves resume).",
        stats.get("requests_rerouted", 0),
    )
    lines += render_counter(
        "polykey_streams_resumed_total",
        "Mid-stream requests resumed on another replica with "
        "already-emitted tokens suppressed.",
        stats.get("streams_resumed", 0),
    )
    lines += render_header(
        "polykey_router_decisions_total",
        "Routing decisions by dominant reason (prefix-hit / least-delay "
        "/ headroom).",
        "counter",
    )
    for reason, count in sorted(stats.get("router_decisions", {}).items()):
        lines.append(render_sample(
            "polykey_router_decisions_total", {"reason": reason}, count
        ))
    return lines


def engine_collector(engine_or_provider):
    """Scrape-time collector over a live InferenceEngine OR a
    ReplicaPool: counters and gauges come from `stats()` snapshots (the
    public surface, so a rename of engine internals can't 500 the
    scrape); the latency families read the EngineMetrics histograms
    directly — part of its public contract. A pool renders every engine
    family once per replica with a ``replica`` label plus the pool-tier
    families (replica_state, rerouted/resumed, router decisions); a bare
    engine renders the exact unlabeled page it always has.

    Accepts either the object or a zero-arg provider returning one — a
    supervised restart (engine/supervisor.py) swaps the live engine out
    from under the registry, and the scrape must follow to the fresh
    instance instead of reading the corpse forever."""

    def collect() -> list[str]:
        target = (
            engine_or_provider()
            if callable(engine_or_provider) else engine_or_provider
        )
        pool = target if hasattr(target, "replicas") else None
        if pool is not None:
            members = [
                ({"replica": str(rep.index)}, rep.engine, rep.engine.stats())
                for rep in pool.replicas
            ]
        else:
            members = [({}, target, target.stats())]
        lines: list[str] = []
        for kind, name, help_text, key in _ENGINE_FAMILIES:
            if kind == "phases":
                lines += render_header(name, help_text, "counter")
                for labels, _engine, snap in members:
                    for phase in ("queued", "prefill", "decode"):
                        lines.append(render_sample(
                            name, {**labels, "phase": phase},
                            snap[f"deadline_expired_{phase}"],
                        ))
            elif kind == "hist":
                lines += render_header(name, help_text, "histogram")
                for labels, engine, _snap in members:
                    lines += _histogram_samples(
                        name, labels, getattr(engine.metrics, key)
                    )
            else:
                lines += render_header(name, help_text, kind)
                for labels, _engine, snap in members:
                    lines.append(render_sample(name, labels, snap[key]))
        if any(snap.get("drafts_proposed") for _, _, snap in members):
            for name, help_text, key in _SPEC_FAMILIES:
                lines += render_header(name, help_text, "counter")
                for labels, _engine, snap in members:
                    if snap.get("drafts_proposed"):
                        lines.append(render_sample(name, labels, snap[key]))
        if pool is not None:
            lines += _pool_lines(pool, members)
        return lines

    return collect


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = None  # set by MetricsHTTPServer subclassing

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            try:
                body = self.registry.render().encode()
            except Exception as e:  # a broken collector must not 500 opaquely
                self.send_response(500)
                self.end_headers()
                self.wfile.write(f"collector error: {e}\n".encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.end_headers()
            self.wfile.write(b"ok\n")
        else:
            self.send_response(404)
            self.end_headers()
            self.wfile.write(b"try /metrics\n")

    def log_message(self, *args) -> None:
        pass  # scrapes are high-frequency noise; the JSON log stays clean


class MetricsHTTPServer:
    """Daemon-thread exposition server. `port=0` binds an ephemeral port
    (tests / smoke); `.port` reports the bound one."""

    def __init__(self, registry: Registry, host: str = "0.0.0.0",
                 port: int = 9464):
        handler = type("BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="polykey-metrics",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
