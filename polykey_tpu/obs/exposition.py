"""Prometheus exposition endpoint + the engine scrape collector.

A stdlib ``http.server`` thread on the gateway (no new dependencies, no
asyncio) serving:

- ``GET /metrics`` — the registry's full text page. A scraper sending
  ``Accept: application/openmetrics-text`` gets the OpenMetrics
  rendering: same families plus per-bucket exemplars carrying the
  ``trace_id`` of a recent request in that bucket (TTFT / ITL /
  host-stall / device-ms), so a p99 bucket links straight to its
  recorded span tree in the flight recorder.
- ``GET /healthz`` — 200 "ok" (container-level liveness probes that
  can't speak gRPC health).
- ``GET /debug/*`` — the read-only flight-deck surface (ISSUE 10),
  served ONLY while ``POLYKEY_DEBUG_ENDPOINTS=1``: engine stats JSON,
  the Perfetto timeline export, the flight recorder, a single trace by
  id, and the single-flight profiler trigger. See `DebugSurface`.

The engine collector snapshots `InferenceEngine` state at scrape time —
no background sampler, no per-step bookkeeping beyond what
`EngineMetrics` already does.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from .prometheus import (
    CONTENT_TYPE,
    CONTENT_TYPE_OPENMETRICS,
    Registry,
    render_counter,
    render_gauge,
    render_header,
    render_histogram_samples,
    render_sample,
)


# The engine metric families, rendered from `engine.stats()` snapshots
# (counters/gauges) and the EngineMetrics histograms. One table serves
# BOTH exposition shapes: a bare engine renders unlabeled samples
# (byte-compatible with the pre-pool page), a replica pool renders one
# sample per replica with a {replica="i"} label — same family names, so
# dashboards survive turning the pool on. kind ∈ {counter, gauge,
# hist}; `key` indexes the stats snapshot, hist entries name the
# EngineMetrics attribute instead.
_ENGINE_FAMILIES: tuple = (
    ("counter", "polykey_requests_admitted_total",
     "Requests accepted into the engine queue.", "requests_admitted"),
    ("counter", "polykey_requests_completed_total",
     "Requests finished successfully.", "requests_completed"),
    ("counter", "polykey_requests_failed_total",
     "Requests finished with an error (includes cancellations: "
     "stop-sequence matches and client disconnects).", "requests_failed"),
    ("counter", "polykey_requests_shed_total",
     "Requests rejected at admission (queue bound or "
     "estimated-delay check) with RESOURCE_EXHAUSTED.", "requests_shed"),
    # One family, one sample per expiry phase: queued (dropped at
    # dequeue, never prefilled), prefill (mid-chunked-prefill),
    # decode (block-boundary drop).
    ("phases", "polykey_deadline_expired_total",
     "Requests dropped because their deadline passed, by phase.", None),
    ("counter", "polykey_decode_tokens_total",
     "Tokens emitted by the decode loop.", "tokens_generated"),
    ("counter", "polykey_decode_steps_total",
     "Decode blocks processed.", "decode_steps"),
    ("gauge", "polykey_active_requests",
     "Requests currently holding a decode slot.", "slots_busy"),
    ("gauge", "polykey_queue_depth",
     "Requests waiting for admission.", "queued"),
    ("gauge", "polykey_pages_free",
     "Free KV pages in the block allocator.", "pages_free"),
    ("gauge", "polykey_pages_total",
     "Total KV pages in the pool.", "pages_total"),
    ("gauge", "polykey_tokens_per_sec",
     "Decode throughput over the last ~1s window.", "tokens_per_sec"),
    # Occupancy tracker (ISSUE 4): measured live-lane accounting — the
    # counters avg_lanes derives from (lane_steps / steps), the EWMA
    # "now" gauge, and the per-block distribution.
    ("counter", "polykey_dispatched_blocks_total",
     "Decode blocks / spec rounds dispatched.", "blocks_dispatched"),
    ("counter", "polykey_dispatched_steps_total",
     "Device decode steps dispatched (spec rounds weigh gamma+1).",
     "steps_dispatched"),
    ("counter", "polykey_lane_steps_total",
     "Live-lane-steps dispatched (sum of lanes x steps per block); "
     "divided by polykey_dispatched_steps_total gives measured "
     "average occupancy.", "lane_steps"),
    ("gauge", "polykey_live_lanes",
     "EWMA of live decode lanes per dispatched block.", "lanes_ewma"),
    ("gauge", "polykey_decode_slots",
     "Configured decode slots (occupancy denominator).", "slots_total"),
    ("counter", "polykey_prefill_tokens_total",
     "Prefill tokens dispatched (bucket groups + chunks).",
     "prefill_tokens_total"),
    ("gauge", "polykey_prefill_interleave_max_tokens",
     "Worst single-iteration prefill injection while decode lanes "
     "were live (bounded by the prefill budget + one dispatch).",
     "interleave_max_tokens"),
    ("hist", "polykey_live_lanes_per_block",
     "Live decode lanes at block dispatch.", "lanes_hist"),
    # Lookahead dispatch pipeline (ISSUE 6): how deep the dispatch
    # frontier runs ahead of the processed frontier, and what the host
    # pays when it fails to (DEPLOY.md "diagnosing host-bound decode").
    ("gauge", "polykey_dispatch_inflight",
     "Decode blocks dispatched but not yet processed (the "
     "in-flight lookahead queue).", "inflight_blocks"),
    ("gauge", "polykey_dispatch_lookahead_depth",
     "Configured lookahead depth (POLYKEY_DISPATCH_LOOKAHEAD; "
     "1 = synchronous dispatch-then-read).", "lookahead_depth"),
    ("hist", "polykey_host_stall_ms",
     "Time _process_step blocked waiting for a block's D2H "
     "readback to land, ms (~0 when the lookahead pipeline hides "
     "the roundtrip).", "host_stall_hist"),
    # Device-time attribution (ISSUE 10): per-block device-busy
    # (dispatch gap minus host stall) apportioned to the lanes live in
    # that block, accumulated per request — wall time split into
    # device vs host from the recorded schedule.
    ("gauge", "polykey_device_busy_fraction",
     "Fraction of inter-dispatch wall time attributed to device "
     "compute (cumulative: device-busy ms / dispatch-gap ms).",
     "device_busy_fraction"),
    ("hist", "polykey_request_device_ms",
     "Per-request device time, ms: each block's device-busy window "
     "(dispatch gap minus host stall) split across its live lanes.",
     "device_ms_hist"),
    ("hist", "polykey_ttft_ms",
     "Time to first token (enqueue to first emit), ms.", "ttft_hist"),
    ("hist", "polykey_itl_ms",
     "Inter-token gap, ms (per decode block, amortized per token).",
     "itl_hist"),
    # Host-memory KV tier (ISSUE 15): cold-page offload/restore
    # accounting. Families render (at 0) on tier-less engines too, so
    # dashboards exist before the tier is turned on.
    ("kvfaults", "polykey_kv_page_faults_total",
     "Prefix-cache hits on HOST-resident pages, by kind: prefix "
     "(sticky short-prompt session resuming off spilled pages), ctx "
     "(a long-context prompt's middle pages paging back in).", None),
    ("counter", "polykey_kv_pages_evicted_total",
     "Cold pages spilled from the device pool to the host tier.",
     "kv_pages_evicted"),
    ("gauge", "polykey_kv_host_pages",
     "KV pages currently resident in the host tier.", "kv_host_pages"),
    ("gauge", "polykey_kv_device_pages",
     "Device pool pages in use by slots/prefix cache (reserved "
     "garbage page excluded).", "kv_device_pages"),
    ("hist", "polykey_kv_restore_ms",
     "Per-fault restore latency, ms: host gather + upload + scatter "
     "dispatch for one faulting slot's pages.", "kv_restore_hist"),
)

_SPEC_FAMILIES: tuple = (
    ("polykey_spec_drafts_proposed_total",
     "Speculative draft tokens proposed.", "drafts_proposed"),
    ("polykey_spec_drafts_accepted_total",
     "Speculative draft tokens accepted.", "drafts_accepted"),
)


# One label-set's samples of a histogram family (header emitted once by
# the caller); exemplar rendering lives in the shared prometheus helper.
_histogram_samples = render_histogram_samples


def _pool_lines(pool, members: list) -> list[str]:
    """Pool-tier families (ISSUE 9): replica lifecycle states and the
    failover/router counters. `members` is [(labels, engine, snap)]."""
    from ..engine.replica_pool import STATES  # lazy: obs must not import engine at module load

    stats = pool.stats()
    lines = render_header(
        "polykey_replica_state",
        "Replica lifecycle (1 for the replica's current state; states: "
        + ", ".join(STATES) + ").",
        "gauge",
    )
    states = stats.get("replica_states", {})
    for index in sorted(states, key=int):
        for state in STATES:
            lines.append(render_sample(
                "polykey_replica_state",
                {"replica": index, "state": state},
                1 if states[index] == state else 0,
            ))
    lines += render_gauge(
        "polykey_replicas_serving",
        "Replicas currently in SERVING state.",
        stats.get("replicas_serving", 0),
    )
    lines += render_counter(
        "polykey_requests_rerouted_total",
        "Requests moved to another replica after an engine-lifecycle "
        "failure (queued moves are lossless; mid-stream moves resume).",
        stats.get("requests_rerouted", 0),
    )
    lines += render_counter(
        "polykey_streams_resumed_total",
        "Mid-stream requests resumed on another replica with "
        "already-emitted tokens suppressed.",
        stats.get("streams_resumed", 0),
    )
    lines += render_header(
        "polykey_router_decisions_total",
        "Routing decisions by dominant reason (prefix-hit / least-delay "
        "/ headroom).",
        "counter",
    )
    for reason, count in sorted(stats.get("router_decisions", {}).items()):
        lines.append(render_sample(
            "polykey_router_decisions_total", {"reason": reason}, count
        ))
    return lines


def _slo_lines(members: list) -> list[str]:
    """SLO signal-plane families (ISSUE 11), rendered from each
    engine's cached last evaluation (`SignalPlane.slo_state()` — the
    scrape never recomputes window math). Headers render whenever any
    member carries a plane, so dashboards and the exposition-under-
    churn gate see the families even before a policy is loaded; samples
    appear per objective once a policy evaluates."""
    states = []
    for labels, engine, _snap in members:
        plane = getattr(engine.metrics, "signals", None)
        if plane is not None:
            states.append((labels, plane.slo_state()))
    if not states:
        return []
    lines = render_header(
        "polykey_slo_budget_remaining_ratio",
        "Error budget remaining over the longest window, per objective "
        "(1 = untouched, 0 = exhausted).",
        "gauge",
    )
    for labels, state in states:
        for name in sorted(state):
            lines.append(render_sample(
                "polykey_slo_budget_remaining_ratio",
                {**labels, "objective": name},
                state[name]["budget_remaining"],
            ))
    lines += render_header(
        "polykey_slo_burn_rate",
        "Error-budget burn rate per objective and window (1 = burning "
        "exactly at the objective's allowance; >1 exhausts early).",
        "gauge",
    )
    for labels, state in states:
        for name in sorted(state):
            for window, burn in sorted(state[name]["burn_rate"].items()):
                if burn is None:
                    continue    # window carried no evidence: no sample
                lines.append(render_sample(
                    "polykey_slo_burn_rate",
                    {**labels, "objective": name, "window": window},
                    burn,
                ))
    lines += render_header(
        "polykey_slo_breaches_total",
        "Burn-threshold crossings per objective (breach events; each "
        "also lands on the timeline and flight recorder).",
        "counter",
    )
    for labels, state in states:
        for name in sorted(state):
            lines.append(render_sample(
                "polykey_slo_breaches_total",
                {**labels, "objective": name},
                state[name]["breaches"],
            ))
    return lines


class _WireHist:
    """Histogram stand-in over bucket counts shipped from a worker
    process (DisaggPool stats `_hists` entries): render-compatible with
    `render_histogram_samples` without a live Histogram object in this
    process. Exemplars don't cross the control plane (None)."""

    def __init__(self, spec: dict):
        self._bounds = list(spec.get("bounds", ()))
        self._counts = list(spec.get("counts", ()))
        self._sum = float(spec.get("sum", 0.0))

    def snapshot(self) -> dict:
        cumulative = []
        running = 0
        for bound, count in zip(self._bounds, self._counts[:-1]):
            running += count
            cumulative.append((bound, running))
        total = running + (self._counts[-1] if self._counts else 0)
        return {"buckets": cumulative, "inf": total, "sum": self._sum,
                "count": total}

    def exemplars(self):
        return None


# Worker-histogram keys shipped over the control plane → the engine
# family they render as.
_DISAGG_HISTS = {"polykey_ttft_ms": "ttft_ms", "polykey_itl_ms": "itl_ms"}


def _disagg_lines(pool) -> list[str]:
    """Exposition for a DisaggPool (ISSUE 13): every engine family
    rendered once per WORKER with {tier, replica} labels (the per-tier
    labels on the PR 7 replica families), the replica-state machine
    keyed by tier, and the coordinator-owned handoff families. Worker
    snapshots come from the pool's cached control-plane stats — a dead
    worker's last snapshot keeps rendering (counters are monotonic),
    its state gauge tells the truth."""
    from ..engine.replica_pool import STATES  # lazy: obs must not import engine at module load

    stats = pool.stats()
    members = [
        ({"tier": snap.get("tier", "?"),
          "replica": str(snap.get("replica", i))}, snap)
        for i, snap in enumerate(stats.get("per_worker", ()))
    ]
    lines: list[str] = []
    for kind, name, help_text, key in _ENGINE_FAMILIES:
        if kind == "phases":
            lines += render_header(name, help_text, "counter")
            for labels, snap in members:
                for phase in ("queued", "prefill", "decode"):
                    lines.append(render_sample(
                        name, {**labels, "phase": phase},
                        snap.get(f"deadline_expired_{phase}", 0),
                    ))
        elif kind == "kvfaults":
            lines += render_header(name, help_text, "counter")
            for labels, snap in members:
                for fault_kind in ("prefix", "ctx"):
                    lines.append(render_sample(
                        name, {**labels, "kind": fault_kind},
                        snap.get(f"kv_page_faults_{fault_kind}", 0),
                    ))
        elif kind == "hist":
            if name not in _DISAGG_HISTS:
                continue    # bucket counts for these don't cross the wire
            lines += render_header(name, help_text, "histogram")
            for labels, snap in members:
                spec = (snap.get("_hists") or {}).get(_DISAGG_HISTS[name])
                if spec:
                    lines += _histogram_samples(name, labels,
                                                _WireHist(spec))
        else:
            lines += render_header(name, help_text, kind)
            for labels, snap in members:
                lines.append(render_sample(name, labels,
                                           snap.get(key, 0) or 0))
    # Worker lifecycle, tier-labeled (the state machine is shared with
    # the in-process pool — COMPONENTS.md §12/§16).
    lines += render_header(
        "polykey_replica_state",
        "Worker lifecycle (1 for the worker's current state; states: "
        + ", ".join(STATES) + ").",
        "gauge",
    )
    for name_key, state in sorted(stats.get("tier_states", {}).items()):
        tier, _, index = name_key.partition("/")
        for candidate in STATES:
            lines.append(render_sample(
                "polykey_replica_state",
                {"tier": tier, "replica": index, "state": candidate},
                1 if state == candidate else 0,
            ))
    lines += render_header(
        "polykey_replicas_serving",
        "Workers currently in SERVING state, per tier.",
        "gauge",
    )
    for tier, counts in sorted(stats.get("tiers", {}).items()):
        lines.append(render_sample(
            "polykey_replicas_serving", {"tier": tier},
            counts.get("serving", 0),
        ))
    lines += render_counter(
        "polykey_requests_rerouted_total",
        "Requests re-routed to other workers after a worker failure "
        "(any handoff phase; the re-run replays with delivered tokens "
        "suppressed).",
        stats.get("requests_rerouted", 0),
    )
    lines += render_counter(
        "polykey_streams_resumed_total",
        "Mid-stream requests resumed on another worker with "
        "already-delivered tokens suppressed.",
        stats.get("streams_resumed", 0),
    )
    # Handoff families (ISSUE 13 satellites) — coordinator-owned.
    lines += render_header(
        "polykey_handoffs_total",
        "KV handoffs by outcome: ok (decode completed), retried (one "
        "attempt re-routed), aborted (re-route budget exhausted).",
        "counter",
    )
    for outcome, count in sorted(stats.get("handoffs", {}).items()):
        lines.append(render_sample(
            "polykey_handoffs_total", {"outcome": outcome}, count,
        ))
    lines += render_counter(
        "polykey_handoff_bytes_total",
        "Serialized KV bytes fetched from the prefill tier (wire-format "
        "blobs; each decode ship re-counts nothing — this is the fetch "
        "side).",
        stats.get("handoff_bytes", 0),
    )
    lines += render_header(
        "polykey_handoff_ms",
        "End-to-end handoff latency, ms: prefill-side fetch start to "
        "decode-side accept.",
        "histogram",
    )
    lines += _histogram_samples("polykey_handoff_ms", {}, pool.handoff_ms)
    return lines


def _autopilot_lines(target) -> list[str]:
    """Controller families (ISSUE 18): empty when no autopilot is
    attached, so POLYKEY_AUTOPILOT unset leaves the page byte-identical."""
    autopilot = getattr(target, "autopilot", None)
    if autopilot is None:
        return []
    snap = autopilot.snapshot()
    lines = render_header(
        "polykey_autopilot_decisions_total",
        "Autopilot actuations by action and direction", "counter",
    )
    for key, count in snap["decisions_total"].items():
        action, _, direction = key.partition(":")
        lines.append(render_sample(
            "polykey_autopilot_decisions_total",
            {"action": action, "direction": direction}, count,
        ))
    lines += render_header(
        "polykey_autopilot_setpoint",
        "Current autopilot-managed knob setpoints", "gauge",
    )
    for name, value in sorted(snap["setpoints"].items()):
        lines.append(render_sample(
            "polykey_autopilot_setpoint", {"name": name}, value,
        ))
    lines += render_header(
        "polykey_autopilot_paused",
        "1 while the autopilot is paused for a supervised restart",
        "gauge",
    )
    lines.append(render_sample(
        "polykey_autopilot_paused", {}, int(snap["paused"]),
    ))
    return lines


def engine_collector(engine_or_provider):
    """Scrape-time collector over a live InferenceEngine OR a
    ReplicaPool: counters and gauges come from `stats()` snapshots (the
    public surface, so a rename of engine internals can't 500 the
    scrape); the latency families read the EngineMetrics histograms
    directly — part of its public contract. A pool renders every engine
    family once per replica with a ``replica`` label plus the pool-tier
    families (replica_state, rerouted/resumed, router decisions); a bare
    engine renders the exact unlabeled page it always has.

    Accepts either the object or a zero-arg provider returning one — a
    supervised restart (engine/supervisor.py) swaps the live engine out
    from under the registry, and the scrape must follow to the fresh
    instance instead of reading the corpse forever."""

    def collect() -> list[str]:
        target = (
            engine_or_provider()
            if callable(engine_or_provider) else engine_or_provider
        )
        if hasattr(target, "workers"):
            # Disaggregated pool (ISSUE 13): per-worker snapshots ride
            # the control plane; families render {tier, replica}-labeled.
            return _disagg_lines(target) + _autopilot_lines(target)
        pool = target if hasattr(target, "replicas") else None
        if pool is not None:
            members = [
                ({"replica": str(rep.index)}, rep.engine, rep.engine.stats())
                for rep in pool.replicas
            ]
        else:
            members = [({}, target, target.stats())]
        lines: list[str] = []
        for kind, name, help_text, key in _ENGINE_FAMILIES:
            if kind == "phases":
                lines += render_header(name, help_text, "counter")
                for labels, _engine, snap in members:
                    for phase in ("queued", "prefill", "decode"):
                        lines.append(render_sample(
                            name, {**labels, "phase": phase},
                            snap[f"deadline_expired_{phase}"],
                        ))
            elif kind == "kvfaults":
                lines += render_header(name, help_text, "counter")
                for labels, _engine, snap in members:
                    for fault_kind in ("prefix", "ctx"):
                        lines.append(render_sample(
                            name, {**labels, "kind": fault_kind},
                            snap.get(f"kv_page_faults_{fault_kind}", 0),
                        ))
            elif kind == "hist":
                lines += render_header(name, help_text, "histogram")
                for labels, engine, _snap in members:
                    lines += _histogram_samples(
                        name, labels, getattr(engine.metrics, key)
                    )
            else:
                lines += render_header(name, help_text, kind)
                for labels, _engine, snap in members:
                    lines.append(render_sample(name, labels, snap[key]))
        if any(snap.get("drafts_proposed") for _, _, snap in members):
            for name, help_text, key in _SPEC_FAMILIES:
                lines += render_header(name, help_text, "counter")
                for labels, _engine, snap in members:
                    if snap.get("drafts_proposed"):
                        lines.append(render_sample(name, labels, snap[key]))
        if any(snap.get("spec_gamma") is not None for _, _, snap in members):
            # Per-lane dial aggregates (ISSUE 19): gamma went per-lane,
            # so the families carry a `stat` label (mean/min/max over
            # occupied lanes) instead of pretending one global exists.
            # Present whenever spec is configured — operators watch the
            # dial BEFORE traffic proposes anything.
            for name, help_text, prefix in (
                ("polykey_spec_gamma",
                 "Per-lane speculative gamma dial, aggregated over "
                 "occupied lanes (stat: mean/min/max).", "spec_gamma"),
                ("polykey_spec_accept_rate",
                 "Per-lane draft acceptance EWMA, aggregated over "
                 "occupied lanes (stat: mean/min/max).",
                 "spec_accept_ewma"),
            ):
                lines += render_header(name, help_text, "gauge")
                for labels, _engine, snap in members:
                    if snap.get("spec_gamma") is None:
                        continue
                    for stat in ("mean", "min", "max"):
                        lines.append(render_sample(
                            name, {**labels, "stat": stat},
                            snap[f"{prefix}_{stat}"],
                        ))
        if pool is not None:
            lines += _pool_lines(pool, members)
        lines += _slo_lines(members)
        lines += _autopilot_lines(target)
        return lines

    return collect


class DebugSurface:
    """Read-only flight-deck endpoints (ISSUE 10), mounted on the
    metrics HTTP server and gated by ``POLYKEY_DEBUG_ENDPOINTS=1``:

    - ``/debug/engine``        — engine_stats snapshot as JSON
    - ``/debug/slo``           — windowed signal-plane snapshot + SLO
      burn/budget state (obs.signals.signals_snapshot; ISSUE 11)
    - ``/debug/timeline``      — Perfetto/Chrome-trace export of the
      engine timeline (one process per replica for a pool)
    - ``/debug/flight``        — flight-recorder span trees + events
    - ``/debug/trace/<id>``    — one recorded span tree by trace id
    - ``/debug/profile?seconds=N`` — blocking single-flight
      jax.profiler capture; 409 while another capture runs

    The gate is re-read per request (no enabled override), so an
    operator can flip the env on a live process without a restart being
    required for the "disabled ⇒ 404" contract to hold. Everything here
    is read-only except the profiler trigger, which writes only to its
    own artifact directory.
    """

    def __init__(self, engine_provider=None, obs=None, profiler=None,
                 enabled: Optional[bool] = None):
        self.engine_provider = engine_provider
        self.obs = obs
        self.profiler = profiler
        self.enabled = enabled          # None → read the env per request

    def _enabled_now(self) -> bool:
        if self.enabled is not None:
            return self.enabled
        return os.environ.get("POLYKEY_DEBUG_ENDPOINTS", "") == "1"

    def _engine(self):
        return self.engine_provider() if self.engine_provider else None

    def handle(self, path: str, query: str) -> tuple[int, str, bytes]:
        """Route one /debug request. Returns (status, content_type,
        body); unknown paths and the disabled state are both 404 — a
        gated-off surface must be indistinguishable from an absent one."""
        if not self._enabled_now():
            return 404, "text/plain", b"not found\n"
        try:
            return self._route(path, query)
        except Exception as e:
            # A debug endpoint must never take the metrics server down,
            # and an opaque 500 defeats its whole purpose.
            return 500, "text/plain", f"debug error: {e}\n".encode()

    def _route(self, path: str, query: str) -> tuple[int, str, bytes]:
        if path == "/debug/engine":
            engine = self._engine()
            if engine is None:
                return 404, "text/plain", b"no engine wired\n"
            return 200, "application/json", _json_bytes(engine.stats())
        if path == "/debug/timeline":
            engine = self._engine()
            if engine is None:
                return 404, "text/plain", b"no engine wired\n"
            from .timeline import engine_timelines, to_perfetto

            trace = to_perfetto(
                engine_timelines(engine),
                meta={"source": "polykey /debug/timeline"},
            )
            return 200, "application/json", _json_bytes(trace)
        if path == "/debug/slo":
            engine = self._engine()
            if engine is None:
                return 404, "text/plain", b"no engine wired\n"
            from .signals import signals_snapshot

            registry = self.obs.registry if self.obs is not None else None
            return 200, "application/json", _json_bytes(
                signals_snapshot(engine, registry=registry)
            )
        if path == "/debug/flight":
            if self.obs is None:
                return 404, "text/plain", b"no recorder wired\n"
            return 200, "application/json", _json_bytes({
                "traces": self.obs.recorder.traces(),
                "events": self.obs.recorder.events(),
            })
        if path.startswith("/debug/trace/"):
            if self.obs is None:
                return 404, "text/plain", b"no recorder wired\n"
            trace_id = path[len("/debug/trace/"):]
            for trace in reversed(self.obs.recorder.traces()):
                if trace.get("trace_id") == trace_id:
                    return 200, "application/json", _json_bytes(trace)
            return 404, "text/plain", b"trace not found (ring evicted?)\n"
        if path == "/debug/profile":
            if self.profiler is None:
                return 404, "text/plain", b"no profiler wired\n"
            from .profiler import ProfilerBusyError

            try:
                seconds = float(parse_qs(query).get("seconds", ["2"])[0])
            except ValueError:
                return 400, "text/plain", b"seconds must be a number\n"
            try:
                result = self.profiler.capture(seconds)
            except ProfilerBusyError as e:
                return 409, "text/plain", f"{e}\n".encode()
            return 200, "application/json", _json_bytes(result)
        return 404, "text/plain", b"unknown debug endpoint\n"


def _json_bytes(obj) -> bytes:
    return (json.dumps(obj, indent=1, default=str) + "\n").encode()


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = None  # set by MetricsHTTPServer subclassing
    debug: Optional[DebugSurface] = None

    def do_GET(self):  # noqa: N802 (http.server API)
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            # Content negotiation: only an explicit OpenMetrics Accept
            # gets the exemplar rendering; everyone else keeps the
            # byte-stable classic page.
            openmetrics = "application/openmetrics-text" in (
                self.headers.get("Accept") or ""
            )
            try:
                body = self.registry.render(openmetrics=openmetrics).encode()
            except Exception as e:  # a broken collector must not 500 opaquely
                self.send_response(500)
                self.end_headers()
                self.wfile.write(f"collector error: {e}\n".encode())
                return
            self.send_response(200)
            self.send_header(
                "Content-Type",
                CONTENT_TYPE_OPENMETRICS if openmetrics else CONTENT_TYPE,
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.end_headers()
            self.wfile.write(b"ok\n")
        elif path.startswith("/debug/") and self.debug is not None:
            status, ctype, body = self.debug.handle(path, query)
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_response(404)
            self.end_headers()
            self.wfile.write(b"try /metrics\n")

    def log_message(self, *args) -> None:
        pass  # scrapes are high-frequency noise; the JSON log stays clean


class MetricsHTTPServer:
    """Daemon-thread exposition server. `port=0` binds an ephemeral port
    (tests / smoke); `.port` reports the bound one. Passing a
    `DebugSurface` mounts the /debug flight-deck routes (still gated by
    POLYKEY_DEBUG_ENDPOINTS at request time)."""

    def __init__(self, registry: Registry, host: str = "0.0.0.0",
                 port: int = 9464, debug: Optional[DebugSurface] = None):
        handler = type("BoundHandler", (_Handler,),
                       {"registry": registry, "debug": debug})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="polykey-metrics",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
