"""Cross-process clock alignment for the disagg tiers.

Every process in a disagg deployment stamps its timeline and spans with
its OWN ``time.monotonic()`` — a clock whose zero is arbitrary per
process, so worker events cannot be placed on the coordinator's axis by
subtraction alone. This module is the ping-based offset estimator the
merged flight deck (ISSUE 16) rests on:

- The coordinator already heartbeats every worker (``disagg_heartbeat_s``)
  with a ``ping`` op; the reply now carries the worker's monotonic
  timestamp taken while building the reply. The estimator samples the
  coordinator clock immediately before send (``t_send``) and after
  receive (``t_recv``) and assumes the reply was stamped at the
  request/response midpoint — the classic NTP-style bound:

      offset      = (t_send + t_recv) / 2 - remote_mono
      uncertainty = (t_recv - t_send) / 2          # half the RTT

  so ``local ≈ remote + offset`` within ±uncertainty.
- Samples are quality-filtered, not averaged: the lowest-uncertainty
  sample wins, but its uncertainty is AGED by a drift bound (crystal
  oscillators drift ~tens of ppm; we budget 200 ppm) so a stale perfect
  sample eventually loses to a fresh mediocre one. Re-estimating on
  every heartbeat keeps the aged uncertainty near RTT/2 forever.

The estimator is deliberately stateless across restarts: a restarted
worker has a NEW monotonic epoch, so the pool resets the sync when a
member's pid changes.
"""

from __future__ import annotations

import time
from typing import Optional


class ClockSync:
    """Maps one remote process's monotonic clock onto the local one.

    Thread contract: ``update`` is called from a single thread (the
    pool's heartbeat loop); readers (``offset``/``to_local``) may race a
    concurrent update and observe either the old or the new estimate —
    both are valid mappings within their stated uncertainty.
    """

    __slots__ = ("drift", "offset", "_uncertainty", "_at", "samples",
                 "accepted")

    def __init__(self, drift_ppm: float = 200.0):
        self.drift = drift_ppm * 1e-6
        self.offset: Optional[float] = None   # local ≈ remote + offset
        self._uncertainty = float("inf")
        self._at = 0.0                        # local stamp of best sample
        self.samples = 0
        self.accepted = 0

    def update(self, t_send: float, t_recv: float,
               remote_mono: float) -> bool:
        """Fold in one ping exchange. Returns True when the sample
        replaced the current estimate (lower aged uncertainty)."""
        rtt = t_recv - t_send
        if rtt < 0:                 # non-monotonic caller bug; drop it
            return False
        sample_offset = (t_send + t_recv) / 2.0 - remote_mono
        sample_unc = rtt / 2.0
        self.samples += 1
        current = self.uncertainty(now=t_recv)
        if current is not None and sample_unc >= current:
            return False
        self.offset = sample_offset
        self._uncertainty = sample_unc
        self._at = t_recv
        self.accepted += 1
        return True

    def uncertainty(self, now: Optional[float] = None) -> Optional[float]:
        """Current bound on |true offset - estimate|, drift-aged. None
        until the first sample lands."""
        if self.offset is None:
            return None
        if now is None:
            now = time.monotonic()
        return self._uncertainty + self.drift * max(0.0, now - self._at)

    def to_local(self, remote_t: float) -> float:
        """Map a remote monotonic timestamp onto the local clock.
        Identity until the first sample (callers render unaligned rather
        than not at all)."""
        return remote_t if self.offset is None else remote_t + self.offset

    def reset(self) -> None:
        """Forget the estimate — required when the remote restarts (its
        monotonic epoch changed, so the old offset is meaningless)."""
        self.offset = None
        self._uncertainty = float("inf")
        self._at = 0.0

    def snapshot(self) -> dict:
        return {
            "offset_s": self.offset,
            "uncertainty_s": self.uncertainty(),
            "samples": self.samples,
            "accepted": self.accepted,
        }
