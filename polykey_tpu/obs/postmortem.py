"""Crash-durable black boxes and the postmortem CLI.

The chaos soaks kill workers with ``os._exit(1)`` — by design nothing
flushes, so before this module a SIGKILL'd worker took its timeline and
flight-recorder rings to the grave and the run's most interesting
seconds were unrecoverable. A `BlackBox` is the flight-deck counterpart
of the host-KV tier's restart-durable index (PR 15): each member
checkpoints its rings to its per-member state dir with the same
atomic tmp→``os.replace`` idiom, amortized every K timeline appends and
forced on the supervisor's trip path and at control-plane op intake —
the moments that matter are exactly the ones right before a death, and
op intake happens-after the fatal request's trace id was recorded.

``python -m polykey_tpu.obs.postmortem <state-dir>`` (``make
postmortem``) reads every surviving box, maps worker rings onto the
coordinator clock using the offsets the coordinator's own box carries
(`obs.clocks.ClockSync`, re-estimated each heartbeat), and emits

- a human triage report: who went silent first, each member's final
  events, and the trace ids still in flight when the ring froze;
- ONE merged Perfetto file in which the death is an ordinary — if
  truncated — set of process rows, handoff arcs included.

File format (JSON, one object per member, ``blackbox-<role>.json``):
``version``/``role``/``pid``/``wrote_mono``/``wrote_unix``/``meta``/
``timeline`` (schema-expanded events)/``traces``/``events`` (flight
recorder rings). The coordinator's ``meta.clock_offsets`` maps role →
`ClockSync.snapshot()`. When a member is reincarnated (process respawn
or in-process engine restart), the dead incarnation's final box is
rotated to ``blackbox-<role>.prev.json`` so the replacement's boot
baseline can't clobber the death evidence; the reader loads both.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Optional

from .timeline import TimelineRecorder, merge_timelines, to_perfetto
from .trace import FlightRecorder

BLACKBOX_VERSION = 1
BLACKBOX_PREFIX = "blackbox-"
COORDINATOR_ROLE = "coordinator"


def blackbox_path(state_dir: str, role: str) -> str:
    return os.path.join(state_dir, f"{BLACKBOX_PREFIX}{role}.json")


class BlackBox:
    """Amortized, atomic checkpoint of one member's observability rings.

    ``tick()`` is the cheap call sprinkled on hot-ish paths: it compares
    the timeline's lifetime append counter against the last flushed mark
    and only serializes every ``every`` appends (or when forced). A
    flush is tmp-write + ``os.replace``, so readers never observe a torn
    box and a crash mid-flush leaves the previous complete checkpoint.
    """

    def __init__(
        self,
        state_dir: str,
        role: str,
        timeline: Optional[TimelineRecorder] = None,
        recorder: Optional[FlightRecorder] = None,
        every: int = 64,
        meta: Optional[dict] = None,
    ):
        self.path = blackbox_path(state_dir, role)
        self.role = role
        self.every = max(1, int(every))
        self.meta: dict = dict(meta or {})
        self._timeline = timeline
        self._recorder = recorder
        # Appended count at last flush; None forces the first tick to
        # write a baseline box (a member that dies before its first
        # amortized window must still leave evidence it booted).
        self._mark: Optional[int] = None
        self.flushes = 0
        # Serializes concurrent flushes: a forced shutdown flush racing
        # an amortized heartbeat tick would both write the SAME tmp
        # path, and whichever os.replace loses finds it already gone.
        self._flush_lock = threading.Lock()
        os.makedirs(state_dir, exist_ok=True)
        self._rotate()

    def _rotate(self) -> None:
        """Preserve the PREVIOUS incarnation's final checkpoint as
        ``blackbox-<role>.prev.json``. A respawned worker (same role,
        same path) would otherwise clobber the death evidence with its
        boot baseline — exactly the box the postmortem needs. One level
        deep: only the most recent death per role is kept."""
        if os.path.exists(self.path):
            try:
                os.replace(self.path,
                           self.path[:-len(".json")] + ".prev.json")
            except OSError:
                pass     # unreadable squatter; flush() will overwrite it

    def rebind(self, timeline: Optional[TimelineRecorder] = None,
               recorder: Optional[FlightRecorder] = None) -> None:
        """Point at a fresh engine's rings after a supervisor restart
        (the replacement engine allocates new recorders). The tripped
        engine's final flush is rotated aside first — same clobber
        hazard as a process respawn, in-process."""
        self._rotate()
        self._timeline = timeline
        self._recorder = recorder
        with self._flush_lock:
            self._mark = None

    def tick(self, force: bool = False) -> bool:
        appended = (self._timeline.appended
                    if self._timeline is not None else 0)
        with self._flush_lock:
            if (not force and self._mark is not None
                    and 0 <= appended - self._mark < self.every):
                return False
            self._mark = appended
        self.flush()
        return True

    def flush(self) -> str:
        payload = {
            "version": BLACKBOX_VERSION,
            "role": self.role,
            "pid": os.getpid(),
            "wrote_mono": time.monotonic(),
            "wrote_unix": time.time(),
            "meta": dict(self.meta),
            "timeline": (self._timeline.events()
                         if self._timeline is not None else []),
            "traces": (self._recorder.traces()
                       if self._recorder is not None else []),
            "events": (self._recorder.events()
                       if self._recorder is not None else []),
        }
        tmp = self.path + ".tmp"
        with self._flush_lock:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
            self.flushes += 1
        return self.path


# -- reader / reconstruction --------------------------------------------------


def load_blackboxes(state_dir: str) -> list[dict]:
    """Every parseable box under ``state_dir``, sorted coordinator-first
    then by role. Unparseable files (a crash can't tear one, but a
    foreign file can squat the prefix) are skipped, not fatal."""
    boxes = []
    try:
        names = sorted(os.listdir(state_dir))
    except FileNotFoundError:
        return []
    for name in names:
        if not (name.startswith(BLACKBOX_PREFIX)
                and name.endswith(".json")):
            continue
        path = os.path.join(state_dir, name)
        try:
            with open(path) as f:
                box = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(box, dict) or "timeline" not in box:
            continue
        box["_path"] = path
        boxes.append(box)
    boxes.sort(key=lambda b: (b.get("role") != COORDINATOR_ROLE,
                              str(b.get("role"))))
    return boxes


def _clock_offsets(boxes: list[dict]) -> dict[str, float]:
    for box in boxes:
        if box.get("role") == COORDINATOR_ROLE:
            offsets = box.get("meta", {}).get("clock_offsets", {})
            return {
                role: snap["offset_s"]
                for role, snap in offsets.items()
                if isinstance(snap, dict)
                and isinstance(snap.get("offset_s"), (int, float))
            }
    return {}


def merged_perfetto(boxes: list[dict]) -> dict:
    """ONE Perfetto trace from the surviving boxes: coordinator is pid 0
    on its own clock; each worker row rides the coordinator clock via
    the offset the coordinator's box recorded for it (identity when the
    offset didn't survive — unaligned beats absent)."""
    offsets = _clock_offsets(boxes)
    groups = []
    next_pid = 1
    for box in boxes:
        role = str(box.get("role", "?"))
        if role == COORDINATOR_ROLE:
            pid, offset = 0, 0.0
        else:
            pid, next_pid = next_pid, next_pid + 1
            offset = offsets.get(role, 0.0)
        groups.append((pid, role, box.get("timeline", []), offset))
    named = merge_timelines(groups)
    return to_perfetto(named, meta={
        "source": "postmortem",
        "clock_offsets": offsets,
        "boxes": [
            {"role": b.get("role"), "pid_os": b.get("pid"),
             "wrote_unix": b.get("wrote_unix")}
            for b in boxes
        ],
    })


def _inflight_traces(events: list[dict]) -> list[str]:
    """Trace ids admitted into a slot and never retired — the requests
    the process was holding when its ring froze."""
    open_slots: dict[int, Optional[str]] = {}
    for event in events:
        kind = event.get("kind")
        if kind == "admit":
            open_slots[event.get("slot")] = event.get("trace_id")
        elif kind == "slot_end":
            open_slots.pop(event.get("slot"), None)
    return sorted({t for t in open_slots.values() if t})


def _fmt_event(event: dict) -> str:
    kind = event.get("kind", "?")
    rest = {k: v for k, v in event.items() if k not in ("kind", "t")}
    if kind == "note":
        attrs = rest.pop("attrs", {}) or {}
        detail = " ".join(f"{k}={v}" for k, v in attrs.items())
        return f"t={event.get('t', 0):.6f} note {rest.get('note_kind')} " \
               f"{detail}".rstrip()
    detail = " ".join(f"{k}={v}" for k, v in rest.items())
    return f"t={event.get('t', 0):.6f} {kind} {detail}".rstrip()


def triage_report(boxes: list[dict], last: int = 12) -> str:
    """Human triage: members ordered by how long they'd been silent
    (stalest checkpoint first — amortized flushing means the process
    that stopped writing earliest is the likely first casualty)."""
    if not boxes:
        return "postmortem: no black boxes found\n"
    by_staleness = sorted(boxes, key=lambda b: b.get("wrote_unix", 0.0))
    newest = max(b.get("wrote_unix", 0.0) for b in boxes)
    lines = [f"postmortem: {len(boxes)} black box(es)"]
    first = by_staleness[0]
    if len(boxes) > 1:
        silent_s = newest - first.get("wrote_unix", 0.0)
        lines.append(
            f"likely first casualty: {first.get('role')} "
            f"(last checkpoint {silent_s:.3f}s before the newest box)"
        )
    for box in by_staleness:
        events = box.get("timeline", [])
        inflight = _inflight_traces(events)
        lines.append("")
        lines.append(
            f"-- {box.get('role')} (os pid {box.get('pid')}, "
            f"{len(events)} events, {len(box.get('traces', []))} span "
            f"trees) [{box.get('_path', '?')}]"
        )
        if inflight:
            lines.append(f"   in-flight traces: {', '.join(inflight)}")
        for event in events[-last:]:
            lines.append(f"   {_fmt_event(event)}")
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.obs.postmortem",
        description="Reconstruct the last seconds before a death from "
                    "the black boxes in a disagg state dir.",
    )
    parser.add_argument("state_dir", help="per-run state dir holding "
                        f"{BLACKBOX_PREFIX}*.json checkpoints")
    parser.add_argument("--out", default=None,
                        help="merged Perfetto path (default "
                             "<state_dir>/postmortem.perfetto.json)")
    parser.add_argument("--last", type=int, default=12,
                        help="final events to print per member")
    args = parser.parse_args(argv)

    boxes = load_blackboxes(args.state_dir)
    sys.stdout.write(triage_report(boxes, last=args.last))
    if not boxes:
        return 2
    out = args.out or os.path.join(args.state_dir,
                                   "postmortem.perfetto.json")
    with open(out, "w") as f:
        json.dump(merged_perfetto(boxes), f)
    sys.stdout.write(f"merged perfetto: {out}\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
