"""Request tracing: monotonic-clock spans, trace-id propagation, and a
bounded flight recorder of recent span trees.

The questions this answers — "where did THIS slow request spend its
time", "what was in flight when the watchdog tripped" — need more than
counters: per-request trees whose phases partition wall-clock time. The
design keeps the hot path nearly free:

- A `Span` is a plain object stamped with `time.monotonic()`; creating
  one costs an allocation and a clock read. The engine only creates
  spans for requests that arrived with a trace attached (gateway
  traffic), so bench/embedder paths pay nothing.
- Trace ids ride gRPC metadata (``x-trace-id``) so a caller's id is
  honored end to end and echoed back in trailing metadata; absent one,
  the interceptor mints 16 hex bytes from `os.urandom`.
- The `FlightRecorder` is a fixed-capacity deque of FINISHED trees plus
  a separate event ring (watchdog trips, engine deaths). Old entries
  fall off; memory is bounded by capacity × tree size, never by uptime.

Cross-thread contract: the gateway handler thread owns the root span;
the engine thread appends children to it. Child-list appends take the
root's lock (shared down the tree), which is uncontended in practice —
the two threads touch the tree at different phases of the request.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

_local = threading.local()


def new_trace_id() -> str:
    return os.urandom(8).hex()


def current_span() -> Optional["Span"]:
    """The thread's active root span (set by the gateway interceptor for
    the duration of the RPC it is handling). Reads are only meaningful at
    handler start — synchronously after the interceptor set it."""
    return getattr(_local, "span", None)


def set_current_span(span: Optional["Span"]) -> None:
    _local.span = span


class Span:
    """One timed phase. `start`/`end` are monotonic seconds; `finish` is
    idempotent. Children nest arbitrarily deep; the tree renders via
    `to_dict` with durations in ms."""

    __slots__ = (
        "name", "trace_id", "start", "end", "attrs", "children", "_lock",
    )

    def __init__(
        self,
        name: str,
        trace_id: Optional[str] = None,
        start: Optional[float] = None,
        _lock: Optional[threading.Lock] = None,
    ):
        self.name = name
        self.trace_id = trace_id or new_trace_id()
        self.start = time.monotonic() if start is None else start
        self.end: Optional[float] = None
        self.attrs: dict = {}
        self.children: list[Span] = []
        # One lock per TREE (children share the root's): appends from the
        # engine thread and the handler thread serialize on it.
        self._lock = _lock if _lock is not None else threading.Lock()

    def child(
        self,
        name: str,
        start: Optional[float] = None,
        end: Optional[float] = None,
        **attrs,
    ) -> "Span":
        """Open (or, when `end` is given, record a completed) child span.
        Explicit timestamps let the engine convert transition timestamps
        it already tracks (RequestTimings) into spans after the fact."""
        span = Span(name, trace_id=self.trace_id, start=start,
                    _lock=self._lock)
        with self._lock:
            if end is not None:
                span.end = end
            if attrs:
                span.attrs.update(attrs)
            self.children.append(span)
        return span

    def finish(self, end: Optional[float] = None) -> None:
        with self._lock:
            if self.end is None:
                self.end = time.monotonic() if end is None else end

    @property
    def duration_ms(self) -> float:
        end = self.end if self.end is not None else time.monotonic()
        return max(0.0, (end - self.start) * 1e3)

    def set(self, **attrs) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def to_dict(self) -> dict:
        with self._lock:
            children = list(self.children)
            attrs = dict(self.attrs)
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            "duration_ms": round(self.duration_ms, 3),
        }
        if attrs:
            out["attrs"] = attrs
        if children:
            out["children"] = [c.to_dict() for c in children]
        return out

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class Tracer:
    """Root-span factory bound to a recorder: `finish_and_record` closes
    a root and files its tree in the flight recorder."""

    def __init__(self, recorder: Optional["FlightRecorder"] = None):
        self.recorder = recorder

    def start(self, name: str, trace_id: Optional[str] = None) -> Span:
        return Span(name, trace_id=trace_id)

    def finish_and_record(self, span: Span) -> None:
        span.finish()
        if self.recorder is not None:
            self.recorder.record(span)


class FlightRecorder:
    """Bounded ring of recent finished span trees + an event ring.

    Postmortem tool: when a request stalls or the watchdog trips, the
    recorder holds the last `capacity` request trees and the events
    around them without any external collector running."""

    def __init__(self, capacity: int = 64, event_capacity: int = 256):
        # Memory discipline (ISSUE 10): a 0-capacity ring is DISABLED —
        # no deque allocated, every append a no-op — so an obs-less
        # deployment pays neither the rings nor the to_dict renders
        # record() would otherwise do per request.
        self._traces: Optional[deque] = (
            deque(maxlen=capacity) if capacity > 0 else None
        )
        self._events: Optional[deque] = (
            deque(maxlen=event_capacity) if event_capacity > 0 else None
        )
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        if self._traces is None:
            return
        # Store the rendered dict, not the live Span: entries are frozen
        # at record time and safe to hand out without locking the tree.
        with self._lock:
            self._traces.append(span.to_dict())

    def event(self, kind: str, **attrs) -> None:
        if self._events is None:
            return
        entry = {"kind": kind, "monotonic": time.monotonic(),
                 "time": time.time(), **attrs}
        with self._lock:
            self._events.append(entry)

    def last(
        self, pred: Optional[Callable[[dict], bool]] = None
    ) -> Optional[dict]:
        for trace in reversed(self.traces()):
            if pred is None or pred(trace):
                return trace
        return None

    def traces(self) -> list[dict]:
        with self._lock:
            return list(self._traces) if self._traces is not None else []

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events) if self._events is not None else []
