"""Engine timeline: a typed, bounded flight-deck ring + Perfetto export.

PR 6 proved the two-frontier lookahead pipeline with scalar counters
(`overlap_ratio`, `host_stall_ms`) and an ad-hoc `_pipe_events` ring of
bare tuples; PR 7 added a replica tier whose failovers were visible only
as counts. Nobody could *see* the pipeline — which block overlapped
which readback, what a lane's life looked like, where a re-routed stream
landed. This module is that missing picture:

- `TimelineRecorder` — the promoted, always-on ring. Every event is a
  compact tuple ``(kind, t_monotonic, *fields)`` with a fixed per-kind
  schema (`EVENT_FIELDS`), appended from the engine thread (plus rare
  notes from supervisor/pool threads — deque appends are atomic). Memory
  is bounded by `capacity`, never by uptime; an engine constructed with
  ``timeline_capacity=0`` holds **no recorder at all** (``engine.timeline
  is None``) and every emission site is a single ``is None`` branch, so
  disabling observability costs literally nothing on the hot path.
- `to_perfetto` — renders the ring as Chrome-trace/Perfetto JSON
  (load at https://ui.perfetto.dev): a *dispatch frontier* track (one
  slice per block, ending at the next dispatch — steady state tiles the
  row), a *processed frontier* track (one slice per readback), a *host
  stall* track (slices only where the processed frontier actually
  blocked — an empty row IS the proof the pipeline hid the roundtrip),
  and one row per decode slot showing each request's residency with its
  trace id. A replica pool exports one Perfetto "process" per replica.

The schedule becomes evidence: the recorded event order is what the
loop-trace regression test pins (dispatch N+1 happens-before process N),
and the committed `perf/timeline_*.json` artifacts let a reviewer SEE
the ≥2-deep overlap instead of trusting a ratio.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Iterable, Optional

# Event schema: tuple layout is (kind, t, *fields) with `fields` named
# here, in order. Documented in COMPONENTS.md §13; the exporter and the
# structure tests both key off this table, so a new event kind is one
# entry + one emission site.
EVENT_FIELDS: dict[str, tuple[str, ...]] = {
    # One decode block (or spec round) dispatched. `gap_ms` is the host
    # gap since the previous dispatch (the attribution window).
    "dispatch": ("seq", "block_kind", "lanes", "steps", "gap_ms"),
    # One in-flight block processed. `t` is the sync start, `end` the
    # post-emit wall time; `stall_ms` is None for dead blocks whose
    # readback was skipped; `busy_ms` is the device-busy attribution
    # charged to this block (gap − stall, clamped ≥ 0).
    "process": ("seq", "end", "stall_ms", "lookahead", "queued_after",
                "busy_ms"),
    # A request admitted into a slot (tokenized, pages allocated).
    "admit": ("slot", "trace_id", "prompt_tokens"),
    # One prefill dispatch touching a slot (bucket group member or a
    # long-prompt chunk); `final` marks the activating dispatch.
    "prefill": ("slot", "tokens", "final"),
    # First token resolved — the slot's decode phase began.
    "slot_start": ("slot", "trace_id"),
    # Slot retired (done / error / cancelled), with tokens generated.
    "slot_end": ("slot", "reason", "tokens"),
    # Deadline expiry outside a slot (queued) — slot-holding expiries
    # surface as slot_end with a deadline reason.
    "expire": ("phase", "trace_id"),
    # Generic instant marker: supervisor restarts, pool re-routes,
    # profiler captures. `attrs` is a small dict.
    "note": ("note_kind", "attrs"),
}


class TimelineRecorder:
    """Bounded ring of typed engine events (monotonic-stamped).

    Appends are lock-free (CPython deque appends are atomic) and cost a
    tuple allocation + a clock read — cheap enough to stay always-on at
    per-block granularity. Readers snapshot with ``events()``/``raw()``.
    """

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError(
                "TimelineRecorder needs capacity >= 1; a disabled "
                "timeline is `None`, not an empty recorder (the engine "
                "must not allocate a ring it will never fill)"
            )
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        # Lifetime append count (NOT ring length): black boxes flush
        # every K appends, so they need a counter that keeps growing
        # after the ring wraps. Benign races on += from note() threads
        # only ever delay a flush by a few events.
        self.appended = 0

    # -- emission (engine thread; notes may come from other threads) ---------

    def _append(self, entry: tuple) -> None:
        self._ring.append(entry)
        self.appended += 1

    def dispatch(self, seq: int, block_kind: str, lanes: int, steps: int,
                 gap_ms: float) -> None:
        self._append(
            ("dispatch", time.monotonic(), seq, block_kind, lanes, steps,
             gap_ms)
        )

    def process(self, seq: int, start: float, end: float,
                stall_ms: Optional[float], lookahead: int,
                queued_after: int, busy_ms: float) -> None:
        self._append(
            ("process", start, seq, end, stall_ms, lookahead, queued_after,
             busy_ms)
        )

    def admit(self, slot: int, trace_id: Optional[str],
              prompt_tokens: int) -> None:
        self._append(
            ("admit", time.monotonic(), slot, trace_id, prompt_tokens)
        )

    def prefill(self, slot: int, tokens: int, final: bool) -> None:
        self._append(
            ("prefill", time.monotonic(), slot, tokens, final)
        )

    def slot_start(self, slot: int, trace_id: Optional[str]) -> None:
        self._append(("slot_start", time.monotonic(), slot, trace_id))

    def slot_end(self, slot: int, reason: str, tokens: int) -> None:
        self._append(("slot_end", time.monotonic(), slot, reason, tokens))

    def expire(self, phase: str, trace_id: Optional[str]) -> None:
        self._append(("expire", time.monotonic(), phase, trace_id))

    def note(self, note_kind: str, **attrs) -> None:
        self._append(("note", time.monotonic(), note_kind, attrs))

    # -- read side -----------------------------------------------------------

    def raw(self) -> list[tuple]:
        return list(self._ring)

    def events(self) -> list[dict]:
        """Schema-expanded view: one dict per event with ``kind``, ``t``
        and the kind's named fields (EVENT_FIELDS)."""
        out = []
        for entry in list(self._ring):
            kind, t = entry[0], entry[1]
            fields = EVENT_FIELDS.get(kind, ())
            event = {"kind": kind, "t": t}
            event.update(zip(fields, entry[2:]))
            out.append(event)
        return out


def engine_timelines(engine_or_pool) -> list[tuple[int, str, list[dict]]]:
    """Normalize an engine or a pool into exporter input:
    ``[(pid, label, events)]`` — one Perfetto process per replica, pid =
    replica index. Engines with the timeline disabled contribute an
    empty event list (the export stays valid, just blank). A disagg
    pool brings its own clock-aligned merge (`DisaggPool
    .merged_timelines`): one process per worker plus the coordinator,
    worker timestamps mapped onto the coordinator's clock — so
    /debug/timeline serves the cross-process flight deck unchanged."""
    merged = getattr(engine_or_pool, "merged_timelines", None)
    if callable(merged):
        return merged()
    if hasattr(engine_or_pool, "replicas"):
        out = []
        for rep in engine_or_pool.replicas:
            timeline = getattr(rep.engine, "timeline", None)
            out.append((
                rep.index, f"replica {rep.index}",
                timeline.events() if timeline is not None else [],
            ))
        return out
    timeline = getattr(engine_or_pool, "timeline", None)
    return [(0, "engine",
             timeline.events() if timeline is not None else [])]


def merge_timelines(
    groups: Iterable[tuple[int, str, list[dict], float]],
) -> list[tuple[int, str, list[dict]]]:
    """Map N processes' timelines onto ONE clock for a merged export.

    ``groups`` is ``[(pid, label, events, offset_s)]`` where ``offset_s``
    translates that process's monotonic timestamps onto the reference
    (coordinator) clock — ``local = remote + offset`` as estimated by
    `obs.clocks.ClockSync` (the coordinator itself rides with offset 0).
    Returns exporter input (``[(pid, label, events)]``) with every
    timestamp field shifted; input event dicts are not mutated.
    """
    out = []
    for pid, label, events, offset in groups:
        if offset:
            shifted = []
            for event in events:
                event = dict(event)
                event["t"] = event["t"] + offset
                end = event.get("end")
                if isinstance(end, (int, float)):
                    event["end"] = end + offset
                shifted.append(event)
            events = shifted
        out.append((pid, label, list(events)))
    return out


# Track (Perfetto tid) layout within one engine's process. Slot rows
# start at _TID_SLOT0 so slot counts up to ~hundreds never collide.
_TID_DISPATCH = 1
_TID_PROCESS = 2
_TID_STALL = 3
_TID_ENGINE = 4
_TID_SLOT0 = 10


def _thread_meta(pid: int, tid: int, name: str) -> dict:
    return {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": name}}


def _slice(pid: int, tid: int, name: str, ts_us: int, dur_us: int,
           args: Optional[dict] = None) -> dict:
    event = {"ph": "X", "pid": pid, "tid": tid, "name": name,
             "ts": ts_us, "dur": max(1, dur_us), "cat": "polykey"}
    if args:
        event["args"] = args
    return event


def _instant(pid: int, tid: int, name: str, ts_us: int,
             args: Optional[dict] = None) -> dict:
    event = {"ph": "i", "s": "t", "pid": pid, "tid": tid, "name": name,
             "ts": ts_us, "cat": "polykey"}
    if args:
        event["args"] = args
    return event


def to_perfetto(
    named_timelines: Iterable[tuple[int, str, list[dict]]],
    meta: Optional[dict] = None,
) -> dict:
    """Render recorder events as a Chrome-trace JSON object.

    Tracks per engine process: dispatch frontier (block slices tiling
    the row — each ends where the next dispatch begins, so a row with no
    gaps IS steady-state dispatch), processed frontier (sync start →
    post-emit), host stalls (only blocking readbacks), one row per
    decode slot (request residency, admit → retire, named by trace id),
    and an engine-events row for expiries/notes (restarts, re-routes,
    profiler captures). Timestamps are µs relative to the earliest
    event across all replicas, so a pool export lines replicas up on
    one clock (they share the process's monotonic clock).
    """
    named = [(pid, label, events) for pid, label, events in named_timelines]
    t0 = min(
        (event["t"] for _, _, events in named for event in events),
        default=0.0,
    )

    def us(t: float) -> int:
        return int(round((t - t0) * 1e6))

    trace_events: list[dict] = []
    # Handoff arcs (merged disagg exports): the prefill worker's
    # `handoff_serialize` note marks serialize end, the decode worker's
    # `handoff_scatter` note marks scatter start; matching handoff_ids
    # become a Perfetto flow pair so the wire hop renders as ONE
    # causally-ordered arc across process rows.
    arc_starts: dict[str, tuple[int, int]] = {}
    arc_ends: dict[str, tuple[int, int]] = {}
    for pid, label, events in named:
        if not events:
            continue        # disabled/empty timeline: no tracks to draw
        trace_events.append({
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": f"polykey {label}"},
        })
        trace_events.append(_thread_meta(pid, _TID_DISPATCH,
                                         "dispatch frontier"))
        trace_events.append(_thread_meta(pid, _TID_PROCESS,
                                         "processed frontier"))
        trace_events.append(_thread_meta(pid, _TID_STALL, "host stalls"))
        trace_events.append(_thread_meta(pid, _TID_ENGINE, "engine events"))

        dispatches = [e for e in events if e["kind"] == "dispatch"]
        processes = {e["seq"]: e for e in events if e["kind"] == "process"}
        max_t = max((e.get("end", e["t"]) for e in events), default=0.0)

        # Dispatch frontier: block N's slice runs to block N+1's
        # dispatch (device work serializes through the donation chain,
        # so consecutive dispatches tile the device's schedule); the
        # final block falls back to its own readback end, then max_t.
        for i, event in enumerate(dispatches):
            if i + 1 < len(dispatches):
                end_t = dispatches[i + 1]["t"]
            else:
                proc = processes.get(event["seq"])
                end_t = proc["end"] if proc is not None else max_t
            trace_events.append(_slice(
                pid, _TID_DISPATCH, f"block {event['seq']}",
                us(event["t"]), us(max(end_t, event["t"])) - us(event["t"]),
                args={"seq": event["seq"], "kind": event["block_kind"],
                      "lanes": event["lanes"], "steps": event["steps"],
                      "gap_ms": round(event["gap_ms"], 3)},
            ))

        slot_tids = set()
        open_slots: dict[int, dict] = {}
        for event in events:
            kind = event["kind"]
            if kind == "process":
                stall = event["stall_ms"]
                trace_events.append(_slice(
                    pid, _TID_PROCESS, f"block {event['seq']}",
                    us(event["t"]), us(event["end"]) - us(event["t"]),
                    args={"seq": event["seq"],
                          "lookahead": event["lookahead"],
                          "queued_after": event["queued_after"],
                          "stall_ms": (round(stall, 3)
                                       if stall is not None else None),
                          "busy_ms": round(event["busy_ms"], 3)},
                ))
                if stall is not None and stall > 0.05:
                    trace_events.append(_slice(
                        pid, _TID_STALL, f"stall block {event['seq']}",
                        us(event["t"]), int(stall * 1e3),
                        args={"seq": event["seq"],
                              "stall_ms": round(stall, 3)},
                    ))
            elif kind == "admit":
                open_slots[event["slot"]] = event
            elif kind == "prefill":
                tid = _TID_SLOT0 + event["slot"]
                slot_tids.add(event["slot"])
                trace_events.append(_instant(
                    pid, tid,
                    "prefill final" if event["final"] else "prefill chunk",
                    us(event["t"]), args={"tokens": event["tokens"]},
                ))
            elif kind == "slot_start":
                tid = _TID_SLOT0 + event["slot"]
                slot_tids.add(event["slot"])
                trace_events.append(_instant(
                    pid, tid, "first token", us(event["t"]),
                ))
            elif kind == "slot_end":
                slot = event["slot"]
                admit = open_slots.pop(slot, None)
                start_t = admit["t"] if admit is not None else event["t"]
                trace_id = (admit or {}).get("trace_id")
                slot_tids.add(slot)
                trace_events.append(_slice(
                    pid, _TID_SLOT0 + slot,
                    trace_id or f"request@slot{slot}",
                    us(start_t), us(event["t"]) - us(start_t),
                    args={"slot": slot, "reason": event["reason"],
                          "tokens": event["tokens"],
                          "prompt_tokens": (admit or {}).get("prompt_tokens"),
                          "trace_id": trace_id},
                ))
            elif kind == "expire":
                trace_events.append(_instant(
                    pid, _TID_ENGINE, f"deadline expired ({event['phase']})",
                    us(event["t"]), args={"trace_id": event["trace_id"]},
                ))
            elif kind == "note":
                note_kind = event["note_kind"]
                attrs = dict(event["attrs"])
                handoff_id = attrs.get("handoff_id")
                if handoff_id is not None:
                    if note_kind == "handoff_serialize":
                        arc_starts[str(handoff_id)] = (pid, us(event["t"]))
                    elif note_kind == "handoff_scatter":
                        arc_ends[str(handoff_id)] = (pid, us(event["t"]))
                trace_events.append(_instant(
                    pid, _TID_ENGINE, note_kind, us(event["t"]),
                    args=attrs,
                ))
        # Requests still resident when the ring was exported: open tail
        # slices to the export horizon, marked open (frontier state is
        # data, not an error).
        for slot, admit in open_slots.items():
            slot_tids.add(slot)
            trace_events.append(_slice(
                pid, _TID_SLOT0 + slot,
                (admit.get("trace_id") or f"request@slot{slot}") + " (open)",
                us(admit["t"]), us(max_t) - us(admit["t"]),
                args={"slot": slot, "open": True,
                      "trace_id": admit.get("trace_id")},
            ))
        for slot in sorted(slot_tids):
            trace_events.append(_thread_meta(
                pid, _TID_SLOT0 + slot, f"slot {slot}"
            ))

    for handoff_id, (start_pid, start_ts) in arc_starts.items():
        end = arc_ends.get(handoff_id)
        if end is None:
            continue            # one-sided (aborted mid-wire): no arc
        end_pid, end_ts = end
        trace_events.append({
            "ph": "s", "id": handoff_id, "pid": start_pid,
            "tid": _TID_ENGINE, "ts": start_ts,
            "name": "handoff", "cat": "handoff",
        })
        trace_events.append({
            "ph": "f", "bp": "e", "id": handoff_id, "pid": end_pid,
            "tid": _TID_ENGINE, "ts": end_ts,
            "name": "handoff", "cat": "handoff",
        })

    out = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if meta:
        out["otherData"] = meta
    return out
