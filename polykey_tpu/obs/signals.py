"""SLO signal plane: windowed metrics, error-budget burn rates, breaches.

Everything the flight deck (PR 8) exports is cumulative-since-boot: a
p95 that has absorbed six hours of traffic barely moves when the last
minute goes bad, and nothing in the process can *judge* what it sees —
no objective, no budget, no gate. This module is that judgment layer,
and its `signals_snapshot()` read-side is the interface ROADMAP item 5's
autopilot controller will consume:

- `SignalPlane` — a bounded ring of periodic `EngineMetrics` snapshots
  (raw counter values + raw histogram bucket counts), sampled from the
  engine loop at block boundaries and time-gated to `interval_s`; the
  idle loop's 20 Hz tick is the low-rate fallback timer, and the
  read side (`snapshot()`/`stats_fields()`) also samples so windows
  keep advancing even when the engine thread is wedged — which is
  exactly when alerting matters. Two ring entries subtract into a
  WINDOWED view: monotone counters become rates, cumulative histograms
  become delta-histograms whose quantiles (`estimate_quantile`) and
  good-fractions (`fraction_le`) cover only the window — the fix for
  the long-standing "p95 since boot" staleness in `engine_stats`.
  Disabled (``signals_interval_s=0``) means `metrics.signals is None`:
  no ring, no samples, one ``is None`` branch at the loop emission site
  (the ``timeline_capacity=0`` discipline). The plane hangs off
  `EngineMetrics`, which the supervisor already hands to the fresh
  engine on restart — windows survive supervised restarts for free.
- `SloPolicy` / `SloObjective` — declarative objectives (env/JSON):
  latency ("P(TTFT <= 2000 ms) >= 0.95"), availability
  ("1 - (shed + deadline_expired + failed)/total >= 0.999"), and
  floor/ceiling bounds on windowed scalars (device_busy_fraction,
  avg_lanes, tokens_per_sec). Every objective reduces per window to a
  BAD-EVENT FRACTION; burn_rate = bad_fraction / error_budget — the
  standard SRE multi-window burn-rate formulation, so burn 1.0 means
  "consuming budget exactly as fast as the objective allows" and a
  sustained burn > 1 exhausts the budget before the budget window ends.
  Threshold crossings emit typed `slo_breach`/`slo_recovered` events to
  the engine timeline (visible in `to_perfetto` next to the dispatch
  frontier) and the flight recorder, and count into
  ``polykey_slo_breaches_total{objective}``.
- Prometheus export (obs.exposition `_slo_lines`):
  ``polykey_slo_budget_remaining_ratio{objective}``,
  ``polykey_slo_burn_rate{objective,window}``,
  ``polykey_slo_breaches_total{objective}`` — per-replica labeled under
  a pool, like every other engine family.
- ``python -m polykey_tpu.obs.signals --emit-alert-rules`` renders
  Prometheus alert-rule YAML from the SAME `SloPolicy`, so in-process
  breach detection and external alerting cannot drift (DEPLOY.md
  alerting runbook).

The knobs: ``POLYKEY_SIGNALS_INTERVAL`` (seconds between ring samples;
0 disables the plane), ``POLYKEY_SIGNALS_WINDOWS`` (comma-separated
window seconds, default "60,300,3600"), ``POLYKEY_SLO`` (inline policy
JSON, ``@/path/to/policy.json``, or ``default``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from .histogram import estimate_quantile, fraction_le

DEFAULT_WINDOWS: tuple[float, ...] = (60.0, 300.0, 3600.0)
DEFAULT_INTERVAL_S = 5.0

# EngineMetrics histogram attributes the plane snapshots, keyed by the
# signal name objectives reference (the exported family stem).
HIST_SIGNALS: dict[str, str] = {
    "ttft_ms": "ttft_hist",
    "itl_ms": "itl_hist",
    "host_stall_ms": "host_stall_hist",
    "request_device_ms": "device_ms_hist",
    # Host-KV restore latency (ISSUE 15): windowed restore tails are
    # the autopilot's evidence for tuning POLYKEY_KV_RESTORE_SLOTS
    # (p95 >> p50 means restores queue behind the per-iteration budget).
    "kv_restore_ms": "kv_restore_hist",
}

# Windowed scalar signals floor/ceiling objectives may bound; values
# come from `summarize_deltas` keys of the same name.
SCALAR_SIGNALS = frozenset({
    "device_busy_fraction", "avg_lanes", "tokens_per_sec",
    "availability", "host_stall_ms_mean", "lookahead_observed_mean",
    "spec_accept_rate",
})

ENV_POLICY = "POLYKEY_SLO"
ENV_WINDOWS = "POLYKEY_SIGNALS_WINDOWS"


def window_label(seconds: float) -> str:
    """Human window label for metric labels and stat-key suffixes:
    60 -> "1m", 300 -> "5m", 3600 -> "1h", 90 -> "90s"."""
    s = int(round(seconds))
    if s >= 3600 and s % 3600 == 0:
        return f"{s // 3600}h"
    if s >= 60 and s % 60 == 0:
        return f"{s // 60}m"
    return f"{seconds:g}s"


def windows_from_spec(spec: str) -> tuple[float, ...]:
    """Comma-separated window seconds -> sorted tuple; "" -> the
    1m/5m/1h defaults. Malformed or non-positive entries RAISE — the
    same fail-fast rule as POLYKEY_SLO (a typo'd window spec silently
    falling back to defaults would alert on windows the operator never
    asked for, with nothing visibly wrong)."""
    if not spec:
        return DEFAULT_WINDOWS
    try:
        windows = tuple(sorted(float(x) for x in spec.split(",") if x.strip()))
    except ValueError as e:
        raise ValueError(
            f"bad signals windows spec {spec!r}: comma-separated "
            "seconds, e.g. '60,300,3600'"
        ) from e
    if not windows or any(w <= 0 for w in windows):
        raise ValueError(
            f"bad signals windows spec {spec!r}: need at least one "
            "window, all > 0 seconds"
        )
    return windows


def windows_from_env() -> tuple[float, ...]:
    # polylint: disable=ML004(fallback when no EngineConfig exists (standalone plane); the engine passes config.signals_windows through)
    return windows_from_spec(os.environ.get(ENV_WINDOWS, ""))


# -- objectives ---------------------------------------------------------------


@dataclass(frozen=True)
class SloObjective:
    """One declarative objective. `kind` selects the bad-fraction rule:

    - ``latency``: `signal` names a histogram (HIST_SIGNALS); good means
      an observation <= `threshold_ms`; `target` is the required good
      fraction (error budget = 1 - target).
    - ``availability``: good = completed, bad = failed + shed +
      deadline-expired; `target` is the required good fraction.
    - ``floor`` / ``ceiling``: `signal` names a windowed scalar
      (SCALAR_SIGNALS); the window is bad (fraction 1.0) when the value
      crosses `target`; `time_budget` is the allowed fraction of time
      in violation (the error budget).

    `burn_threshold` is the breach line on the shortest window's burn
    (default 1.0 = "burning faster than the budget allows");
    `fast_burn` only parameterizes the emitted page-severity alert rule.
    """

    name: str
    kind: str
    signal: str = ""
    threshold_ms: float = 0.0
    target: float = 0.99
    time_budget: float = 0.05
    burn_threshold: float = 1.0
    fast_burn: float = 14.0

    def validate(self) -> None:
        if not self.name or any(c in self.name for c in '{}",\n'):
            raise ValueError(f"bad objective name {self.name!r}")
        if self.kind == "latency":
            if self.signal not in HIST_SIGNALS:
                raise ValueError(
                    f"latency objective {self.name!r} needs signal in "
                    f"{sorted(HIST_SIGNALS)}, got {self.signal!r}"
                )
            if self.threshold_ms <= 0:
                raise ValueError(
                    f"latency objective {self.name!r} needs threshold_ms > 0"
                )
        elif self.kind == "availability":
            pass
        elif self.kind in ("floor", "ceiling"):
            if self.signal not in SCALAR_SIGNALS:
                raise ValueError(
                    f"{self.kind} objective {self.name!r} needs signal in "
                    f"{sorted(SCALAR_SIGNALS)}, got {self.signal!r}"
                )
            if not 0.0 < self.time_budget <= 1.0:
                raise ValueError(
                    f"objective {self.name!r}: time_budget must be in (0, 1]"
                )
        else:
            raise ValueError(
                f"unknown objective kind {self.kind!r}; use latency, "
                "availability, floor, or ceiling"
            )
        if self.kind in ("latency", "availability") \
                and not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1)"
            )
        if self.burn_threshold <= 0:
            raise ValueError(
                f"objective {self.name!r}: burn_threshold must be > 0"
            )

    @property
    def error_budget(self) -> float:
        if self.kind in ("latency", "availability"):
            return 1.0 - self.target
        return self.time_budget


DEFAULT_OBJECTIVES: tuple[SloObjective, ...] = (
    SloObjective(name="interactive_ttft", kind="latency", signal="ttft_ms",
                 threshold_ms=2000.0, target=0.95),
    SloObjective(name="itl_tail", kind="latency", signal="itl_ms",
                 threshold_ms=500.0, target=0.99),
    SloObjective(name="availability", kind="availability", target=0.999),
    SloObjective(name="device_busy", kind="floor",
                 signal="device_busy_fraction", target=0.5,
                 time_budget=0.1),
)


@dataclass(frozen=True)
class SloPolicy:
    objectives: tuple[SloObjective, ...] = ()

    def validate(self) -> None:
        seen = set()
        for objective in self.objectives:
            objective.validate()
            if objective.name in seen:
                raise ValueError(f"duplicate objective {objective.name!r}")
            seen.add(objective.name)

    @classmethod
    def from_json(cls, obj) -> "SloPolicy":
        if isinstance(obj, dict):
            obj = obj.get("objectives", [])
        if not isinstance(obj, list):
            raise ValueError("SLO policy JSON must be a list of objectives "
                             'or {"objectives": [...]}')
        fields = set(SloObjective.__dataclass_fields__)
        objectives = []
        for entry in obj:
            unknown = set(entry) - fields
            if unknown:
                raise ValueError(
                    f"unknown objective fields {sorted(unknown)} "
                    f"(valid: {sorted(fields)})"
                )
            objectives.append(SloObjective(**entry))
        policy = cls(objectives=tuple(objectives))
        policy.validate()
        return policy

    @classmethod
    def from_spec(cls, raw: str) -> Optional["SloPolicy"]:
        """Policy spec string: empty -> None (no objectives, windows
        only); ``default`` -> the built-in objective set; ``@path`` ->
        JSON file; anything else -> inline JSON. Malformed policy raises
        at engine construction — a typo'd SLO must not silently serve
        unwatched."""
        raw = (raw or "").strip()
        if not raw:
            return None
        if raw == "default":
            policy = cls(objectives=DEFAULT_OBJECTIVES)
            policy.validate()
            return policy
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                return cls.from_json(json.load(f))
        return cls.from_json(json.loads(raw))

    @classmethod
    def from_env(cls) -> Optional["SloPolicy"]:
        # polylint: disable=ML004(fallback when no EngineConfig exists (standalone plane); the engine passes config.slo_policy through)
        return cls.from_spec(os.environ.get(ENV_POLICY, ""))


# -- windowed math ------------------------------------------------------------


def summarize_deltas(deltas: dict, bounds: dict) -> dict:
    """Derived stats over one window's counter/histogram deltas (the
    dict `SignalPlane.window_deltas` returns): rates, availability,
    occupancy, pipeline health, and delta-quantiles. `bounds` maps each
    histogram signal name to its bucket bounds. Pure function of the
    deltas so pool aggregation (`merge_deltas`) reuses it verbatim."""
    c = deltas["counters"]
    covered = deltas["covered_s"]
    completed = c.get("requests_completed", 0)
    # Availability denominator: completed + failed + shed. Deadline
    # expiries are NOT added separately — every expiry already counts
    # in requests_failed (engine._expire/_finish call on_finish(
    # failed=True) alongside on_deadline_expired), so adding the phase
    # counters would double-count each expiry and inflate burn ~2x.
    # The expiry breakdown still rides the summary as its own key.
    bad = c.get("requests_failed", 0) + c.get("requests_shed", 0)
    total = completed + bad
    steps = c.get("steps_dispatched", 0)
    gap = c.get("dispatch_gap_ms_total", 0.0)
    synced = c.get("blocks_synced", 0)
    processed = c.get("blocks_processed", 0)
    out = {
        "covered_s": round(covered, 2),
        "requests_completed": completed,
        "requests_failed": c.get("requests_failed", 0),
        "requests_shed": c.get("requests_shed", 0),
        "deadline_expired": (c.get("deadline_expired_queued", 0)
                             + c.get("deadline_expired_prefill", 0)
                             + c.get("deadline_expired_decode", 0)),
        "availability": round(completed / total, 5) if total else None,
        "tokens_per_sec": (
            round(c.get("tokens_generated", 0) / covered, 2)
            if covered > 0 else None
        ),
        "avg_lanes": (
            round(c.get("lane_steps", 0) / steps, 2) if steps else None
        ),
        "device_busy_fraction": (
            round(c.get("device_busy_ms_total", 0.0) / gap, 4)
            if gap > 0 else None
        ),
        "host_stall_ms_mean": (
            round(c.get("host_stall_ms_total", 0.0) / synced, 3)
            if synced else None
        ),
        "lookahead_observed_mean": (
            round(c.get("lookahead_sum", 0) / processed, 2)
            if processed else None
        ),
        # Autopilot contract fields (ISSUE 18). Explicit None when the
        # window holds no evidence — the controller treats None as
        # "hold", never as zero. arrival_rate_per_s is the interactive-
        # presence signal (prefill-budget actuation); the kv_* rates
        # are the PR 15 fault-pressure signals (restore-slot and
        # resident-floor actuations).
        "arrival_rate_per_s": (
            round(c.get("requests_admitted", 0) / covered, 3)
            if covered > 0 else None
        ),
        "kv_page_faults": (
            c.get("kv_page_faults_prefix", 0)
            + c.get("kv_page_faults_ctx", 0)
        ),
        "kv_fault_rate_per_min": (
            round((c.get("kv_page_faults_prefix", 0)
                   + c.get("kv_page_faults_ctx", 0)) * 60.0 / covered, 3)
            if covered > 0 else None
        ),
        "kv_pages_restored": c.get("kv_pages_restored", 0),
        # Windowed draft acceptance (ISSUE 19): the autopilot's
        # decide_gamma evidence. None when the window proposed nothing
        # (spec off, or an idle/gate-failed stretch) — a null verdict,
        # never a zero.
        "spec_accept_rate": (
            round(
                c.get("drafts_accepted", 0) / c.get("drafts_proposed", 0),
                4,
            )
            if c.get("drafts_proposed", 0) > 0 else None
        ),
    }
    for name, (counts, _sum) in deltas["hists"].items():
        n = sum(counts)
        out[f"{name}_count"] = n
        if n <= 0:
            continue
        b = bounds[name]
        quantiles = (50, 95, 99) if name in ("ttft_ms", "itl_ms") \
            else (50, 95)
        for q in quantiles:
            out[f"{name}_p{q}"] = round(
                estimate_quantile(b, counts, n, q), 2
            )
    return out


def merge_deltas(parts: list[dict]) -> Optional[dict]:
    """Element-wise sum of several replicas' window deltas into one
    pool-aggregate delta (counters add; histogram bucket counts add —
    every ms histogram shares DEFAULT_MS_BUCKETS). covered_s is the max:
    replicas sample on their own clocks and the aggregate window is the
    union span."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    counters: dict = {}
    hists: dict = {}
    for part in parts:
        for key, value in part["counters"].items():
            counters[key] = counters.get(key, 0) + value
        for name, (counts, hsum) in part["hists"].items():
            if name in hists:
                prev_counts, prev_sum = hists[name]
                hists[name] = (
                    tuple(a + b for a, b in zip(prev_counts, counts)),
                    prev_sum + hsum,
                )
            else:
                hists[name] = (tuple(counts), hsum)
    return {
        "covered_s": max(p["covered_s"] for p in parts),
        "counters": counters,
        "hists": hists,
    }


@dataclass
class _SloState:
    breached: bool = False
    breaches: int = 0
    # (t, violated) evaluation history for floor/ceiling time budgets.
    history: deque = field(default_factory=deque)
    last: dict = field(default_factory=dict)


class SignalPlane:
    """Bounded ring of metrics snapshots + SLO evaluation over them.

    Owned by (attached to) an `EngineMetrics`, which the supervisor's
    metrics-adoption path hands to the fresh engine on restart — so the
    ring, the windows, and the breach states all survive supervised
    restarts (the adoption test pins it). The engine rebinds `timeline`
    after a restart (supervisor._restart) since the ring it notes into
    belongs to the engine, not the metrics.
    """

    def __init__(self, metrics, windows: tuple = DEFAULT_WINDOWS,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 capacity: int = 0, policy: Optional[SloPolicy] = None,
                 timeline=None, recorder=None):
        if interval_s <= 0:
            raise ValueError(
                "SignalPlane needs interval_s > 0; a disabled plane is "
                "`metrics.signals is None`, not a zero-interval sampler"
            )
        if not windows:
            raise ValueError("SignalPlane needs at least one window")
        self.metrics = metrics
        self.windows = tuple(sorted(float(w) for w in windows))
        self.interval_s = float(interval_s)
        if capacity <= 0:
            # Cover the longest window at the sampling cadence, plus two
            # samples of slack so the baseline lookup always finds an
            # entry older than the window.
            capacity = min(8192, int(self.windows[-1] / self.interval_s) + 2)
        self.capacity = capacity
        self.timeline = timeline
        self.recorder = recorder
        self._bounds = {
            name: getattr(metrics, attr).bounds
            for name, attr in HIST_SIGNALS.items()
        }
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._eval_lock = threading.Lock()
        self._last_t = 0.0
        self._slo: dict[str, _SloState] = {}
        self.policy: Optional[SloPolicy] = None
        if policy is not None:
            self.set_policy(policy)

    # -- policy ---------------------------------------------------------------

    def set_policy(self, policy: Optional[SloPolicy]) -> None:
        """Install (or clear) the objective set; resets breach state —
        budget accounting against the OLD objectives is meaningless
        against the new ones."""
        if policy is not None:
            policy.validate()
        with self._eval_lock:
            self.policy = policy
            self._slo = {}

    # -- sampling (engine loop + read side) -----------------------------------

    def maybe_sample(self, now: Optional[float] = None) -> bool:
        """Append a ring sample if `interval_s` elapsed since the last
        one, then evaluate the SLO policy. The fast path — one clock
        read and a float compare, no lock — is what the engine loop pays
        per iteration when no sample is due."""
        if now is None:
            now = time.monotonic()
        if now - self._last_t < self.interval_s:
            return False
        with self._lock:
            if now - self._last_t < self.interval_s:
                return False
            self._last_t = now
            self._ring.append(self._capture(now))
        if self.policy is not None and self.policy.objectives:
            self._evaluate(now)
        return True

    def sample_now(self) -> None:
        """Force a ring sample regardless of the interval gate, then
        evaluate. Harness hook (perf_gate, tests) for pinning a
        measurement boundary exactly — the periodic path may lag a
        finish by up to `interval_s`."""
        now = time.monotonic()
        with self._lock:
            self._last_t = now
            self._ring.append(self._capture(now))
        if self.policy is not None and self.policy.objectives:
            self._evaluate(now)

    def _capture(self, now: float) -> tuple:
        counters = self.metrics.counter_sample()
        hists = {
            name: getattr(self.metrics, attr).counts_snapshot()
            for name, attr in HIST_SIGNALS.items()
        }
        return (now, counters, hists)

    def samples(self) -> int:
        return len(self._ring)

    # -- windowed read side ---------------------------------------------------

    def window_deltas(self, seconds: float) -> Optional[dict]:
        """Counter/histogram deltas between the newest sample and the
        newest sample at least `seconds` older (falling back to the
        oldest in the ring — `covered_s` reports what the window
        actually spans, so a freshly booted plane answers honestly
        instead of refusing). None with fewer than two samples."""
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return None
        end_t, end_c, end_h = ring[-1]
        base = ring[0]
        for sample in reversed(ring[:-1]):
            if end_t - sample[0] >= seconds:
                base = sample
                break
        base_t, base_c, base_h = base
        covered = end_t - base_t
        if covered <= 0:
            return None
        counters = {
            key: end_c[key] - base_c.get(key, 0) for key in end_c
        }
        hists = {}
        for name, (counts, hsum) in end_h.items():
            base_counts, base_sum = base_h.get(
                name, ((0,) * len(counts), 0.0)
            )
            hists[name] = (
                tuple(e - b for e, b in zip(counts, base_counts)),
                hsum - base_sum,
            )
        return {"covered_s": covered, "counters": counters, "hists": hists}

    def window_summary(self, seconds: float) -> Optional[dict]:
        deltas = self.window_deltas(seconds)
        if deltas is None:
            return None
        return summarize_deltas(deltas, self._bounds)

    def snapshot(self) -> dict:
        """The stable queryable view over every configured window plus
        the SLO state — the structure `signals_snapshot()` nests
        per-replica and the autopilot (ROADMAP item 5) will consume."""
        self.maybe_sample()
        return {
            "interval_s": self.interval_s,
            "samples": len(self._ring),
            "windows": {
                window_label(w): self.window_summary(w)
                for w in self.windows
            },
            "slo": self.slo_state(),
        }

    def stats_fields(self) -> dict:
        """Windowed keys for `engine_stats` (the "*_5m" satellite):
        quantiles/rates over the window nearest 300 s, suffixed with its
        label — TTFT/ITL tails that reflect the last minutes instead of
        the whole uptime."""
        self.maybe_sample()
        window = min(self.windows, key=lambda w: abs(w - 300.0))
        summary = self.window_summary(window)
        if not summary:
            return {}
        label = window_label(window)
        keys = (
            "ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
            "itl_ms_p50", "itl_ms_p95", "itl_ms_p99",
            "host_stall_ms_p50", "host_stall_ms_p95",
            "tokens_per_sec", "device_busy_fraction", "availability",
        )
        return {
            f"{key}_{label}": summary[key]
            for key in keys
            if summary.get(key) is not None
        }

    # -- SLO evaluation -------------------------------------------------------

    def _bad_fraction(self, objective: SloObjective,
                      deltas: Optional[dict],
                      summary: Optional[dict]) -> Optional[float]:
        """The window's bad-event fraction in [0, 1] for one objective,
        or None when the window carries no evidence (no events → no
        verdict, never a synthetic 0 or 1)."""
        if deltas is None or summary is None:
            return None
        if objective.kind == "latency":
            entry = deltas["hists"].get(objective.signal)
            if entry is None:
                return None
            good = fraction_le(
                self._bounds[objective.signal], entry[0],
                objective.threshold_ms,
            )
            return None if good is None else 1.0 - good
        if objective.kind == "availability":
            availability = summary.get("availability")
            return None if availability is None else 1.0 - availability
        value = summary.get(objective.signal)
        if value is None:
            return None
        ok = value >= objective.target if objective.kind == "floor" \
            else value <= objective.target
        return 0.0 if ok else 1.0

    def _time_budget_bad(self, state: _SloState, now: float) -> Optional[float]:
        """Fraction of the budget window (longest window) a
        floor/ceiling objective spent in violation, time-weighted over
        the evaluation history. The denominator is the BUDGET WINDOW,
        not the observed span: seconds of early evidence must not
        extrapolate to "budget exhausted" (a warm-up dip under the
        floor consumes only the seconds it actually lasted; time not
        yet observed is assumed healthy, matching the
        no-evidence-no-verdict rule)."""
        horizon = now - self.windows[-1]
        while state.history and state.history[0][0] < horizon:
            state.history.popleft()
        if len(state.history) < 2:
            return None
        violated = 0.0
        entries = list(state.history)
        for (t0, bad), (t1, _) in zip(entries, entries[1:]):
            if bad:
                violated += t1 - t0
        return violated / self.windows[-1]

    def _evaluate(self, now: float) -> None:
        policy = self.policy
        if policy is None:
            return
        with self._eval_lock:
            if self.policy is not policy:
                return              # set_policy raced; skip this round
            deltas_by_w = {w: self.window_deltas(w) for w in self.windows}
            summaries = {
                w: (None if deltas_by_w[w] is None
                    else summarize_deltas(deltas_by_w[w], self._bounds))
                for w in self.windows
            }
            for objective in policy.objectives:
                state = self._slo.setdefault(objective.name, _SloState())
                burns: dict[str, Optional[float]] = {}
                for w in self.windows:
                    bad = self._bad_fraction(
                        objective, deltas_by_w[w], summaries[w]
                    )
                    burns[window_label(w)] = (
                        None if bad is None
                        else round(bad / objective.error_budget, 4)
                    )
                # Budget accounting over the LONGEST window: event kinds
                # read their bad fraction straight from it; time-bounded
                # kinds integrate the violation history.
                if objective.kind in ("floor", "ceiling"):
                    short_bad = self._bad_fraction(
                        objective, deltas_by_w[self.windows[0]],
                        summaries[self.windows[0]],
                    )
                    if short_bad is not None:
                        state.history.append((now, short_bad > 0.0))
                    budget_bad = self._time_budget_bad(state, now)
                else:
                    budget_bad = self._bad_fraction(
                        objective, deltas_by_w[self.windows[-1]],
                        summaries[self.windows[-1]],
                    )
                remaining = (
                    1.0 if budget_bad is None
                    else max(0.0, min(
                        1.0, 1.0 - budget_bad / objective.error_budget
                    ))
                )
                # Breach detection on the SHORTEST window with evidence:
                # the freshest signal decides, so a cleared fault stops
                # the burn as soon as the short window ages it out.
                breach_burn = next(
                    (burns[window_label(w)] for w in self.windows
                     if burns[window_label(w)] is not None),
                    None,
                )
                if breach_burn is not None:
                    if breach_burn > objective.burn_threshold \
                            and not state.breached:
                        state.breached = True
                        state.breaches += 1
                        self._emit(
                            "slo_breach", objective=objective.name,
                            burn_rate=breach_burn,
                            threshold=objective.burn_threshold,
                            budget_remaining=round(remaining, 4),
                        )
                    elif breach_burn <= objective.burn_threshold \
                            and state.breached:
                        state.breached = False
                        self._emit(
                            "slo_recovered", objective=objective.name,
                            burn_rate=breach_burn,
                            budget_remaining=round(remaining, 4),
                        )
                state.last = {
                    "kind": objective.kind,
                    "burn_rate": burns,
                    "budget_remaining": round(remaining, 4),
                    "breached": state.breached,
                    "breaches": state.breaches,
                }

    def _emit(self, kind: str, **attrs) -> None:
        timeline = self.timeline
        if timeline is not None:
            timeline.note(kind, **attrs)
        recorder = self.recorder
        if recorder is not None:
            recorder.event(kind, **attrs)

    def slo_state(self) -> dict:
        """Last evaluation per objective (cached — the scrape path must
        not recompute window math): {name: {burn_rate: {window: x},
        budget_remaining, breached, breaches, kind}}. Empty without a
        policy."""
        with self._eval_lock:
            return {
                name: dict(state.last)
                for name, state in self._slo.items() if state.last
            }


# -- process-level read side --------------------------------------------------


def _engines_of(engine_or_pool) -> list[tuple[int, object]]:
    if hasattr(engine_or_pool, "workers"):
        # Disaggregated pool (ISSUE 13): the engines live in other
        # processes — no in-process planes to read or bind. The snapshot
        # degrades to its gateway section; per-worker windowed stats
        # ride the pool's control-plane stats instead.
        return []
    if hasattr(engine_or_pool, "replicas"):
        return [(rep.index, rep.engine) for rep in engine_or_pool.replicas]
    return [(getattr(engine_or_pool, "replica_id", 0), engine_or_pool)]


def bind_recorder(engine_or_pool, recorder) -> None:
    """Give every replica's signal plane the shared flight recorder so
    breach/recovery events land next to watchdog trips and restarts
    (the gateway wires this; engines alone have no recorder)."""
    for _, engine in _engines_of(engine_or_pool):
        plane = getattr(engine.metrics, "signals", None)
        if plane is not None and plane.recorder is None:
            plane.recorder = recorder


def signals_snapshot(engine_or_pool, registry=None) -> dict:
    """The queryable signal-plane view over an engine OR a replica pool
    — the `/debug/slo` payload and the autopilot's read API:

    - ``replicas``: per-replica plane snapshots (windows + slo) plus
      live "now" signals (queue-delay estimate, instantaneous load,
      service-time EWMA) the router already scores on;
    - ``aggregate``: the pool-merged windowed view (counter deltas and
      histogram deltas summed across replicas — real pool quantiles,
      not averages of quantiles);
    - ``gateway``: RPC-level availability from the interceptor's
      ``polykey_rpcs_total{method,code}`` counter when a registry is
      provided — the accounting layer above the engine, where sheds and
      aborts that never reached a slot still count against the service.
    """
    members = _engines_of(engine_or_pool)
    replicas: dict = {}
    planes = []
    for index, engine in members:
        plane = getattr(engine.metrics, "signals", None)
        entry: dict = {"enabled": plane is not None}
        if plane is not None:
            planes.append(plane)
            entry.update(plane.snapshot())
        entry["now"] = {
            "queue_delay_s": round(engine.queue_delay_estimate_s(), 4),
            "load_fraction": round(engine.load_fraction(), 4),
            "service_time_ewma_s": round(
                engine.metrics.service_time_ewma_s(), 4
            ),
        }
        replicas[str(index)] = entry
    out: dict = {"replicas": replicas}
    if planes:
        windows = planes[0].windows
        bounds = planes[0]._bounds
        out["aggregate"] = {
            window_label(w): (
                None if (merged := merge_deltas(
                    [plane.window_deltas(w) for plane in planes]
                )) is None else summarize_deltas(merged, bounds)
            )
            for w in windows
        }
    pool_windows = getattr(engine_or_pool, "signal_windows", None)
    if callable(pool_windows):
        # Disagg pool (ISSUE 16): no in-process planes, but the
        # coordinator keeps its OWN windowed ring of cross-tier handoff
        # signals — wire bandwidth, handoff-latency delta-quantiles,
        # per-tier fault/restore rates. The autopilot reads tier
        # pressure here, same shape discipline as `aggregate`.
        out["pool"] = pool_windows()
        now_fn = getattr(engine_or_pool, "handoff_now", None)
        if callable(now_fn):
            out["pool_now"] = now_fn()
        offsets = getattr(engine_or_pool, "clock_offsets", None)
        if callable(offsets):
            out["clock_offsets"] = offsets()
        tiers_fn = getattr(engine_or_pool, "tier_now", None)
        if callable(tiers_fn):
            # Per-tier live pressure (ISSUE 18): serving/total counts
            # plus heartbeat-fed queue-delay and load means — the tier-
            # scaling controller's primary reading. queue_delay_s is
            # explicitly None when no serving worker has answered a
            # ping yet (no evidence ⇒ the controller holds).
            out["tiers"] = tiers_fn()
    autopilot = getattr(engine_or_pool, "autopilot", None)
    if autopilot is not None:
        # Closed-loop controller state (ISSUE 18): current setpoints,
        # pause state, and the last-N decision ring — /debug/slo is how
        # flightwatch's AUTOPILOT section reads them.
        out["autopilot"] = autopilot.snapshot()
    if registry is not None:
        out["gateway"] = gateway_availability(registry)
    return out


def signals_available(engine_or_pool) -> bool:
    """Whether `signals_snapshot` over this target yields evidence a
    controller may act on — the autopilot's refuse-to-start gate
    (POLYKEY_SIGNALS_INTERVAL=0 allocates no plane, and a control loop
    reading permanently-absent windows would hold forever while
    claiming to supervise). A disagg pool's coordinator ring samples on
    the heartbeat, but its spawned workers inherit the same
    signals_interval_s; the config gate covers both layouts."""
    if hasattr(engine_or_pool, "workers"):
        config = getattr(engine_or_pool, "config", None)
        return bool(config is not None
                    and getattr(config, "signals_interval_s", 0) > 0)
    return any(
        getattr(engine.metrics, "signals", None) is not None
        for _index, engine in _engines_of(engine_or_pool)
    )


def gateway_availability(registry) -> Optional[dict]:
    """Cumulative RPC-outcome accounting from the gateway interceptor's
    counter: OK vs non-OK per the LLM-serving methods. Gateway-level
    availability differs from the engine's when requests die before a
    slot (auth, parse, UNAVAILABLE during restart) — the SLO a client
    actually experiences."""
    counter = registry.get("polykey_rpcs_total")
    if counter is None:
        return None
    ok = bad = 0
    with counter._lock:
        items = list(counter._values.items())
    for (method, code), count in items:
        if not method.endswith(("ExecuteTool", "ExecuteToolStream")):
            continue
        if code == "OK":
            ok += count
        else:
            bad += count
    total = ok + bad
    return {
        "rpcs_ok": int(ok),
        "rpcs_failed": int(bad),
        "availability": round(ok / total, 5) if total else None,
    }


# -- alert-rule emission ------------------------------------------------------


def _yaml_quote(value: str) -> str:
    return '"' + value.replace("\\", "\\\\").replace('"', '\\"') + '"'


def alert_rules_yaml(policy: SloPolicy,
                     windows: tuple = DEFAULT_WINDOWS) -> str:
    """Prometheus alert-rule YAML generated from the SAME SloPolicy the
    in-process plane evaluates — one source of truth, so external
    alerting and the `polykey_slo_*` families cannot drift. Two rules
    per objective (the standard multi-window burn-rate pair):

    - page: the short AND mid windows both burn above `fast_burn`
      (a fast leak that exhausts budget in hours, worth waking someone);
    - ticket: the long window burns above `burn_threshold`
      (a slow leak that exhausts budget before the window rolls over).
    """
    windows = tuple(sorted(float(w) for w in windows))
    short = window_label(windows[0])
    mid = window_label(windows[min(1, len(windows) - 1)])
    long_ = window_label(windows[-1])
    lines = [
        "# Generated by: python -m polykey_tpu.obs.signals"
        " --emit-alert-rules",
        "# Source of truth: the same SloPolicy the engine's signal plane",
        "# evaluates in-process (POLYKEY_SLO). Regenerate on any policy",
        "# change; do not edit by hand.",
        "groups:",
        "- name: polykey-slo",
        "  rules:",
    ]
    for objective in policy.objectives:
        sel = f'{{objective="{objective.name}"}}'
        short_sel = f'{{objective="{objective.name}",window="{short}"}}'
        mid_sel = f'{{objective="{objective.name}",window="{mid}"}}'
        long_sel = f'{{objective="{objective.name}",window="{long_}"}}'
        camel = "".join(
            part.capitalize() for part in objective.name.split("_")
        )
        lines += [
            f"  - alert: PolykeySloFastBurn{camel}",
            "    expr: >-",
            f"      polykey_slo_burn_rate{short_sel}"
            f" > {objective.fast_burn:g}",
            f"      and polykey_slo_burn_rate{mid_sel}"
            f" > {objective.fast_burn:g}",
            f"    for: {short}",
            "    labels:",
            "      severity: page",
            "    annotations:",
            "      summary: " + _yaml_quote(
                f"SLO {objective.name}: fast error-budget burn "
                f"(> {objective.fast_burn:g}x over {short} and {mid})"
            ),
            f"  - alert: PolykeySloSlowBurn{camel}",
            "    expr: >-",
            f"      polykey_slo_burn_rate{long_sel}"
            f" > {objective.burn_threshold:g}",
            f"    for: {mid}",
            "    labels:",
            "      severity: ticket",
            "    annotations:",
            "      summary: " + _yaml_quote(
                f"SLO {objective.name}: sustained burn over {long_} "
                "will exhaust the error budget"
            ),
            f"  - alert: PolykeySloBudgetLow{camel}",
            "    expr: >-",
            f"      polykey_slo_budget_remaining_ratio{sel} < 0.1",
            f"    for: {mid}",
            "    labels:",
            "      severity: ticket",
            "    annotations:",
            "      summary: " + _yaml_quote(
                f"SLO {objective.name}: less than 10% of the error "
                "budget remains"
            ),
        ]
    return "\n".join(lines) + "\n"


def main(argv: Optional[list] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m polykey_tpu.obs.signals",
        description="SLO signal-plane tooling (alert-rule emission).",
    )
    parser.add_argument(
        "--emit-alert-rules", action="store_true",
        help="print Prometheus alert-rule YAML derived from the policy",
    )
    parser.add_argument(
        "--policy", default="",
        help="policy source: inline JSON, @/path.json, or 'default' "
             "(default: POLYKEY_SLO, falling back to the built-ins)",
    )
    parser.add_argument(
        "--windows", default="",
        help="comma-separated window seconds (default: "
             "POLYKEY_SIGNALS_WINDOWS or 60,300,3600)",
    )
    args = parser.parse_args(argv)
    if not args.emit_alert_rules:
        parser.error("nothing to do; pass --emit-alert-rules")
    if args.policy:
        os.environ[ENV_POLICY] = args.policy
    policy = SloPolicy.from_env()
    if policy is None:
        policy = SloPolicy(objectives=DEFAULT_OBJECTIVES)
    if args.windows:
        windows = tuple(
            sorted(float(x) for x in args.windows.split(",") if x.strip())
        )
    else:
        windows = windows_from_env()
    print(alert_rules_yaml(policy, windows), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
