"""Observability: request tracing, latency histograms, Prometheus export.

The standing measurement substrate for perf work (ISSUE 1): every
serving component reports into one `Observability` bundle —

- `registry` — Prometheus metrics, rendered by the /metrics endpoint
  and the `engine_stats` tool's ``metrics_text`` view;
- `tracer` + `recorder` — per-request span trees (root opened by the
  gRPC interceptor, children recorded by the engine) kept in a bounded
  flight recorder for postmortems.

Everything is stdlib-only and cheap enough to stay on in production.
"""

from .clocks import ClockSync
from .exposition import DebugSurface, MetricsHTTPServer, engine_collector
from .histogram import DEFAULT_MS_BUCKETS, Histogram, log_buckets
from .postmortem import BlackBox, load_blackboxes, merged_perfetto
from .profiler import ProfilerBusyError, ProfilerCapture
from .prometheus import (
    CONTENT_TYPE,
    CONTENT_TYPE_OPENMETRICS,
    Counter,
    Gauge,
    HistogramMetric,
    Registry,
    render_counter,
    render_gauge,
    render_histogram,
)
from .signals import (
    SignalPlane,
    SloObjective,
    SloPolicy,
    signals_snapshot,
)
from .timeline import (
    TimelineRecorder,
    engine_timelines,
    merge_timelines,
    to_perfetto,
)
from .trace import (
    FlightRecorder,
    Span,
    Tracer,
    current_span,
    new_trace_id,
    set_current_span,
)


class Observability:
    """Composition root shared by the gateway and its backend."""

    def __init__(self, recorder_capacity: int = 64):
        self.registry = Registry()
        self.recorder = FlightRecorder(capacity=recorder_capacity)
        self.tracer = Tracer(self.recorder)


__all__ = [
    "BlackBox",
    "CONTENT_TYPE",
    "CONTENT_TYPE_OPENMETRICS",
    "ClockSync",
    "Counter",
    "DEFAULT_MS_BUCKETS",
    "DebugSurface",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "HistogramMetric",
    "MetricsHTTPServer",
    "Observability",
    "ProfilerBusyError",
    "ProfilerCapture",
    "SignalPlane",
    "SloObjective",
    "SloPolicy",
    "TimelineRecorder",
    "signals_snapshot",
    "engine_collector",
    "engine_timelines",
    "load_blackboxes",
    "merge_timelines",
    "merged_perfetto",
    "Registry",
    "Span",
    "Tracer",
    "current_span",
    "log_buckets",
    "new_trace_id",
    "render_counter",
    "render_gauge",
    "render_histogram",
    "set_current_span",
    "to_perfetto",
]
