"""Fixed-bucket latency histograms with Prometheus semantics.

The engine's old percentile gauges sorted a 512-entry ring on every
snapshot and could not be exported to Prometheus (which needs cumulative
bucket counts, not samples). This histogram is the replacement: a fixed
set of log-spaced upper bounds chosen at construction, O(#buckets) per
observation (binary search), O(#buckets) per percentile estimate, and a
snapshot that renders directly as a ``*_bucket{le=...}`` family.

Buckets are CUMULATIVE only at render time — internally each bucket
holds its own count so `observe` touches exactly one slot (plus sum and
count), keeping the step-loop cost flat regardless of traffic.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Optional, Sequence


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> list[float]:
    """Log-spaced upper bounds from `lo` to at least `hi`, `per_decade`
    bounds per factor of 10. Bounds are rounded to 3 significant digits
    so the rendered ``le`` labels stay human-readable."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    bounds: list[float] = []
    step = 10.0 ** (1.0 / per_decade)
    v = lo
    while True:
        r = float(f"{v:.3g}")
        if not bounds or r > bounds[-1]:
            bounds.append(r)
        if r >= hi:
            break
        v *= step
    return bounds


# Default bounds for millisecond latencies: 0.5 ms .. 2 min covers TTFT
# on-chip (sub-ms cache hits) through queue-saturated tails.
DEFAULT_MS_BUCKETS = log_buckets(0.5, 120_000.0, per_decade=4)


def estimate_quantile(bounds, counts, total: int, q: float) -> float:
    """The q-th percentile (q in [0, 100]) of a raw per-bucket count
    vector (`counts` aligned with `bounds`, +Inf bucket last; `total` is
    the observation count). Shared by `Histogram.percentiles` and the
    signal plane's DELTA quantiles (obs.signals): subtracting two ring
    snapshots' counts yields a windowed histogram this estimates over —
    the fix for "p95 since boot" staleness. Returns 0.0 when empty;
    values beyond the largest finite bound clamp to it."""
    if total <= 0:
        return 0.0
    rank = q / 100.0 * total
    running = 0.0
    for i, c in enumerate(counts[:-1]):
        if running + c >= rank and c > 0:
            upper = bounds[i]
            lower = bounds[i - 1] if i > 0 else 0.0
            frac = (rank - running) / c
            return lower + (upper - lower) * min(1.0, max(0.0, frac))
        running += c
    return bounds[-1]


def fraction_le(bounds, counts, threshold: float) -> Optional[float]:
    """Fraction of observations <= `threshold` in a raw count vector
    (+Inf bucket last), interpolating linearly inside the straddling
    bucket — the good-event fraction a latency SLO needs ("P(TTFT <=
    500 ms)") from bucket counts alone. None when empty. Everything in
    the +Inf bucket is above any threshold."""
    total = sum(counts)
    if total <= 0:
        return None
    running = 0.0
    lower = 0.0
    for i, upper in enumerate(bounds):
        c = counts[i]
        if threshold < upper:
            if threshold <= lower:
                frac = 0.0
            else:
                frac = (threshold - lower) / (upper - lower)
            return (running + c * frac) / total
        running += c
        lower = upper
    return running / total          # threshold >= last bound: all finite


class Histogram:
    """Thread-safe fixed-bucket histogram.

    `bounds` are inclusive upper bounds of the finite buckets; one
    implicit +Inf bucket catches the overflow. Percentile estimates
    interpolate linearly inside the winning bucket (Prometheus'
    histogram_quantile rule), so their error is bounded by the bucket
    ratio — with 4 buckets/decade, ~±30% worst case, which is what
    log-spaced operational histograms trade for O(1) memory.
    """

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        self.bounds: tuple[float, ...] = tuple(
            sorted(bounds if bounds is not None else DEFAULT_MS_BUCKETS)
        )
        if not self.bounds:
            raise ValueError("histogram needs at least one finite bucket")
        self._counts = [0] * (len(self.bounds) + 1)   # [+Inf] is last
        self._sum = 0.0
        self._count = 0
        # OpenMetrics exemplars (ISSUE 10): the most recent
        # (value, trace_id, unix_ts) observed per bucket, so a p99
        # bucket on a dashboard links to a concrete recorded trace.
        # Lazily allocated on the first traced observation — histograms
        # that never see a trace id (bench, soak) pay no memory.
        self._exemplars: Optional[list] = None
        self._lock = threading.Lock()

    def observe(self, value: float, count: int = 1,
                trace_id: Optional[str] = None) -> None:
        """Record `count` observations of `value` in one locked update
        (the engine amortizes a decode block's inter-token gap over the
        block's tokens this way). A `trace_id` stamps the bucket's
        exemplar — last writer wins, which is exactly the "give me ANY
        recent request in this bucket" exemplar semantics."""
        if count <= 0 or value != value or value in (math.inf, -math.inf):
            return                      # NaN/Inf would poison the sum
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += count
            self._sum += value * count
            self._count += count
            if trace_id is not None:
                if self._exemplars is None:
                    self._exemplars = [None] * (len(self.bounds) + 1)
                self._exemplars[idx] = (value, trace_id, time.time())

    def exemplars(self) -> Optional[list]:
        """Per-bucket exemplars aligned with `bounds` (+Inf last), or
        None when no traced observation was ever recorded."""
        with self._lock:
            return list(self._exemplars) if self._exemplars else None

    def counts_snapshot(self) -> tuple[tuple[int, ...], float]:
        """One locked copy of the RAW per-bucket counts (+Inf last) plus
        the sum — the signal plane's ring stores these and diffs two of
        them into a windowed histogram (estimate_quantile/fraction_le
        over the delta)."""
        with self._lock:
            return tuple(self._counts), self._sum

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> dict:
        """Cumulative Prometheus view: [(le, cumulative_count)...] with a
        trailing ("+Inf", total), plus sum and count."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total = self._sum, self._count
        cumulative = []
        running = 0
        for bound, c in zip(self.bounds, counts[:-1]):
            running += c
            cumulative.append((bound, running))
        return {
            "buckets": cumulative,
            "inf": total,
            "sum": total_sum,
            "count": total,
        }

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 100]). Returns 0.0 when
        empty. Values beyond the largest finite bound clamp to it (the
        +Inf bucket has no upper edge to interpolate toward)."""
        return self.percentiles(q)[0]

    def percentiles(self, *qs: float) -> tuple[float, ...]:
        """All requested quantiles from ONE locked copy of the counts, so
        a snapshot can never report p99 < p50 because observations landed
        between per-quantile reads."""
        for q in qs:
            if not 0 <= q <= 100:
                raise ValueError(f"percentile must be in [0, 100], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return tuple(self._estimate(q, counts, total) for q in qs)

    def _estimate(self, q: float, counts: list[int], total: int) -> float:
        return estimate_quantile(self.bounds, counts, total, q)
