"""On-demand jax.profiler capture with a single-flight guarantee.

TPU hardware windows are scarce (ROUND6.md: the chip has been gone for
days at a stretch), so the first minutes of the next window must harvest
maximal evidence — which means profiling has to be ONE call away on a
live server, not a redeploy. This wraps ``jax.profiler`` start/stop
behind a lock so the two triggers (the ``engine_profile`` gRPC tool and
the ``/debug/profile`` HTTP endpoint) can never start two overlapping
captures: jax's profiler is process-global, and a second start_trace
either raises or silently corrupts the first capture's artifact.

CPU-safe by construction (jax traces host + CPU-backend events too), so
the whole path is testable now and pays off unchanged on hardware.
Every start/stop lands in the flight recorder, so a postmortem reader
can see that a capture was running when a stall happened — profiling
overhead is itself a serving event worth recording.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

# Bounds for the HTTP trigger's blocking capture: long enough for a few
# decode blocks even on a cold CPU engine, short enough that a stray
# request can't pin the profiler (and a handler thread) for minutes.
MIN_CAPTURE_S = 0.1
MAX_CAPTURE_S = 60.0

DEFAULT_DIR = "/tmp/polykey_profile"


class ProfilerBusyError(ValueError):
    """A capture is already running (single-flight contract)."""


class ProfilerCapture:
    """Process-wide profiler guard shared by every trigger surface."""

    def __init__(self, base_dir: Optional[str] = None, recorder=None):
        self._base_dir = base_dir
        self.recorder = recorder
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._captures = 0

    @property
    def base_dir(self) -> str:
        # POLYKEY_PROFILE_DIR is read per capture, not cached: an
        # operator pointing it at a fresh PD mid-incident must win.
        return (self._base_dir
                or os.environ.get("POLYKEY_PROFILE_DIR")
                or DEFAULT_DIR)

    @property
    def active_dir(self) -> Optional[str]:
        return self._dir

    def status(self) -> dict:
        return {
            "profiling": self._dir is not None,
            "log_dir": self._dir or "",
            "captures": self._captures,
        }

    def start(self, log_dir: Optional[str] = None) -> str:
        """Begin a capture. Raises ProfilerBusyError when one is already
        running — the caller decides whether that is a 409 or a tool
        error; nobody ever gets a second concurrent trace."""
        import jax

        # Path assembly stays outside the critical section (PL004); the
        # lock covers only the busy check, the jax start, and the state
        # flip, so two racing starters serialize on exactly that.
        fallback = os.path.join(
            self.base_dir,
            time.strftime("%Y%m%d-%H%M%S", time.gmtime()),
        )
        with self._lock:
            if self._dir is not None:
                raise ProfilerBusyError(
                    f"profiler already tracing to {self._dir}"
                )
            target = log_dir or f"{fallback}-{self._captures}"
            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
            self._dir = target
            self._captures += 1
        if self.recorder is not None:
            self.recorder.event("profiler_start", log_dir=target)
        return target

    def stop(self) -> str:
        import jax

        with self._lock:
            if self._dir is None:
                raise ValueError("profiler is not tracing")
            # Free the single-flight slot BEFORE stop_trace can raise
            # (disk full while flushing the artifact): a failed stop
            # must not wedge profiling until process restart — the next
            # start() gets a fresh chance instead of 409 forever.
            target, self._dir = self._dir, None
            jax.profiler.stop_trace()
        if self.recorder is not None:
            self.recorder.event(
                "profiler_stop", log_dir=target,
                files=_artifact_count(target),
            )
        return target

    def capture(self, seconds: float,
                log_dir: Optional[str] = None) -> dict:
        """Blocking start→sleep→stop round trip (the HTTP trigger).
        Returns the artifact summary; raises ProfilerBusyError when a
        capture is already in flight."""
        seconds = min(MAX_CAPTURE_S, max(MIN_CAPTURE_S, float(seconds)))
        target = self.start(log_dir)
        try:
            time.sleep(seconds)
        finally:
            # Even an interrupted sleep must release the single-flight
            # slot, or one bad request wedges profiling until restart.
            self.stop()
        return {
            "log_dir": target,
            "seconds": seconds,
            "files": _artifact_count(target),
        }


def _artifact_count(log_dir: str) -> int:
    total = 0
    for _root, _dirs, files in os.walk(log_dir):
        total += len(files)
    return total
