"""Prometheus text-format (version 0.0.4) metric primitives.

No client_prometheus dependency (the container doesn't ship one): this
is the small subset serving needs — counters, gauges, histograms over
`obs.histogram.Histogram`, and callback collectors that snapshot live
engine state at scrape time. Rendering follows the exposition format:
one ``# HELP``/``# TYPE`` header per family, samples with sorted label
sets, cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count`` for
histograms.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Optional

from .histogram import Histogram

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# Negotiated via the Accept header (obs.exposition): the OpenMetrics
# rendering is the classic page plus per-bucket exemplars carrying
# trace_id and a terminating "# EOF" — the subset serving needs to link
# a p99 bucket to its recorded span tree. Scrapers that don't ask for
# it get the byte-stable classic page.
CONTENT_TYPE_OPENMETRICS = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

# Render-mode flag (thread-local): set by Registry.render for the
# duration of one page render, read by the histogram sample renderers —
# collectors are plain zero-arg callables, so the mode can't ride an
# argument without breaking every registered collector's signature.
_render_local = threading.local()


def openmetrics_active() -> bool:
    return getattr(_render_local, "openmetrics", False)


@contextlib.contextmanager
def _render_mode(openmetrics: bool):
    previous = getattr(_render_local, "openmetrics", False)
    _render_local.openmetrics = openmetrics
    try:
        yield
    finally:
        _render_local.openmetrics = previous


def render_exemplar_suffix(exemplar: Optional[tuple]) -> str:
    """OpenMetrics exemplar tail for a bucket sample line:
    `` # {trace_id="abc"} value unix_ts``. Empty string outside
    OpenMetrics mode or without an exemplar."""
    if exemplar is None or not openmetrics_active():
        return ""
    value, trace_id, ts = exemplar
    return (f' # {{trace_id="{_escape_label(str(trace_id))}"}} '
            f"{_fmt_value(value)} {ts:.3f}")


def _fmt_value(v: float) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v == int(v)
                              and abs(v) < 1e15):
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _labels_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_header(name: str, help_text: str, metric_type: str) -> list[str]:
    help_esc = help_text.replace("\\", r"\\").replace("\n", r"\n")
    return [f"# HELP {name} {help_esc}", f"# TYPE {name} {metric_type}"]


def render_sample(name: str, labels: dict, value: float) -> str:
    return f"{name}{_labels_str(labels)} {_fmt_value(value)}"


def render_counter(name: str, help_text: str, value: float,
                   labels: Optional[dict] = None) -> list[str]:
    return render_header(name, help_text, "counter") + [
        render_sample(name, labels or {}, value)
    ]


def render_gauge(name: str, help_text: str, value: float,
                 labels: Optional[dict] = None) -> list[str]:
    return render_header(name, help_text, "gauge") + [
        render_sample(name, labels or {}, value)
    ]


def render_histogram_samples(name: str, labels: dict,
                             hist: Histogram) -> list[str]:
    """One label-set's sample lines for a histogram family (no header —
    the text format forbids repeating it, so multi-label-set callers
    emit it once themselves). In OpenMetrics render mode, bucket lines
    carry trace_id exemplars. The ONE place exemplar bucket rendering
    lives — the /metrics engine collector and Registry-owned histograms
    both come through here."""
    snap = hist.snapshot()
    exemplars = hist.exemplars() if openmetrics_active() else None
    lines = []
    for i, (bound, cumulative) in enumerate(snap["buckets"]):
        lines.append(render_sample(
            f"{name}_bucket", {**labels, "le": f"{bound:g}"}, cumulative
        ) + render_exemplar_suffix(exemplars[i] if exemplars else None))
    lines.append(render_sample(
        f"{name}_bucket", {**labels, "le": "+Inf"}, snap["inf"]
    ) + render_exemplar_suffix(exemplars[-1] if exemplars else None))
    lines.append(render_sample(f"{name}_sum", labels, snap["sum"]))
    lines.append(render_sample(f"{name}_count", labels, snap["count"]))
    return lines


def render_histogram(name: str, help_text: str, hist: Histogram,
                     labels: Optional[dict] = None) -> list[str]:
    return render_header(name, help_text, "histogram") \
        + render_histogram_samples(name, labels or {}, hist)


class Counter:
    """Monotonic counter, optionally labeled. Label children are created
    lazily on first `inc` with that label set."""

    metric_type = "counter"

    def __init__(self, name: str, help_text: str,
                 labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help_text = help_text
        self.labelnames = tuple(labelnames)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            # polylint: disable=ML002(prometheus-client contract: label-set cardinality is a declared operator responsibility, the label vocab is static)
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0)

    def render(self) -> list[str]:
        lines = render_header(self.name, self.help_text, self.metric_type)
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0)]           # unlabeled counters always expose
        for key, value in items:
            lines.append(render_sample(
                self.name, dict(zip(self.labelnames, key)), value
            ))
        return lines


class Gauge(Counter):
    """Settable gauge; `fn` makes it a callback gauge evaluated at scrape
    time (live engine state without a background sampler thread)."""

    metric_type = "gauge"

    def __init__(self, name: str, help_text: str,
                 labelnames: tuple[str, ...] = (),
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help_text, labelnames)
        self._fn = fn

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def render(self) -> list[str]:
        if self._fn is not None:
            return render_header(
                self.name, self.help_text, self.metric_type
            ) + [render_sample(self.name, {}, self._fn())]
        return super().render()


class HistogramMetric:
    """Named wrapper binding a math `Histogram` (possibly owned elsewhere,
    e.g. EngineMetrics) into a registry."""

    def __init__(self, name: str, help_text: str,
                 hist: Optional[Histogram] = None, buckets=None):
        self.name = name
        self.help_text = help_text
        self.hist = hist if hist is not None else Histogram(buckets)

    def observe(self, value: float) -> None:
        self.hist.observe(value)

    def render(self) -> list[str]:
        return render_histogram(self.name, self.help_text, self.hist)


class Registry:
    """Scrape-time composition root. Metrics register once; `render()`
    walks them plus any callback collectors (functions returning raw
    exposition lines) and joins the full page."""

    def __init__(self):
        self._metrics: list = []
        self._collectors: list[Callable[[], list[str]]] = []
        self._names: set[str] = set()
        self._lock = threading.Lock()

    def register(self, metric) -> None:
        with self._lock:
            if metric.name in self._names:
                raise ValueError(f"duplicate metric name {metric.name!r}")
            # polylint: disable=ML002(registration is import/startup-time only: bounded by metric definitions in the codebase)
            self._names.add(metric.name)
            # polylint: disable=ML002(registration is import/startup-time only: bounded by metric definitions in the codebase)
            self._metrics.append(metric)

    def get(self, name: str):
        """The registered metric with this name, or None."""
        with self._lock:
            for metric in self._metrics:
                if metric.name == name:
                    return metric
        return None

    def get_or_create(self, factory, name: str, *args, **kwargs):
        """Atomic get-or-register: returns (metric, created). `factory`
        is the metric class (Counter/Gauge/HistogramMetric), constructed
        with (name, *args, **kwargs) only if the name is free — the
        idempotent registration shared registries need (several servers
        or services over one Observability must not race the check)."""
        with self._lock:
            for metric in self._metrics:
                if metric.name == name:
                    return metric, False
            metric = factory(name, *args, **kwargs)
            self._names.add(name)
            self._metrics.append(metric)
            return metric, True

    def counter(self, name: str, help_text: str,
                labelnames: tuple[str, ...] = ()) -> Counter:
        c = Counter(name, help_text, labelnames)
        self.register(c)
        return c

    def gauge(self, name: str, help_text: str,
              labelnames: tuple[str, ...] = (),
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = Gauge(name, help_text, labelnames, fn=fn)
        self.register(g)
        return g

    def histogram(self, name: str, help_text: str,
                  hist: Optional[Histogram] = None,
                  buckets=None) -> HistogramMetric:
        h = HistogramMetric(name, help_text, hist, buckets)
        self.register(h)
        return h

    def register_collector(self, fn: Callable[[], list[str]]) -> None:
        with self._lock:
            # polylint: disable=ML002(registration is import/startup-time only: bounded by collector definitions in the codebase)
            self._collectors.append(fn)

    def render(self, openmetrics: bool = False) -> str:
        with self._lock:
            metrics = list(self._metrics)
            collectors = list(self._collectors)
        lines: list[str] = []
        with _render_mode(openmetrics):
            for metric in metrics:
                lines.extend(metric.render())
            for fn in collectors:
                lines.extend(fn())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"
