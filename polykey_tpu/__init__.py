"""polykey_tpu — a TPU-native inference gateway framework.

Re-implements the capabilities of spounge-ai/polykey-service (the gRPC
`polykey.v2.PolykeyService` tool-execution gateway) with a co-located JAX/XLA/
Pallas serving engine instead of a mock/proxy backend:

- ``polykey_tpu.gateway``  — gRPC server/client/config/observability parity
  with the reference (cmd/polykey, cmd/dev_client, internal/{server,service,
  config} in /root/reference).
- ``polykey_tpu.models``   — Llama-3 / Mixtral / Gemma-2 model families as
  functional JAX pytrees.
- ``polykey_tpu.ops``      — Pallas TPU kernels (paged attention, flash
  prefill, ring attention, MoE dispatch) with jnp fallbacks for CPU tests.
- ``polykey_tpu.engine``   — continuous-batching scheduler, paged KV cache,
  sampling, streaming token delivery, speculative decode.
- ``polykey_tpu.parallel`` — device mesh + sharding specs (dp/tp/pp/sp/ep)
  mapped onto ICI/DCN via jax.sharding.
- ``polykey_tpu.train``    — sharded fine-tuning step (loss/grad/optimizer).
"""

__version__ = "0.1.0"

# Runtime lock-order witness (racelint's dynamic half, ISSUE 14): with
# POLYKEY_LOCK_WITNESS=1, every threading.Lock/RLock created by code in
# this repo is wrapped to record the observed acquisition-order graph,
# dumped as JSON at exit for `python -m polykey_tpu.analysis race
# --witness`. The hook lives here so locks created at class/module
# import time are covered. The env check below only gates the IMPORT
# cost (the analysis package must not load on every polykey import);
# witness.maybe_install() owns the authoritative gating.
import os as _os

if _os.environ.get("POLYKEY_LOCK_WITNESS", "") == "1":
    from .analysis import witness as _witness

    _witness.maybe_install()

# Runtime heap witness (memlint's dynamic half, ISSUE 17): with
# POLYKEY_HEAP_WITNESS=1, tracemalloc starts here — before jax and the
# model registries import — so their allocation sites are attributed,
# and soak checkpoints record labeled heap + pool-occupancy samples,
# dumped per-process at exit for `python -m polykey_tpu.analysis mem
# --witness`. Same gating shape as the lock witness above.
if _os.environ.get("POLYKEY_HEAP_WITNESS", "") == "1":
    from .analysis import heapwitness as _heapwitness

    _heapwitness.maybe_install()

# Runtime starvation witness (schedlint's dynamic half, ISSUE 20): with
# POLYKEY_SCHED_WITNESS=1, the engine loop records per-slot wait-age and
# consecutive-skip counters at every dispatch boundary (restore /
# prefill / decode frontiers), dumped per-process at exit for
# `python -m polykey_tpu.analysis sched --witness`. Same gating shape
# as the lock and heap witnesses above.
if _os.environ.get("POLYKEY_SCHED_WITNESS", "") == "1":
    from .analysis import schedwitness as _schedwitness

    _schedwitness.maybe_install()
