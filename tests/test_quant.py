"""Int8 weight-only quantization tests."""

import dataclasses

import jax
import jax.numpy as jnp

from polykey_tpu.engine.sampling import SamplingParams
from polykey_tpu.models.config import TINY_LLAMA, TINY_MIXTRAL, TINY_GEMMA
from polykey_tpu.models.generate import generate
from polykey_tpu.models.quant import (
    dequantize,
    params_bytes,
    qdot,
    quantize,
    quantize_params,
)
from polykey_tpu.models.transformer import forward, init_params


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8
    back = dequantize(qt, jnp.float32)
    # Per-channel symmetric int8: error <= scale/2 per entry.
    per_chan = jnp.max(jnp.abs(w), axis=0) / 127.0
    assert (jnp.abs(back - w) <= per_chan[None, :] * 0.51 + 1e-7).all()


def test_qdot_matches_dequantized_matmul():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32), jnp.float32)
    qt = quantize(w)
    ref = x @ dequantize(qt, jnp.float32)
    out = qdot(x, qt)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-4


def test_quantized_tree_halves_storage():
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.bfloat16)
    qparams = quantize_params(params, cfg)
    # bf16 → int8 (+small fp32 scales): comfortably under 0.62x.
    assert params_bytes(qparams) < 0.62 * params_bytes(params)


def _logit_agreement(cfg, seed=0):
    params = init_params(jax.random.PRNGKey(seed), cfg, jnp.float32)
    qparams = quantize_params(params, cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16)).astype(jnp.int32)
    h_fp, _ = forward(params, cfg, tokens, pos, None)
    h_q, _ = forward(qparams, cfg, tokens, pos, None)
    assert jnp.isfinite(h_q.astype(jnp.float32)).all()
    # Int8 per-channel keeps hidden states close at tiny scale.
    denom = jnp.maximum(jnp.abs(h_fp.astype(jnp.float32)), 1.0)
    rel = jnp.abs(h_fp.astype(jnp.float32) - h_q.astype(jnp.float32)) / denom
    assert float(jnp.mean(rel)) < 0.05, float(jnp.mean(rel))


def test_quantized_forward_tracks_fp_llama():
    _logit_agreement(TINY_LLAMA)


def test_quantized_forward_tracks_fp_mixtral_both_formulations():
    _logit_agreement(TINY_MIXTRAL)
    _logit_agreement(dataclasses.replace(TINY_MIXTRAL, moe_dispatch=True))


def test_quantized_forward_tracks_fp_gemma():
    _logit_agreement(TINY_GEMMA)


def test_quantized_greedy_generation_runs_end_to_end():
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    qparams = quantize_params(params, cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    seq_lens = jnp.full((2,), 8, jnp.int32)
    sampling = SamplingParams(max_new_tokens=12, temperature=0.0)
    out, n = generate(
        qparams, cfg, tokens, seq_lens, jax.random.PRNGKey(2), sampling,
        max_len=32,
    )
    assert (n == 12).all()
    assert ((out >= 0) & (out < cfg.vocab_size)).all()


# ---- int4 (group-wise) ----


def test_int4_roundtrip_error_bound():
    """Group-wise symmetric int4: per-entry error <= group scale / 2."""
    w = jax.random.normal(jax.random.PRNGKey(3), (256, 32), jnp.float32)
    qt = quantize(w, bits=4, group_size=128)
    # Packed storage: uint8 nibble pairs along the contraction axis
    # (jnp.int4 is rejected by the axon remote backend; quant.py).
    assert qt.q.dtype == jnp.uint8 and qt.bits == 4
    assert qt.q.shape == (128, 32) and qt.shape == (256, 32)
    assert qt.s.shape == (2, 32)
    back = dequantize(qt, jnp.float32)
    grouped = w.reshape(2, 128, 32)
    per_group = jnp.max(jnp.abs(grouped), axis=1) / 7.0        # [2, 32]
    err = jnp.abs(back.reshape(2, 128, 32) - grouped)
    assert (err <= per_group[:, None, :] * 0.51 + 1e-7).all()


def test_int4_group_size_shrinks_to_axis():
    w = jax.random.normal(jax.random.PRNGKey(4), (64, 16), jnp.float32)
    qt = quantize(w, bits=4, group_size=128)   # 64 % 128 != 0 → one group
    assert qt.s.shape == (1, 16)
    assert jnp.isfinite(dequantize(qt, jnp.float32)).all()


def test_int4_qdot_matches_dequantized_matmul():
    w = jax.random.normal(jax.random.PRNGKey(5), (256, 16), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 256), jnp.float32)
    qt = quantize(w, bits=4)
    ref = x @ dequantize(qt, jnp.float32)
    out = qdot(x, qt)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-3


def test_int4_tree_quarters_block_storage():
    """bits=4 tree: block linears int4 (+fp32 group scales), embed and
    lm_head stay int8 — total well under the int8 tree's bytes."""
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.bfloat16)
    q8 = quantize_params(params, cfg)
    q4 = quantize_params(params, cfg, bits=4)
    assert q4["layers"]["attn"]["wq"].q.dtype == jnp.uint8
    assert q4["layers"]["attn"]["wq"].bits == 4
    assert q4["layers"]["mlp"]["down"].q.dtype == jnp.uint8
    assert q4["layers"]["mlp"]["down"].bits == 4
    assert q4["embed"].q.dtype == jnp.int8
    if "lm_head" in q4:
        assert q4["lm_head"].q.dtype == jnp.int8
    assert params_bytes(q4) < params_bytes(q8)


def test_int4_forward_tracks_fp_all_families():
    """Same hidden-state agreement gate as int8, at a looser int4
    tolerance; all three families, both MoE formulations."""
    for cfg in (TINY_LLAMA, TINY_MIXTRAL, TINY_GEMMA,
                dataclasses.replace(TINY_MIXTRAL, moe_dispatch=True)):
        params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        qparams = quantize_params(params, cfg, bits=4)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(16), (2, 16)).astype(jnp.int32)
        h_fp, _ = forward(params, cfg, tokens, pos, None)
        h_q, _ = forward(qparams, cfg, tokens, pos, None)
        assert jnp.isfinite(h_q.astype(jnp.float32)).all()
        denom = jnp.maximum(jnp.abs(h_fp.astype(jnp.float32)), 1.0)
        rel = jnp.abs(
            h_fp.astype(jnp.float32) - h_q.astype(jnp.float32)) / denom
        # Tiny models quantize COARSELY: hidden 64 < group_size collapses
        # to one group per column (per-channel int4), and 2-layer MoE
        # routing amplifies flips — real 128-group models track far
        # tighter. This is a sanity gate, not an accuracy claim.
        assert float(jnp.mean(rel)) < 0.35, (cfg.name, float(jnp.mean(rel)))
