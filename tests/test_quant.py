"""Int8 weight-only quantization tests."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from polykey_tpu.engine.sampling import SamplingParams
from polykey_tpu.models.config import TINY_LLAMA, TINY_MIXTRAL, TINY_GEMMA
from polykey_tpu.models.generate import generate
from polykey_tpu.models.quant import (
    QuantizedTensor,
    dequantize,
    params_bytes,
    qdot,
    quantize,
    quantize_params,
)
from polykey_tpu.models.transformer import forward, init_params


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8
    back = dequantize(qt, jnp.float32)
    # Per-channel symmetric int8: error <= scale/2 per entry.
    per_chan = jnp.max(jnp.abs(w), axis=0) / 127.0
    assert (jnp.abs(back - w) <= per_chan[None, :] * 0.51 + 1e-7).all()


def test_qdot_matches_dequantized_matmul():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32), jnp.float32)
    qt = quantize(w)
    ref = x @ dequantize(qt, jnp.float32)
    out = qdot(x, qt)
    assert float(jnp.max(jnp.abs(ref - out))) < 1e-4


def test_quantized_tree_halves_storage():
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.bfloat16)
    qparams = quantize_params(params, cfg)
    # bf16 → int8 (+small fp32 scales): comfortably under 0.62x.
    assert params_bytes(qparams) < 0.62 * params_bytes(params)


def _logit_agreement(cfg, seed=0):
    params = init_params(jax.random.PRNGKey(seed), cfg, jnp.float32)
    qparams = quantize_params(params, cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16)).astype(jnp.int32)
    h_fp, _ = forward(params, cfg, tokens, pos, None)
    h_q, _ = forward(qparams, cfg, tokens, pos, None)
    assert jnp.isfinite(h_q.astype(jnp.float32)).all()
    # Int8 per-channel keeps hidden states close at tiny scale.
    denom = jnp.maximum(jnp.abs(h_fp.astype(jnp.float32)), 1.0)
    rel = jnp.abs(h_fp.astype(jnp.float32) - h_q.astype(jnp.float32)) / denom
    assert float(jnp.mean(rel)) < 0.05, float(jnp.mean(rel))


def test_quantized_forward_tracks_fp_llama():
    _logit_agreement(TINY_LLAMA)


def test_quantized_forward_tracks_fp_mixtral_both_formulations():
    _logit_agreement(TINY_MIXTRAL)
    _logit_agreement(dataclasses.replace(TINY_MIXTRAL, moe_dispatch=True))


def test_quantized_forward_tracks_fp_gemma():
    _logit_agreement(TINY_GEMMA)


def test_quantized_greedy_generation_runs_end_to_end():
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    qparams = quantize_params(params, cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    seq_lens = jnp.full((2,), 8, jnp.int32)
    sampling = SamplingParams(max_new_tokens=12, temperature=0.0)
    out, n = generate(
        qparams, cfg, tokens, seq_lens, jax.random.PRNGKey(2), sampling,
        max_len=32,
    )
    assert (n == 12).all()
    assert ((out >= 0) & (out < cfg.vocab_size)).all()
