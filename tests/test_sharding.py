"""Multi-device sharding tests on the virtual 8-device CPU mesh.

The invariant: sharded execution is numerically the same computation — TP/EP/
DP sharded forwards must match the single-device result to float tolerance.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polykey_tpu.models.config import TINY_LLAMA, TINY_MIXTRAL
from polykey_tpu.models.transformer import forward, init_params, unembed
from polykey_tpu.parallel.mesh import AXIS_NAMES, MeshConfig, create_mesh
from polykey_tpu.parallel.sharding import (
    batch_sharding,
    param_shardings,
    shard_params,
)

# Widened tiny config so tp=4 divides heads/hidden cleanly.
CFG = dataclasses.replace(
    TINY_LLAMA, hidden_size=128, intermediate_size=256, num_heads=8,
    num_kv_heads=4, head_dim=16,
)

MOE_CFG = dataclasses.replace(
    TINY_MIXTRAL, hidden_size=128, intermediate_size=256, num_heads=8,
    num_kv_heads=4, head_dim=16,
)


def _logits(cfg, params, tokens, positions):
    hidden, _ = forward(params, cfg, tokens, positions, None)
    return unembed(params, cfg, hidden)


@pytest.fixture(scope="module")
def batch():
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(16), (4, 16)).astype(jnp.int32)
    return tokens, positions


@pytest.mark.parametrize(
    "mesh_config",
    [
        MeshConfig(tp=4),
        MeshConfig(dp=4),
        MeshConfig(dp=2, tp=2),
        MeshConfig(dp=2, tp=4),
        MeshConfig(pp=2, tp=2),
    ],
    ids=lambda m: "x".join(f"{n}{s}" for n, s in zip(AXIS_NAMES, m.shape) if s > 1),
)
def test_sharded_forward_matches_single_device(mesh_config, batch):
    assert jax.device_count() >= mesh_config.num_devices, "need 8 CPU devices"
    tokens, positions = batch
    params = init_params(jax.random.PRNGKey(0), CFG, jnp.float32)
    expected = np.asarray(_logits(CFG, params, tokens, positions))

    mesh = create_mesh(mesh_config, jax.devices()[: mesh_config.num_devices])
    sharded = shard_params(params, CFG, mesh)
    tokens_s = jax.device_put(tokens, batch_sharding(mesh, 2))
    positions_s = jax.device_put(positions, batch_sharding(mesh, 2))

    got = jax.jit(lambda p, t, pos: _logits(CFG, p, t, pos))(
        sharded, tokens_s, positions_s
    )
    np.testing.assert_allclose(expected, np.asarray(got), rtol=2e-4, atol=2e-4)


def test_moe_ep_sharded_matches_single_device(batch):
    tokens, positions = batch
    params = init_params(jax.random.PRNGKey(2), MOE_CFG, jnp.float32)
    expected = np.asarray(_logits(MOE_CFG, params, tokens, positions))

    mesh = create_mesh(MeshConfig(dp=2, ep=2, tp=2), jax.devices()[:8])
    sharded = shard_params(params, MOE_CFG, mesh)
    tokens_s = jax.device_put(tokens, batch_sharding(mesh, 2))
    positions_s = jax.device_put(positions, batch_sharding(mesh, 2))

    got = jax.jit(lambda p, t, pos: _logits(MOE_CFG, p, t, pos))(
        sharded, tokens_s, positions_s
    )
    np.testing.assert_allclose(expected, np.asarray(got), rtol=3e-4, atol=3e-4)


def test_param_shardings_cover_all_leaves():
    for cfg in (CFG, MOE_CFG):
        mesh = create_mesh(MeshConfig(tp=2), jax.devices()[:2])
        shardings = param_shardings(cfg, mesh)
        params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        assert jax.tree_util.tree_structure(
            shardings
        ) == jax.tree_util.tree_structure(params)


def test_tp_actually_shards_weights():
    """TP must reduce per-device parameter bytes, not just relabel them."""
    mesh = create_mesh(MeshConfig(tp=4), jax.devices()[:4])
    params = shard_params(
        init_params(jax.random.PRNGKey(0), CFG, jnp.float32), CFG, mesh
    )
    wq = params["layers"]["attn"]["wq"]
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape[-1] == wq.shape[-1] // 4


def test_mesh_validation():
    with pytest.raises(ValueError):
        create_mesh(MeshConfig(tp=3), jax.devices()[:8])
