"""Selection rules of the bench's TPU-artifact replay path.

When the tunnel is down at official-bench time, bench.py replays the best
TPU-backed watcher artifact (bench.py:_latest_tpu_artifact) instead of
emitting another CPU fallback. These rules decide what lands in the
round's official artifact, so they are pinned here:
- CPU-fallback / failed / already-replayed artifacts are never selected;
- experiment-sweep artifacts (non-default configs) are excluded;
- a target-comparable 8B line (vs_baseline non-null) beats a NEWER
  partial rescue artifact;
- artifacts older than the age bound are ignored.
"""

import calendar
import json
import os
import time

import bench


def _write(dirpath, name, line, age_s=0.0):
    path = os.path.join(dirpath, name)
    with open(path, "w") as f:
        json.dump(line, f)
    if age_s:
        t = time.time() - age_s
        os.utime(path, (t, t))
    return path


def _tpu_line(metric="llama3_8b_int8_engine_tok_s_per_chip",
              value=2000.0, vs_baseline=1.0, **extra):
    return {"metric": metric, "value": value, "unit": "tok/s",
            "vs_baseline": vs_baseline,
            "details": {"platform": "tpu"}, **extra}


def _select(tmp_path, monkeypatch):
    monkeypatch.setenv("POLYKEY_BENCH_PERF_DIR", str(tmp_path))
    # Pin the age bound: an ambient operator override would change which
    # fixtures age out.
    monkeypatch.setenv("POLYKEY_BENCH_REPLAY_MAX_AGE_H", "14")
    return bench._latest_tpu_artifact()


def test_no_artifacts_returns_none(tmp_path, monkeypatch):
    assert _select(tmp_path, monkeypatch) is None


def test_ineligible_artifacts_skipped(tmp_path, monkeypatch):
    cpu = _tpu_line()
    cpu["details"]["platform"] = "cpu"
    _write(tmp_path, "bench_watcher_a.json", cpu)
    _write(tmp_path, "bench_watcher_b.json",
           {"metric": "bench_failed", "value": 0.0,
            "details": {"platform": "tpu"}})
    _write(tmp_path, "bench_watcher_c.json",
           _tpu_line(replayed_from="perf/earlier.json"))
    # Experiment artifacts run non-default configs: never the headline.
    _write(tmp_path, "bench_exp_kv8.json", _tpu_line(value=9999.0))
    assert _select(tmp_path, monkeypatch) is None


def test_8b_beats_newer_partial(tmp_path, monkeypatch):
    _write(tmp_path, "bench_watcher_full.json",
           _tpu_line(value=2345.6), age_s=3600)
    _write(tmp_path, "bench_watcher_rescue.json",
           _tpu_line(metric="llama-1b-bench_engine_tok_s_per_chip",
                     value=900.0, vs_baseline=None))
    path, line = _select(tmp_path, monkeypatch)
    assert path.endswith("bench_watcher_full.json")
    assert line["value"] == 2345.6


def test_newest_8b_wins_and_age_bound(tmp_path, monkeypatch):
    _write(tmp_path, "bench_watcher_old.json",
           _tpu_line(value=2100.0), age_s=7200)
    _write(tmp_path, "bench_watcher_new.json", _tpu_line(value=2200.0))
    path, line = _select(tmp_path, monkeypatch)
    assert path.endswith("bench_watcher_new.json")

    # Everything aged out -> no replay.
    for name in ("bench_watcher_old.json", "bench_watcher_new.json"):
        t = time.time() - 15 * 3600
        os.utime(os.path.join(tmp_path, name), (t, t))
    assert _select(tmp_path, monkeypatch) is None


def test_filename_timestamp_beats_mtime(tmp_path, monkeypatch):
    # ADVICE r4: a git checkout resets mtime to checkout time, so a
    # previous-round watcher artifact would look brand-new by mtime. The
    # filename timestamp is authoritative when present.
    old_ts = time.strftime("%Y%m%d_%H%M%S", time.localtime(
        time.time() - 20 * 3600))
    _write(tmp_path, f"bench_watcher_{old_ts}.json", _tpu_line(value=2100.0))
    # mtime is "now" (just written) but the embedded timestamp is 20 h old
    # -> aged out of the 14 h bound.
    assert _select(tmp_path, monkeypatch) is None


def _select_prior(tmp_path, monkeypatch):
    monkeypatch.setenv("POLYKEY_BENCH_PERF_DIR", str(tmp_path))
    monkeypatch.setenv("POLYKEY_BENCH_XROUND_MAX_AGE_DAYS", "14")
    return bench._prior_round_tpu_artifact()


def test_prior_round_selection_and_provenance(tmp_path, monkeypatch):
    # Experiment/failed artifacts are never eligible; round artifacts are.
    _write(tmp_path, "bench_exp_kv8.json", _tpu_line(value=9999.0))
    _write(tmp_path, "bench_failed_y.json", _tpu_line(value=50.0))
    _write(tmp_path, "bench_stdout_r03.json", _tpu_line(value=117.9))
    path, line, prov = _select_prior(tmp_path, monkeypatch)
    assert path.endswith("bench_stdout_r03.json")
    assert prov["round"] == "r03"
    assert prov["cross_round"] is True
    assert set(prov) >= {"round", "date", "engine_rev"}


def test_prior_round_accepts_aged_watcher_artifact(tmp_path, monkeypatch):
    # A prior round's TPU watcher artifact in its normal on-disk name is
    # legitimate evidence: aged out of the 14 h current-round bound, it
    # must still be reachable by the cross-round path (code-review r5:
    # the initial exclusion made normal watcher evidence unreplayable).
    old_ts = time.strftime("%Y%m%d_%H%M%S",
                           time.localtime(time.time() - 2 * 86400))
    _write(tmp_path, f"bench_watcher_{old_ts}.json", _tpu_line(value=2000.0))
    assert _select(tmp_path, monkeypatch) is None  # current-round: aged out
    path, line, prov = _select_prior(tmp_path, monkeypatch)
    assert path.endswith(f"bench_watcher_{old_ts}.json")


def test_prior_round_age_bound(tmp_path, monkeypatch):
    _write(tmp_path, "bench_stdout_r03.json", _tpu_line(value=117.9),
           age_s=20 * 86400)
    assert _select_prior(tmp_path, monkeypatch) is None


def test_prior_round_prefers_comparable_then_newest(tmp_path, monkeypatch):
    partial = _tpu_line(metric="llama-1b-bench_engine_tok_s_per_chip",
                        value=900.0, vs_baseline=None)
    _write(tmp_path, "bench_partial_r04.json", partial)
    _write(tmp_path, "bench_stdout_r03.json", _tpu_line(value=117.9),
           age_s=86400)
    path, line, prov = _select_prior(tmp_path, monkeypatch)
    assert path.endswith("bench_stdout_r03.json")


def test_compose_cpu_run_headlines_no_tpu_evidence(monkeypatch):
    monkeypatch.delenv("POLYKEY_BENCH_ALLOW_CPU_HEADLINE", raising=False)
    result = {"platform": "cpu",
              "engine_1b": {"model": "tiny-llama", "tok_s": 2923.0,
                            "p50_ttft_ms": 12.0}}
    line = bench._compose_line(result)
    assert line["metric"] == "no_tpu_evidence"
    assert line["value"] == 0.0
    assert line["vs_baseline"] is None
    assert line["cpu_reference"]["value"] == 2923.0
    assert line["details"]["engine_1b"]["tok_s"] == 2923.0

    # The explicit dev override restores the old CPU shape.
    monkeypatch.setenv("POLYKEY_BENCH_ALLOW_CPU_HEADLINE", "1")
    line = bench._compose_line(result)
    assert line["metric"] == "tiny-llama_engine_tok_s_per_chip"
    assert line["value"] == 2923.0


def test_compose_tpu_headline_unchanged():
    result = {"platform": "tpu",
              "engine_8b_int8": {"tok_s": 2100.0, "p50_ttft_ms": 90.0}}
    line = bench._compose_line(result)
    assert line["metric"] == "llama3_8b_int8_engine_tok_s_per_chip"
    assert line["value"] == 2100.0
    assert line["vs_baseline"] == 1.05


def test_artifact_timestamp_git_time_with_relative_path(tmp_path, monkeypatch):
    """The git-log fallback must resolve even when the artifact path is
    RELATIVE (a relative POLYKEY_BENCH_PERF_DIR spells one): the pathspec
    is passed absolute, so -C'ing into the artifact's dir cannot shift
    its meaning. Regression for ADVICE r5 bench.py:148 — the old code
    silently fell back to mtime (checkout time), the exact failure this
    chain guards against."""
    import subprocess

    repo = tmp_path / "checkout"
    perf = repo / "perf"
    perf.mkdir(parents=True)
    # Name must dodge the filename-stamp branches; no measured_at field.
    artifact = perf / "bench_gitfallback.json"
    artifact.write_text(json.dumps(_tpu_line()))
    env = {
        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
        "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
        "GIT_AUTHOR_DATE": "2026-07-01T00:00:00Z",
        "GIT_COMMITTER_DATE": "2026-07-01T00:00:00Z",
        "PATH": os.environ["PATH"],
    }
    subprocess.run(["git", "init", "-q"], cwd=repo, env=env, check=True)
    subprocess.run(["git", "add", "."], cwd=repo, env=env, check=True)
    subprocess.run(["git", "commit", "-q", "-m", "x"], cwd=repo, env=env,
                   check=True)
    # mtime says "now" (checkout-reset shape); git knows July 1.
    committed = calendar.timegm(
        time.strptime("2026-07-01T00:00:00Z", "%Y-%m-%dT%H:%M:%SZ"))
    monkeypatch.chdir(repo)
    ts = bench._artifact_timestamp("perf/bench_gitfallback.json",
                                   _tpu_line())
    assert abs(ts - committed) < 2, (
        f"expected the git commit time, got {ts} (mtime fallback?)")


def test_prior_round_label_from_commit_metadata(tmp_path, monkeypatch):
    """An artifact without an _rNN filename tag derives its round label
    from the commit that added it instead of collapsing to 'unknown'
    (ADVICE r5 bench.py:281)."""
    import subprocess as _sp

    _write(tmp_path, "bench_stdout_tpu.json", _tpu_line())
    real_run = _sp.run

    def fake_run(cmd, **kwargs):
        if "--diff-filter=A" in cmd:
            class R:
                returncode = 0
                stdout = "abc1234 1753660800\n"   # 2025-07-28 UTC
            return R()
        return real_run(cmd, **kwargs)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    path, line, prov = _select_prior(tmp_path, monkeypatch)
    assert prov["round"] == "round-of-2025-07-28"
    assert prov["engine_rev"] == "abc1234"


def test_prior_round_current_bound_flag(tmp_path, monkeypatch):
    """A freshly-written (current-round) artifact carries
    within_current_round_bound=True so the replay wording never claims a
    full-round outage; an aged one carries False."""
    _write(tmp_path, "bench_stdout_r03.json", _tpu_line())
    _, _, prov = _select_prior(tmp_path, monkeypatch)
    assert prov["within_current_round_bound"] is True

    for f in tmp_path.glob("*.json"):
        f.unlink()
    _write(tmp_path, "bench_stdout_r02.json", _tpu_line(), age_s=2 * 86400)
    _, _, prov = _select_prior(tmp_path, monkeypatch)
    assert prov["within_current_round_bound"] is False
