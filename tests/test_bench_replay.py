"""Selection rules of the bench's TPU-artifact replay path.

When the tunnel is down at official-bench time, bench.py replays the best
TPU-backed watcher artifact (bench.py:_latest_tpu_artifact) instead of
emitting another CPU fallback. These rules decide what lands in the
round's official artifact, so they are pinned here:
- CPU-fallback / failed / already-replayed artifacts are never selected;
- experiment-sweep artifacts (non-default configs) are excluded;
- a target-comparable 8B line (vs_baseline non-null) beats a NEWER
  partial rescue artifact;
- artifacts older than the age bound are ignored.
"""

import json
import os
import time

import bench


def _write(dirpath, name, line, age_s=0.0):
    path = os.path.join(dirpath, name)
    with open(path, "w") as f:
        json.dump(line, f)
    if age_s:
        t = time.time() - age_s
        os.utime(path, (t, t))
    return path


def _tpu_line(metric="llama3_8b_int8_engine_tok_s_per_chip",
              value=2000.0, vs_baseline=1.0, **extra):
    return {"metric": metric, "value": value, "unit": "tok/s",
            "vs_baseline": vs_baseline,
            "details": {"platform": "tpu"}, **extra}


def _select(tmp_path, monkeypatch):
    monkeypatch.setenv("POLYKEY_BENCH_PERF_DIR", str(tmp_path))
    # Pin the age bound: an ambient operator override would change which
    # fixtures age out.
    monkeypatch.setenv("POLYKEY_BENCH_REPLAY_MAX_AGE_H", "14")
    return bench._latest_tpu_artifact()


def test_no_artifacts_returns_none(tmp_path, monkeypatch):
    assert _select(tmp_path, monkeypatch) is None


def test_ineligible_artifacts_skipped(tmp_path, monkeypatch):
    cpu = _tpu_line()
    cpu["details"]["platform"] = "cpu"
    _write(tmp_path, "bench_watcher_a.json", cpu)
    _write(tmp_path, "bench_watcher_b.json",
           {"metric": "bench_failed", "value": 0.0,
            "details": {"platform": "tpu"}})
    _write(tmp_path, "bench_watcher_c.json",
           _tpu_line(replayed_from="perf/earlier.json"))
    # Experiment artifacts run non-default configs: never the headline.
    _write(tmp_path, "bench_exp_kv8.json", _tpu_line(value=9999.0))
    assert _select(tmp_path, monkeypatch) is None


def test_8b_beats_newer_partial(tmp_path, monkeypatch):
    _write(tmp_path, "bench_watcher_full.json",
           _tpu_line(value=2345.6), age_s=3600)
    _write(tmp_path, "bench_watcher_rescue.json",
           _tpu_line(metric="llama-1b-bench_engine_tok_s_per_chip",
                     value=900.0, vs_baseline=None))
    path, line = _select(tmp_path, monkeypatch)
    assert path.endswith("bench_watcher_full.json")
    assert line["value"] == 2345.6


def test_newest_8b_wins_and_age_bound(tmp_path, monkeypatch):
    _write(tmp_path, "bench_watcher_old.json",
           _tpu_line(value=2100.0), age_s=7200)
    _write(tmp_path, "bench_watcher_new.json", _tpu_line(value=2200.0))
    path, line = _select(tmp_path, monkeypatch)
    assert path.endswith("bench_watcher_new.json")

    # Everything aged out -> no replay.
    for name in ("bench_watcher_old.json", "bench_watcher_new.json"):
        t = time.time() - 15 * 3600
        os.utime(os.path.join(tmp_path, name), (t, t))
    assert _select(tmp_path, monkeypatch) is None
