"""Autopilot controller tests (ISSUE 18).

Three layers, mirroring the module's pure/impure split:

1. The PURE decision core on canned snapshots — hysteresis bands,
   per-action cooldowns, hard bounds, null-verdict holds (no evidence,
   no verdict), the min-evidence gate, and no flapping under an
   oscillating synthetic signal.
2. The live-knob actuation surfaces (the knob-application audit):
   every actuated knob reaches an attribute the engine loop reads per
   iteration, so a mid-run actuation takes effect within one pass —
   pinned here so a refactor can't silently reintroduce the
   read-once-at-construction bug.
3. The Autopilot thread's lifecycle contracts: typed refuse-to-start
   when the signal plane is off, supervisor pause / setpoint re-apply /
   re-arm, and the DisaggPool elastic surface (scale-down drains
   before killing; a retiring worker's death never burns restart
   budget).
"""

import time
from dataclasses import replace

import pytest

from polykey_tpu.engine import autopilot as ap
from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.disagg_pool import (
    DEAD,
    DECODE,
    DRAINING,
    PREFILL,
    SERVING,
    DisaggPool,
    _Worker,
)
from polykey_tpu.engine.engine import GenRequest, InferenceEngine

CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    num_pages=64,
    max_seq_len=64,
    prefill_buckets=(16,),
    max_new_tokens_cap=32,
    default_max_new_tokens=8,
    decode_block_steps=4,
    signals_interval_s=0.05,
)

CFG = ap.AutopilotConfig(
    interval_s=0.05, cooldown_s=10.0, target_busy=0.75,
    lookahead_max=6, tier_min=1, tier_max=3,
    queue_high_s=0.3, queue_low_s=0.03, min_evidence_s=10.0,
)


def make_state() -> ap.ControllerState:
    state = ap.ControllerState()
    state.setpoints = {
        ap.LOOKAHEAD: 2, ap.PREFILL_BUDGET: 32,
        ap.RESTORE_SLOTS: 2, ap.RESIDENT_FLOOR: 8,
        ap.ROUTE_DELAY_WEIGHT: 1.0,
    }
    state.baselines = dict(state.setpoints)
    state.bounds = {
        ap.LOOKAHEAD: (2, 6), ap.PREFILL_BUDGET: (16, 64),
        ap.RESTORE_SLOTS: (2, 4), ap.RESIDENT_FLOOR: (8, 32),
        ap.ROUTE_DELAY_WEIGHT: (1.0, 8.0),
    }
    state.steps = {ap.PREFILL_BUDGET: 16, ap.RESIDENT_FLOOR: 4}
    return state


def summary(**kw) -> dict:
    base = {"covered_s": 60.0}
    base.update(kw)
    return base


# -- 1. pure decision core ---------------------------------------------------


class TestDecideLookahead:
    def test_deepens_on_stall_with_idle_device(self):
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=5.0, device_busy_fraction=0.4),
            make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.UP
        assert (d.old, d.new) == (2, 3)

    def test_holds_when_device_already_busy(self):
        # Stall evidence alone is not enough: a busy device means the
        # pipeline is not the bottleneck — deeper lookahead just adds
        # wasted-work exposure.
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=5.0, device_busy_fraction=0.9),
            make_state(), CFG, 100.0,
        )
        assert d is None

    def test_relaxes_toward_baseline_when_healthy(self):
        state = make_state()
        state.setpoints[ap.LOOKAHEAD] = 4
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=0.0, device_busy_fraction=0.9),
            state, CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN
        assert (d.old, d.new) == (4, 3)

    def test_never_relaxes_below_baseline(self):
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=0.0, device_busy_fraction=0.9),
            make_state(), CFG, 100.0,
        )
        assert d is None  # already at the boot depth

    def test_bounded_at_max(self):
        state = make_state()
        state.setpoints[ap.LOOKAHEAD] = 6
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=5.0, device_busy_fraction=0.4),
            state, CFG, 100.0,
        )
        assert d is None  # clamp leaves the value unchanged → no decision

    def test_null_reading_holds(self):
        # Idle engine: no dispatches → host_stall p95 is None, never 0.
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=None, device_busy_fraction=None),
            make_state(), CFG, 100.0,
        )
        assert d is None

    def test_inside_band_holds(self):
        # Between the edges (stall present but small, device mid-load):
        # neither the up edge nor the down edge — hysteresis holds.
        d = ap.decide_lookahead(
            summary(host_stall_ms_p95=0.5, device_busy_fraction=0.5),
            make_state(), CFG, 100.0,
        )
        assert d is None


class TestDecidePrefillBudget:
    def test_narrows_under_interactive_arrivals(self):
        d = ap.decide_prefill_budget(
            summary(arrival_rate_per_s=2.0), None, make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN
        assert (d.old, d.new) == (32, 16)

    def test_widens_when_quiet(self):
        d = ap.decide_prefill_budget(
            summary(arrival_rate_per_s=0.0), None, make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.UP
        assert (d.old, d.new) == (32, 48)

    def test_floor_is_one_chunk(self):
        state = make_state()
        state.setpoints[ap.PREFILL_BUDGET] = 16
        d = ap.decide_prefill_budget(
            summary(arrival_rate_per_s=2.0), None, state, CFG, 100.0,
        )
        assert d is None  # already at the chunk floor

    def test_no_arrival_evidence_holds(self):
        d = ap.decide_prefill_budget(
            summary(arrival_rate_per_s=None), None, make_state(), CFG, 100.0,
        )
        assert d is None

    def test_disagg_falls_back_to_pool_handoff_rate(self):
        pool_windows = {"1m": {
            "covered_s": 60.0, "handoffs": {"ok": 120, "failed": 0},
        }}
        d = ap.decide_prefill_budget(
            None, pool_windows, make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN


class TestDecideKvKnobs:
    def test_restore_slots_up_under_fault_pressure(self):
        d = ap.decide_restore_slots(
            summary(kv_fault_rate_per_min=90.0), make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.UP
        assert (d.old, d.new) == (2, 3)

    def test_restore_slots_decays_when_quiet(self):
        state = make_state()
        state.setpoints[ap.RESTORE_SLOTS] = 4
        d = ap.decide_restore_slots(
            summary(kv_fault_rate_per_min=0.0), state, CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN

    def test_resident_floor_up_under_fault_pressure(self):
        d = ap.decide_resident_floor(
            summary(kv_fault_rate_per_min=90.0), make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.UP
        assert (d.old, d.new) == (8, 12)

    def test_no_host_kv_tier_holds(self):
        state = make_state()
        del state.setpoints[ap.RESTORE_SLOTS]
        d = ap.decide_restore_slots(
            summary(kv_fault_rate_per_min=90.0), state, CFG, 100.0,
        )
        assert d is None


class TestDecideGamma:
    """Speculation-cap control (ISSUE 19): the windowed fleet-wide
    accept rate moves the engine's gamma cap between the low ladder
    rung and the boot value; the per-lane device dial handles
    variation inside the cap."""

    @staticmethod
    def spec_state(cap=4, low=2):
        state = make_state()
        state.setpoints[ap.SPEC_GAMMA] = cap
        state.baselines[ap.SPEC_GAMMA] = 4
        state.bounds[ap.SPEC_GAMMA] = (low, 4)
        return state

    def test_collapse_caps_at_low_rung(self):
        d = ap.decide_gamma(
            summary(spec_accept_rate=0.12), self.spec_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN
        assert (d.old, d.new) == (4, 2)

    def test_recovery_restores_boot_cap(self):
        d = ap.decide_gamma(
            summary(spec_accept_rate=0.8), self.spec_state(cap=2),
            CFG, 100.0,
        )
        assert d is not None and d.direction == ap.UP
        assert (d.old, d.new) == (2, 4)

    def test_inside_band_holds(self):
        # 0.45 sits between the 0.35/0.55 edges: hysteresis holds in
        # BOTH directions, whether the cap is up or already down.
        for cap in (4, 2):
            d = ap.decide_gamma(
                summary(spec_accept_rate=0.45), self.spec_state(cap=cap),
                CFG, 100.0,
            )
            assert d is None

    def test_already_at_low_rung_holds(self):
        d = ap.decide_gamma(
            summary(spec_accept_rate=0.12), self.spec_state(cap=2),
            CFG, 100.0,
        )
        assert d is None

    def test_already_at_boot_cap_holds(self):
        d = ap.decide_gamma(
            summary(spec_accept_rate=0.9), self.spec_state(), CFG, 100.0,
        )
        assert d is None

    def test_no_draft_evidence_holds(self):
        # No drafts proposed in the window → spec_accept_rate is None,
        # never 0.0 (a synthesized zero would cap a quiet engine).
        d = ap.decide_gamma(
            summary(spec_accept_rate=None), self.spec_state(), CFG, 100.0,
        )
        assert d is None

    def test_spec_off_never_arms(self):
        # knob_setpoints only exposes spec_gamma on draft-model engines;
        # without the setpoint the action holds forever.
        d = ap.decide_gamma(
            summary(spec_accept_rate=0.12), make_state(), CFG, 100.0,
        )
        assert d is None

    def test_cooldown_gates(self):
        state = self.spec_state()
        state.last_fired[ap.SPEC_GAMMA] = 95.0
        d = ap.decide_gamma(
            summary(spec_accept_rate=0.12), state, CFG, 100.0,
        )
        assert d is None  # 5s elapsed < 10s cooldown

    def test_setter_rung_snaps_and_gates_on_spec(self, engine):
        # The live setter: a non-spec engine reports 0 and holds; the
        # knob only actuates on draft-model engines (covered in
        # test_engine_spec.py's live-dial tests).
        assert engine.set_spec_gamma(2) == 0
        assert "spec_gamma" not in engine.knob_setpoints()


class TestDecideRouteWeights:
    @staticmethod
    def replicas(*p95s):
        return {
            i: {"windows": {"1m": {"covered_s": 60.0, "ttft_ms_p95": v}}}
            for i, v in enumerate(p95s)
        }

    def test_skew_doubles_delay_weight(self):
        d = ap.decide_route_weights(
            self.replicas(50.0, 900.0), make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.UP
        assert (d.old, d.new) == (1.0, 2.0)

    def test_healed_skew_decays(self):
        state = make_state()
        state.setpoints[ap.ROUTE_DELAY_WEIGHT] = 4.0
        d = ap.decide_route_weights(
            self.replicas(50.0, 60.0), state, CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN
        assert (d.old, d.new) == (4.0, 2.0)

    def test_single_replica_holds(self):
        assert ap.decide_route_weights(
            self.replicas(900.0), make_state(), CFG, 100.0,
        ) is None


class TestDecideScale:
    @staticmethod
    def tiers(delay, serving=1, total=None):
        return {DECODE: {
            "queue_delay_s": delay, "serving": serving,
            "total": serving if total is None else total,
        }}

    def test_scales_up_on_queue_pressure(self):
        d = ap.decide_scale(
            DECODE, self.tiers(1.0), make_state(), CFG, 100.0,
        )
        assert d is not None
        assert (d.action, d.direction) == (ap.SCALE_DECODE, ap.UP)

    def test_up_bounded_by_tier_max_including_booting(self):
        # Two serving + one still booting = three TOTAL: at tier_max the
        # in-flight spawn must not be doubled by another decision.
        d = ap.decide_scale(
            DECODE, self.tiers(1.0, serving=2, total=3),
            make_state(), CFG, 100.0,
        )
        assert d is None

    def test_up_waits_for_inflight_boot(self):
        # One serving + one booting, well under tier_max, pressure
        # present: a worker boot pays a compile storm, and stacking a
        # second starves the first — one boot in flight means hold.
        d = ap.decide_scale(
            DECODE, self.tiers(5.0, serving=1, total=2),
            make_state(), CFG, 100.0,
        )
        assert d is None

    def test_scales_down_with_headroom(self):
        d = ap.decide_scale(
            DECODE, self.tiers(0.0, serving=2), make_state(), CFG, 100.0,
        )
        assert d is not None and d.direction == ap.DOWN

    def test_never_below_tier_min(self):
        assert ap.decide_scale(
            DECODE, self.tiers(0.0, serving=1), make_state(), CFG, 100.0,
        ) is None

    def test_null_queue_delay_holds(self):
        # Empty tier / no heartbeat yet: None is "no evidence", and the
        # controller must not read it as "no delay" and scale down.
        assert ap.decide_scale(
            DECODE, self.tiers(None, serving=2), make_state(), CFG, 100.0,
        ) is None


class TestEvaluate:
    @staticmethod
    def snap(**agg):
        return {"aggregate": {"1m": summary(**agg)}}

    def test_cooldown_gates_repeat_decisions(self):
        state = make_state()
        snap = self.snap(host_stall_ms_p95=5.0, device_busy_fraction=0.4)
        first = ap.evaluate(snap, state, CFG, 100.0)
        assert any(d.action == ap.LOOKAHEAD for d in first)
        # Simulate _apply's bookkeeping, then re-evaluate inside the
        # cooldown window: same evidence, no decision.
        state.last_fired[ap.LOOKAHEAD] = 100.0
        state.setpoints[ap.LOOKAHEAD] = 3
        assert not any(
            d.action == ap.LOOKAHEAD
            for d in ap.evaluate(snap, state, CFG, 105.0)
        )
        # Past the cooldown the evidence fires again.
        assert any(
            d.action == ap.LOOKAHEAD
            for d in ap.evaluate(snap, state, CFG, 111.0)
        )

    def test_min_evidence_gate(self):
        snap = {"aggregate": {"1m": {
            "covered_s": 1.0, "host_stall_ms_p95": 5.0,
            "device_busy_fraction": 0.4,
        }}}
        assert ap.evaluate(snap, make_state(), CFG, 100.0) == []

    def test_no_flapping_under_oscillating_signal(self):
        # A signal bouncing INSIDE the hysteresis band must produce
        # zero decisions no matter how long it oscillates.
        state = make_state()
        decisions = []
        for i in range(50):
            stall = 0.8 if i % 2 else 0.1   # below the 1.0ms up edge
            busy = 0.5                       # below the down edge's target
            snap = self.snap(
                host_stall_ms_p95=stall, device_busy_fraction=busy,
                arrival_rate_per_s=0.2,      # inside [0.05, 0.5]
                kv_fault_rate_per_min=10.0,  # inside (0, 30]
            )
            decisions += ap.evaluate(snap, state, CFG, 100.0 + i)
        assert decisions == []

    def test_empty_snapshot_holds_everything(self):
        assert ap.evaluate({}, make_state(), CFG, 100.0) == []


# -- 2. live-knob actuation (the knob-application audit) ---------------------


@pytest.fixture(scope="module")
def engine():
    eng = InferenceEngine(CONFIG)
    yield eng
    eng.shutdown()


class TestLiveKnobSetters:
    def test_lookahead_lands_on_loop_attribute(self, engine):
        old = engine._depth
        try:
            assert engine.set_lookahead(5) == 5
            # _depth_target recomputes from _depth on EVERY dispatch:
            # the attribute the setter wrote is the one the loop reads.
            assert engine._depth == 5
            assert engine.set_lookahead(0) == 1      # clamp floor
            assert engine.set_lookahead(999) == 64   # clamp ceiling
        finally:
            engine.set_lookahead(old)

    def test_prefill_budget_lands_on_loop_attribute(self, engine):
        old = engine._prefill_budget
        try:
            applied = engine.set_prefill_budget(engine._chunk * 3)
            assert engine._prefill_budget == applied == engine._chunk * 3
            # Floor: the budget may never starve a chunk (deadlock).
            assert engine.set_prefill_budget(1) == engine._chunk
        finally:
            engine.set_prefill_budget(old)

    def test_knob_setpoints_reports_live_values(self, engine):
        old = engine._depth
        try:
            engine.set_lookahead(4)
            assert engine.knob_setpoints()["lookahead"] == 4
        finally:
            engine.set_lookahead(old)

    def test_actuation_mid_run_takes_effect(self, engine):
        # Behavioral pin: actuate mid-run, then complete a generation —
        # the engine loop runs with the new setpoints (it reads the
        # attributes per iteration; nothing caches the old values).
        engine.set_lookahead(3)
        engine.set_prefill_budget(engine._chunk * 2)
        req = GenRequest(prompt="hi", max_new_tokens=4)
        engine.submit(req)
        deadline = time.monotonic() + 30
        done = False
        while time.monotonic() < deadline:
            kind, _val = req.out.get(timeout=30)
            if kind in ("done", "error"):
                done = kind == "done"
                break
        assert done

    def test_apply_engine_knobs_maps_and_clamps(self, engine):
        old = engine._depth
        try:
            applied = ap.apply_engine_knobs(
                engine, {"lookahead": 999, "unknown_knob": 7},
            )
            assert applied == {"lookahead": 64}
        finally:
            engine.set_lookahead(old)

    def test_restore_slots_setter_requires_host_kv_engine(self, engine):
        # This config has no host-KV tier, so the setter still clamps
        # and writes the live attribute the restore loop would read.
        assert engine.set_kv_restore_slots(3) == 3
        assert engine._restore_slots == 3


class TestLiveRouteWeights:
    def test_route_weights_live_on_pool(self):
        from polykey_tpu.engine.replica_pool import ReplicaPool

        pool = ReplicaPool(replace(CONFIG, replicas=2))
        assert pool.set_route_weights(delay=4.0) == (1.0, 4.0)
        assert pool._route_delay_weight == 4.0   # what _route reads
        assert pool.set_route_weights(prefix=0.5) == (0.5, 4.0)
        setpoints = pool.knob_setpoints()
        assert setpoints["route_delay_weight"] == 4.0


# -- 3. lifecycle: refuse-to-start, pause/re-arm, elastic pool ---------------


class TestRefuseToStart:
    def test_typed_error_when_signal_plane_off(self):
        eng = InferenceEngine(replace(CONFIG, signals_interval_s=0.0))
        try:
            with pytest.raises(ap.AutopilotUnavailableError):
                ap.Autopilot(eng, config=CFG).start()
        finally:
            eng.shutdown()

    def test_starts_and_publishes_on_target(self, engine):
        pilot = ap.Autopilot(engine, config=CFG).start()
        try:
            assert engine.autopilot is pilot
            from polykey_tpu.obs.signals import signals_snapshot

            assert "autopilot" in signals_snapshot(engine)
        finally:
            pilot.stop()
        assert engine.autopilot is None


class TestPauseRearm:
    def test_pause_blocks_ticks_and_restart_reapplies(self, engine):
        pilot = ap.Autopilot(engine, config=CFG).start()
        try:
            pilot.state.setpoints[ap.LOOKAHEAD] = 5
            pilot._on_trip(engine, "watchdog stall")
            assert pilot.paused
            assert pilot.tick(now=100.0) == []   # paused → no control
            # The "fresh engine" after a supervised restart boots with
            # config-default knobs; the restart listener must re-apply
            # the CURRENT setpoints before re-arming.
            class FreshEngine:
                def set_lookahead(self, depth):
                    self.depth = depth
                    return depth

            fresh = FreshEngine()
            pilot._on_restart(fresh)
            assert fresh.depth == 5
            assert not pilot.paused
        finally:
            pilot.stop()

    def test_snapshot_shape(self, engine):
        pilot = ap.Autopilot(engine, config=CFG).start()
        try:
            snap = pilot.snapshot()
            assert snap["enabled"] is True
            assert snap["paused"] is False
            assert isinstance(snap["setpoints"], dict)
            assert snap["decisions"] == []
        finally:
            pilot.stop()


def make_pool() -> DisaggPool:
    pool = DisaggPool(replace(CONFIG, max_queue_depth=4))
    for tier in (PREFILL, DECODE):
        for i in range(2):
            worker = _Worker(tier=tier, index=i, state=SERVING,
                             addr=("127.0.0.1", 1))   # nothing listens
            worker.ping = {"queue_delay_s": 0.01, "load": 0.1}
            pool.workers.append(worker)
    return pool


class TestElasticPool:
    def test_tier_now_shape_and_null_verdict(self):
        pool = make_pool()
        tiers = pool.tier_now()
        assert tiers[DECODE]["serving"] == 2
        assert tiers[DECODE]["queue_delay_s"] == 0.01
        for worker in pool.workers:
            worker.ping = {}
        assert pool.tier_now()[DECODE]["queue_delay_s"] is None

    def test_scale_down_drains_before_kill(self):
        pool = make_pool()
        # Grab the victim BEFORE actuating: the fake addr refuses
        # connections instantly, so the drain thread can finish and
        # remove the worker from the pool before this thread resumes.
        victim = next(w for w in pool.workers
                      if w.tier == DECODE and w.index == 1)
        name = pool.scale_down(DECODE)
        assert name == "decode/1"   # highest index first
        # The FIRST observable effect is DRAINING (out of routing) with
        # the retiring mark — the kill only happens after the drain
        # thread sees an idle worker (or its connection is already
        # gone, in which case DEAD is a legitimate sighting).
        assert victim.retiring
        assert victim.state in (DRAINING, DEAD)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if victim not in pool.workers:
                break
            time.sleep(0.05)
        assert victim not in pool.workers
        assert victim.state == DEAD

    def test_scale_down_refuses_last_serving_worker(self):
        pool = make_pool()
        pool.workers = [w for w in pool.workers
                        if not (w.tier == DECODE and w.index == 1)]
        assert pool.scale_down(DECODE) is None

    def test_retiring_worker_death_never_respawns(self):
        pool = make_pool()
        victim = next(w for w in pool.workers
                      if w.tier == DECODE and w.index == 1)
        victim.retiring = True
        victim.state = DRAINING
        pool._on_worker_down(victim, "sigkill mid-drain")
        assert victim.state == DEAD
        assert victim not in pool.workers
        assert pool.tier_restores[DECODE] == 0   # no restart burned

    def test_scale_up_refuses_without_process_factory(self):
        pool = make_pool()   # test-constructed: no _seed/_spawner wiring
        assert pool.scale_up(DECODE) is None

    def test_apply_knobs_remembers_setpoints(self):
        pool = make_pool()
        pool.apply_knobs({"lookahead": 4})
        assert pool._knob_setpoints == {"lookahead": 4}

    def test_signals_available_follows_interval(self):
        from polykey_tpu.obs.signals import signals_available

        assert signals_available(make_pool())
        off = DisaggPool(replace(CONFIG, signals_interval_s=0.0))
        assert not signals_available(off)
