"""Client resilience tests (gateway/client.py) against a flaky
in-process server: exponential backoff with jitter on UNAVAILABLE and
RESOURCE_EXHAUSTED, the retry-after-ms trailing-metadata hint honored,
DEADLINE_EXCEEDED never retried, and streams never retried once a chunk
has been observed."""

import io
import types

import grpc
import pytest

from polykey_tpu.gateway import client as client_mod
from polykey_tpu.gateway import errors
from polykey_tpu.gateway import server as gateway_server
from polykey_tpu.gateway.client import Client, RetryPolicy
from polykey_tpu.gateway.jsonlog import Logger
from polykey_tpu.gateway.service import Service
from polykey_tpu.proto import common_v2_pb2 as cmn
from polykey_tpu.proto import polykey_v2_pb2 as pk


class _ScriptedService(Service):
    """Pops one action per call: an exception instance to raise, or None
    to succeed. Stream calls optionally yield a delta BEFORE raising to
    model mid-stream failure."""

    def __init__(self, script, fail_mid_stream=False):
        self.script = list(script)
        self.fail_mid_stream = fail_mid_stream
        self.calls = 0

    def _next_action(self):
        self.calls += 1
        return self.script.pop(0) if self.script else None

    def execute_tool(self, tool_name, parameters, secret_id, metadata):
        action = self._next_action()
        if action is not None:
            raise action
        return pk.ExecuteToolResponse(
            status=cmn.Status(code=200, message="ok"),
            string_output="flaky success",
        )

    def execute_tool_stream(self, tool_name, parameters, secret_id, metadata):
        action = self._next_action()
        if action is not None and self.fail_mid_stream:
            yield pk.ExecuteToolStreamChunk(delta="partial")
        if action is not None:
            raise action
        yield pk.ExecuteToolStreamChunk(delta="whole")
        yield pk.ExecuteToolStreamChunk(
            final=True, status=cmn.Status(code=200, message="ok")
        )


@pytest.fixture()
def flaky_stack():
    """(make_client, service_holder): boots a server around a scripted
    service and builds a Client with a recording no-op sleep."""
    started = []

    def make(script, fail_mid_stream=False, max_attempts=4):
        service = _ScriptedService(script, fail_mid_stream=fail_mid_stream)
        server, _, port = gateway_server.build_server(
            service, Logger(stream=io.StringIO()), address="127.0.0.1:0"
        )
        server.start()
        sleeps: list[float] = []
        policy = RetryPolicy(
            max_attempts=max_attempts, base_delay_s=0.01,
            sleep=sleeps.append,
        )
        cfg = types.SimpleNamespace(
            server_address=f"127.0.0.1:{port}", timeout=5.0
        )
        cli = Client(cfg, Logger(stream=io.StringIO()), retry=policy)
        started.append((server, cli))
        return cli, service, sleeps

    yield make
    for server, cli in started:
        cli.close()
        server.stop(grace=None)


def _request():
    return pk.ExecuteToolRequest(tool_name="example_tool")


def test_unary_retries_unavailable_then_succeeds(flaky_stack):
    cli, service, sleeps = flaky_stack(
        [errors.UnavailableError("engine restarting"),
         errors.UnavailableError("engine restarting")]
    )
    resp = cli.execute_tool(_request(), timeout=5)
    assert resp.string_output == "flaky success"
    assert service.calls == 3
    assert len(sleeps) == 2
    # Exponential: the second wait's jitter floor exceeds half the first
    # attempt's cap (0.01 * 2**1 * 0.5 >= 0.01 * 0.5 * 2).
    assert all(delay > 0 for delay in sleeps)


def test_unary_honors_retry_after_hint(flaky_stack):
    cli, service, sleeps = flaky_stack(
        [errors.ResourceExhaustedError("queue full", retry_after_ms=80)]
    )
    resp = cli.execute_tool(_request(), timeout=5)
    assert resp.string_output == "flaky success"
    assert service.calls == 2
    assert len(sleeps) == 1
    # Hint replaces computed backoff: 80ms scaled by at most +25% jitter.
    assert 0.08 <= sleeps[0] <= 0.08 * 1.25 + 1e-9


def test_unary_never_retries_deadline_exceeded(flaky_stack):
    cli, service, sleeps = flaky_stack(
        [errors.DeadlineExceededError("deadline exceeded while queued")]
    )
    with pytest.raises(grpc.RpcError) as err:
        cli.execute_tool(_request(), timeout=5)
    assert err.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert service.calls == 1
    assert sleeps == []


def test_unary_gives_up_after_max_attempts(flaky_stack):
    cli, service, sleeps = flaky_stack(
        [errors.UnavailableError("down")] * 5, max_attempts=3
    )
    with pytest.raises(grpc.RpcError) as err:
        cli.execute_tool(_request(), timeout=5)
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE
    assert service.calls == 3
    assert len(sleeps) == 2


def test_stream_retries_before_first_chunk(flaky_stack):
    cli, service, sleeps = flaky_stack(
        [errors.UnavailableError("engine restarting")]
    )
    text = cli.execute_tool_stream(_request(), timeout=5)
    assert text == "whole"
    assert service.calls == 2
    assert len(sleeps) == 1


def test_stream_never_retries_mid_stream(flaky_stack):
    cli, service, sleeps = flaky_stack(
        [errors.UnavailableError("engine died mid-decode")],
        fail_mid_stream=True,
    )
    with pytest.raises(grpc.RpcError) as err:
        cli.execute_tool_stream(_request(), timeout=5)
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE
    # A chunk was observed: retrying would replay output. One call only.
    assert service.calls == 1
    assert sleeps == []


def test_retry_none_disables_retries():
    # retry=None → at-most-once: a retryable code still fails immediately
    # (non-idempotent tool calls must not silently duplicate work).
    service = _ScriptedService([errors.UnavailableError("down")])
    server, _, port = gateway_server.build_server(
        service, Logger(stream=io.StringIO()), address="127.0.0.1:0"
    )
    server.start()
    cfg = types.SimpleNamespace(server_address=f"127.0.0.1:{port}", timeout=5.0)
    cli = Client(cfg, Logger(stream=io.StringIO()), retry=None)
    try:
        with pytest.raises(grpc.RpcError) as err:
            cli.execute_tool(_request(), timeout=5)
        assert err.value.code() == grpc.StatusCode.UNAVAILABLE
        assert service.calls == 1
    finally:
        cli.close()
        server.stop(grace=None)


class _ResumableService(Service):
    """Models the ISSUE 9 mid-stream failure contract: the first stream
    yields a prefix then dies UNAVAILABLE with resume-supported +
    resume-tokens trailers; a follow-up call carrying received_tokens
    streams only the suffix. Tokens are 1:1 with characters here."""

    FULL = "abcdef"

    def __init__(self, fail_after: int = 3):
        self.fail_after = fail_after
        self.calls = 0
        self.received_tokens_seen = []

    def execute_tool(self, tool_name, parameters, secret_id, metadata):
        raise NotImplementedError

    def execute_tool_stream(self, tool_name, parameters, secret_id, metadata):
        self.calls += 1
        params = dict(parameters) if parameters is not None else {}
        received = int(params.get("received_tokens", 0))
        self.received_tokens_seen.append(received)
        if received == 0 and self.calls == 1:
            yield pk.ExecuteToolStreamChunk(delta=self.FULL[:self.fail_after])
            raise errors.UnavailableError(
                "engine restarting: watchdog trip",
                trailers=(
                    (errors.RESUME_SUPPORTED_KEY, "1"),
                    (errors.RESUME_TOKENS_KEY, str(self.fail_after)),
                ),
            )
        yield pk.ExecuteToolStreamChunk(delta=self.FULL[received:])
        yield pk.ExecuteToolStreamChunk(
            final=True, status=cmn.Status(code=200, message="ok")
        )


@pytest.fixture()
def resumable_stack():
    started = []

    def make(fail_after=3, max_attempts=4):
        service = _ResumableService(fail_after=fail_after)
        server, _, port = gateway_server.build_server(
            service, Logger(stream=io.StringIO()), address="127.0.0.1:0"
        )
        server.start()
        sleeps: list[float] = []
        policy = RetryPolicy(
            max_attempts=max_attempts, base_delay_s=0.01,
            sleep=sleeps.append,
        )
        cfg = types.SimpleNamespace(
            server_address=f"127.0.0.1:{port}", timeout=5.0
        )
        cli = Client(cfg, Logger(stream=io.StringIO()), retry=policy)
        started.append((server, cli))
        return cli, service, sleeps

    yield make
    for server, cli in started:
        cli.close()
        server.stop(grace=None)


def test_stream_resumes_on_resume_supported_trailer(resumable_stack):
    # Mid-stream UNAVAILABLE *with* the resume trailers IS retried —
    # with received_tokens — and the result concatenates prefix+suffix
    # without replaying anything.
    cli, service, sleeps = resumable_stack(fail_after=3)
    text = cli.execute_tool_stream(_request(), timeout=5)
    assert text == _ResumableService.FULL
    assert service.calls == 2
    assert service.received_tokens_seen == [0, 3]
    assert len(sleeps) == 1


def test_stream_resume_respects_retry_budget(resumable_stack):
    # retry=None (or an exhausted budget) must not resume either — the
    # resume path rides the same policy as ordinary retries.
    service = _ResumableService(fail_after=2)
    server, _, port = gateway_server.build_server(
        service, Logger(stream=io.StringIO()), address="127.0.0.1:0"
    )
    server.start()
    cfg = types.SimpleNamespace(server_address=f"127.0.0.1:{port}", timeout=5.0)
    cli = Client(cfg, Logger(stream=io.StringIO()), retry=None)
    try:
        with pytest.raises(grpc.RpcError) as err:
            cli.execute_tool_stream(_request(), timeout=5)
        assert err.value.code() == grpc.StatusCode.UNAVAILABLE
        assert service.calls == 1
    finally:
        cli.close()
        server.stop(grace=None)


def test_resume_tokens_parse_helper():
    class _Err:
        def __init__(self, md):
            self._md = md

        def trailing_metadata(self):
            return self._md

    ok = _Err(((errors.RESUME_SUPPORTED_KEY, "1"),
               (errors.RESUME_TOKENS_KEY, "17")))
    assert client_mod.resume_tokens_from(ok) == 17
    assert client_mod.resume_tokens_from(
        _Err(((errors.RESUME_TOKENS_KEY, "17"),))
    ) is None   # no resume-supported flag
    assert client_mod.resume_tokens_from(
        _Err(((errors.RESUME_SUPPORTED_KEY, "1"),))
    ) is None   # flag without a count is malformed
    assert client_mod.resume_tokens_from(_Err(None)) is None


def test_retry_after_parse_helpers():
    class _Err:
        def __init__(self, md):
            self._md = md

        def trailing_metadata(self):
            return self._md

    assert client_mod.retry_after_ms_from(_Err((("retry-after-ms", "120"),))) == 120
    assert client_mod.retry_after_ms_from(_Err((("other", "1"),))) is None
    assert client_mod.retry_after_ms_from(_Err((("retry-after-ms", "nan!"),))) is None
    assert client_mod.retry_after_ms_from(_Err(None)) is None


def test_unavailable_carries_no_healthy_replica_retry_hint(flaky_stack):
    """ISSUE 13 satellite: the no-healthy-replica UNAVAILABLE (replica
    pool submit fall-through) must carry retry-after-ms and the client
    must wait on the SERVER's recovery estimate, exactly like a shed —
    previously only the shed path attached the hint, so clients hammered
    a recovering tier at their own (faster) backoff schedule."""
    from polykey_tpu.engine.engine import EngineDeadError

    class _DeadPoolService(_ScriptedService):
        def execute_tool(self, tool_name, parameters, secret_id, metadata):
            self.calls += 1
            if self.script:
                self.script.pop(0)
                # The exact mapping tpu_service._submit applies to a
                # pool's no-healthy-replica EngineDeadError.
                try:
                    raise EngineDeadError(
                        "no serving replica available", retry_after_ms=120
                    )
                except EngineDeadError as e:
                    trailers = ((errors.RETRY_AFTER_MS_KEY, "120"),)
                    raise errors.UnavailableError(str(e), trailers=trailers)
            return pk.ExecuteToolResponse(
                status=cmn.Status(code=200, message="ok"),
                string_output="recovered",
            )

    cli, service, sleeps = flaky_stack([object()])
    # Swap the scripted service's behavior for the dead-pool shape.
    service.__class__ = _DeadPoolService
    resp = cli.execute_tool(_request(), timeout=5)
    assert resp.string_output == "recovered"
    assert len(sleeps) == 1
    # The 120ms hint (not the 10ms computed backoff) drives the wait,
    # scaled by at most +25% jitter — proof the trailer was honored.
    assert 0.12 <= sleeps[0] <= 0.12 * 1.25 + 1e-9


def test_replica_pool_dead_error_maps_to_hinted_unavailable():
    """The service-layer mapping itself: an EngineDeadError carrying
    retry_after_ms becomes UNAVAILABLE with the retry-after-ms trailer
    (and one without the hint stays trailer-free)."""
    from polykey_tpu.engine.engine import EngineDeadError
    from polykey_tpu.gateway.tpu_service import TpuService

    class _DeadEngine:
        def submit(self, request):
            raise EngineDeadError("no serving replica available",
                                  retry_after_ms=250)

    service = TpuService.__new__(TpuService)
    service.engine = _DeadEngine()
    with pytest.raises(errors.UnavailableError) as err:
        service._submit(object())
    assert dict(err.value.trailing_metadata()) == {
        errors.RETRY_AFTER_MS_KEY: "250"
    }

    class _DeadEngineNoHint:
        def submit(self, request):
            raise EngineDeadError("engine is shut down")

    service.engine = _DeadEngineNoHint()
    with pytest.raises(errors.UnavailableError) as err:
        service._submit(object())
    assert err.value.trailing_metadata() == ()


def test_pool_recovery_hint_estimates_from_supervisor_interval():
    """ReplicaPool._recovery_hint_ms: a DRAINING/RESTARTING replica
    means a supervised restart is in flight — the hint derives from the
    supervisor poll interval; all-DEAD hints the conservative second."""
    from polykey_tpu.engine.replica_pool import (
        DEAD, DRAINING, ReplicaPool, _Replica,
    )
    from polykey_tpu.engine.config import EngineConfig

    pool = ReplicaPool.__new__(ReplicaPool)
    pool.config = EngineConfig()
    pool._lock = __import__("threading").Lock()
    pool._supervisor_interval_s = 0.25
    pool.replicas = [
        _Replica(index=0, engine=None, watchdog=None, supervisor=None,
                 state=DRAINING),
        _Replica(index=1, engine=None, watchdog=None, supervisor=None,
                 state=DEAD),
    ]
    assert pool._recovery_hint_ms() == 500       # 2 x 250ms poll
    pool.replicas[0].state = DEAD
    assert pool._recovery_hint_ms() == 1000      # platform recycle
    pool.replicas = []
    assert pool._recovery_hint_ms() is None
