"""Worker process for the 2-process jax.distributed test (not collected
by pytest — no test_ prefix; launched by tests/test_distributed_multiproc.py
and scripts/run_multiproc_demo.sh).

Each process owns 2 virtual CPU devices; `initialize_from_env` joins them
into one 4-device global runtime (the CPU stand-in for one host per ICI
slice), the hybrid DCN mesh puts tp inside a process and dp across the
process boundary, and one train step + one paged serving step execute with
the gradient all-reduce / logit collectives actually crossing the process
boundary over gloo. Output is one JSON line per rank with the loss and a
serving-logit checksum; the parent asserts both ranks agree and match the
single-process reference (VERDICT r3 missing #4 / coverage row #30 — the
multi-process jax.distributed path had never executed anywhere).

Usage: python tests/multiproc_worker.py <rank> <nprocs> <port>
"""

import json
import os
import sys


def train_and_serve(mesh) -> dict:
    """One full train step + one paged serving step on `mesh`, fixed
    seeds/batch. Shared by the worker ranks AND the in-process reference
    (tests/test_distributed_multiproc.py) so the equivalence assertion
    always compares the same computation."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from polykey_tpu.engine.kv_cache import init_paged_kv
    from polykey_tpu.models.config import TINY_LLAMA
    from polykey_tpu.models.transformer import (
        forward_paged,
        init_params,
        unembed,
    )
    from polykey_tpu.parallel.sharding import (
        batch_sharding,
        paged_kv_sharding,
        shard_params,
    )
    from polykey_tpu.train import make_train_step

    cfg = dataclasses.replace(
        TINY_LLAMA, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
    )
    # Same seeds in every process → identical host-side values; device_put
    # onto the global mesh gives each process its addressable shards.
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    serve_params = shard_params(
        init_params(jax.random.PRNGKey(0), cfg, jnp.float32), cfg, mesh
    )

    init_state, train_step, shard_batch = make_train_step(cfg, mesh)
    state = init_state(params)

    B, T = 4, 16
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (B, T), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    stoks, stargs, spos = shard_batch(tokens, targets, positions)
    state, loss = train_step(state, stoks, stargs, spos)
    # Replicated scalar: addressable on every process.
    loss = float(jax.block_until_ready(loss))

    # Paged serving forward on the same mesh (disjoint per-row pages —
    # the engine's allocator invariant).
    paged = jax.device_put(
        init_paged_kv(cfg, num_pages=2 * B + 1, page_size=8,
                      dtype=jnp.float32),
        paged_kv_sharding(mesh),
    )
    page_tables = jax.device_put(
        jnp.arange(1, 2 * B + 1, dtype=jnp.int32).reshape(B, 2),
        batch_sharding(mesh, 2),
    )
    serve_tokens = jax.device_put(tokens[:, :8], batch_sharding(mesh, 2))
    serve_positions = jax.device_put(
        positions[:, :8], batch_sharding(mesh, 2))

    @jax.jit
    def serve_step(params, tokens, positions, paged, page_tables):
        hidden, paged = forward_paged(
            params, cfg, tokens, positions, paged, page_tables
        )
        logits = unembed(params, cfg, hidden[:, -1])
        # Reduce to a scalar checksum: jit replicates scalar outputs, so
        # every process can fetch it without a cross-process gather of
        # the logits.
        return jnp.sum(logits * logits), paged

    checksum, _ = serve_step(
        serve_params, serve_tokens, serve_positions, paged, page_tables
    )
    return {
        "loss": loss,
        "serve_checksum": float(jax.block_until_ready(checksum)),
    }


def main() -> int:
    rank, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    os.environ["POLYKEY_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["POLYKEY_NUM_PROCESSES"] = str(nprocs)
    os.environ["POLYKEY_PROCESS_ID"] = str(rank)

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    # The image pins JAX_PLATFORMS to its TPU plugin; override before the
    # backend initializes (same dance as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")

    from polykey_tpu.parallel.distributed import initialize_from_env

    if not initialize_from_env():
        print(json.dumps({"rank": rank, "error": "initialize_from_env "
                          "returned False"}))
        return 1
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.device_count() == 2 * nprocs, jax.device_count()

    from polykey_tpu.parallel.distributed import create_hybrid_mesh
    from polykey_tpu.parallel.mesh import MeshConfig

    # tp=2 inside each process ("slice"), dp=2 across the process
    # boundary — the layout rule under test: only dp traffic crosses DCN.
    mesh = create_hybrid_mesh(MeshConfig(tp=2), num_slices=nprocs)
    assert mesh.shape["dp"] == nprocs and mesh.shape["tp"] == 2

    metrics = train_and_serve(mesh)
    print(json.dumps({
        "rank": rank,
        "processes": jax.process_count(),
        "global_devices": jax.device_count(),
        **metrics,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
