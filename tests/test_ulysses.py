"""Ulysses attention tests: parity vs full attention and vs ring attention
on a simulated mesh (SURVEY §5's second long-context formulation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from polykey_tpu.ops.attention import attention, make_attention_mask
from polykey_tpu.ops.ring_attention import ring_attention_spmd
from polykey_tpu.ops.ulysses_attention import ulysses_attention_spmd

TOL = 2e-5


def _case(B, T, Hq, Hk, D, seed=0):
    return (
        jax.random.normal(jax.random.PRNGKey(seed), (B, T, Hq, D), jnp.float32),
        jax.random.normal(jax.random.PRNGKey(seed + 1), (B, T, Hk, D), jnp.float32),
        jax.random.normal(jax.random.PRNGKey(seed + 2), (B, T, Hk, D), jnp.float32),
    )


@pytest.mark.parametrize("softcap,win", [
    (None, None), (50.0, None), (None, 24), (30.0, 24),
])
def test_ulysses_matches_full_attention(softcap, win):
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    B, T, Hq, Hk, D = 2, 64, 8, 4, 32          # Hq, Hk divisible by sp=4
    q, k, v = _case(B, T, Hq, Hk, D)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    mask = make_attention_mask(pos, T, sliding_window=win)
    ref = attention(q, k, v, mask, scale=0.2, logit_softcap=softcap)
    w = None if win is None else jnp.int32(win)
    out = ulysses_attention_spmd(
        q, k, v, pos, pos, mesh, scale=0.2, logit_softcap=softcap,
        window=w, head_axis=None,
    )
    assert float(jnp.max(jnp.abs(ref - out))) < TOL


def test_ulysses_matches_ring():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    B, T, Hq, Hk, D = 2, 64, 8, 4, 16
    q, k, v = _case(B, T, Hq, Hk, D, seed=5)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    ring = ring_attention_spmd(
        q, k, v, pos, pos, mesh, scale=0.25, head_axis=None
    )
    uly = ulysses_attention_spmd(
        q, k, v, pos, pos, mesh, scale=0.25, head_axis=None
    )
    assert float(jnp.max(jnp.abs(ring - uly))) < TOL


def test_ulysses_with_tp_head_sharding():
    """tp shards heads first; Ulysses splits the per-device remainder over
    sp (needs (H/tp) % sp == 0)."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "sp", "tp"))
    B, T, Hq, Hk, D = 2, 32, 8, 4, 16          # per-device: Hq=4, Hk=2; sp=2
    q, k, v = _case(B, T, Hq, Hk, D, seed=7)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    ref = attention(q, k, v, make_attention_mask(pos, T), scale=0.25)
    out = ulysses_attention_spmd(q, k, v, pos, pos, mesh, scale=0.25)
    assert float(jnp.max(jnp.abs(ref - out))) < TOL


def test_ulysses_rejects_indivisible_heads():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "sp"))
    B, T, Hq, Hk, D = 1, 32, 8, 2, 16          # Hk=2 not divisible by sp=4
    q, k, v = _case(B, T, Hq, Hk, D)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    with pytest.raises(ValueError, match="ring attention instead"):
        ulysses_attention_spmd(
            q, k, v, pos, pos, mesh, scale=0.25, head_axis=None
        )


def test_train_step_with_ulysses():
    """make_train_step(sp_impl='ulysses') runs a full sharded train step on
    a dp×sp mesh and produces a finite loss."""
    import dataclasses

    from polykey_tpu.models.config import TINY_LLAMA
    from polykey_tpu.models.transformer import init_params
    from polykey_tpu.parallel.mesh import MeshConfig, create_mesh
    from polykey_tpu.train import make_train_step

    cfg = dataclasses.replace(TINY_LLAMA, num_heads=4, num_kv_heads=2)
    mesh = create_mesh(MeshConfig(dp=2, sp=2), devices=jax.devices()[:4])
    init_state, train_step, shard_batch = make_train_step(
        cfg, mesh, sp_impl="ulysses"
    )
    state = init_state(init_params(jax.random.PRNGKey(0), cfg, jnp.float32))

    B, T = 4, 16
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (B, T), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    tokens, targets, positions = shard_batch(tokens, targets, positions)

    state, loss = train_step(state, tokens, targets, positions)
    assert jnp.isfinite(jax.block_until_ready(loss))
