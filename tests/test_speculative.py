"""Speculative decoding tests.

The load-bearing invariant: greedy speculative output is EXACTLY the target
model's greedy decode, for any draft model — acceptance only changes speed,
never the token stream. (tests reference: the reference repo has no
speculative decoding; SURVEY.md §2b lists it as owed to the north star.)
"""

import dataclasses

import jax
import jax.numpy as jnp

from polykey_tpu.engine.sampling import SamplingParams
from polykey_tpu.models.config import TINY_LLAMA
from polykey_tpu.models.generate import generate
from polykey_tpu.models.speculative import speculative_generate
from polykey_tpu.models.transformer import init_params

TARGET_CFG = dataclasses.replace(TINY_LLAMA, name="spec-target")
DRAFT_CFG = dataclasses.replace(
    TINY_LLAMA, name="spec-draft", hidden_size=32, intermediate_size=64,
    num_layers=1, num_heads=2, num_kv_heads=1,
)


def _setup(seed=0, B=3, T=8):
    t_params = init_params(jax.random.PRNGKey(seed), TARGET_CFG, jnp.float32)
    d_params = init_params(jax.random.PRNGKey(seed + 7), DRAFT_CFG, jnp.float32)
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (B, T), 0, TARGET_CFG.vocab_size
    )
    seq_lens = jnp.full((B,), T, jnp.int32)
    return t_params, d_params, tokens, seq_lens


def test_greedy_speculative_equals_target_greedy():
    t_params, d_params, tokens, seq_lens = _setup()
    sampling = SamplingParams(max_new_tokens=24, temperature=0.0)
    key = jax.random.PRNGKey(2)

    ref, ref_n = generate(
        t_params, TARGET_CFG, tokens, seq_lens, key, sampling, max_len=64
    )
    out, out_n = speculative_generate(
        t_params, TARGET_CFG, d_params, DRAFT_CFG, tokens, seq_lens, key,
        sampling, max_len=64, gamma=4,
    )
    assert (out == ref).all(), (out, ref)
    assert (out_n == ref_n).all()


def test_greedy_self_draft_accepts_everything():
    """Draft == target → every proposal accepted; output still exact."""
    t_params, _, tokens, seq_lens = _setup()
    sampling = SamplingParams(max_new_tokens=16, temperature=0.0)
    key = jax.random.PRNGKey(3)

    ref, _ = generate(
        t_params, TARGET_CFG, tokens, seq_lens, key, sampling, max_len=64
    )
    out, _ = speculative_generate(
        t_params, TARGET_CFG, t_params, TARGET_CFG, tokens, seq_lens, key,
        sampling, max_len=64, gamma=3,
    )
    assert (out == ref).all()


def test_gamma_variants_agree():
    t_params, d_params, tokens, seq_lens = _setup(seed=5)
    sampling = SamplingParams(max_new_tokens=12, temperature=0.0)
    key = jax.random.PRNGKey(4)
    outs = [
        speculative_generate(
            t_params, TARGET_CFG, d_params, DRAFT_CFG, tokens, seq_lens,
            key, sampling, max_len=48, gamma=g,
        )[0]
        for g in (1, 2, 5)
    ]
    assert (outs[0] == outs[1]).all()
    assert (outs[1] == outs[2]).all()


def test_sampled_speculative_is_well_formed():
    """Temperature > 0: rejection sampling must emit the full budget of
    valid tokens (distribution equality is the Leviathan identity; here we
    check structure: counts, ranges, determinism under a fixed key)."""
    t_params, d_params, tokens, seq_lens = _setup(seed=9)
    sampling = SamplingParams(max_new_tokens=16, temperature=0.8)
    key = jax.random.PRNGKey(6)

    out, n = speculative_generate(
        t_params, TARGET_CFG, d_params, DRAFT_CFG, tokens, seq_lens, key,
        sampling, max_len=48, gamma=4,
    )
    assert (n == 16).all()          # eos_id=-1 → never stops early
    assert ((out >= 0) & (out < TARGET_CFG.vocab_size)).all()
    out2, _ = speculative_generate(
        t_params, TARGET_CFG, d_params, DRAFT_CFG, tokens, seq_lens, key,
        sampling, max_len=48, gamma=4,
    )
    assert (out == out2).all()      # same key → same stream


def test_eos_stops_rows_independently():
    t_params, d_params, tokens, seq_lens = _setup(seed=11)
    sampling = SamplingParams(max_new_tokens=20, temperature=0.0)
    key = jax.random.PRNGKey(8)
    ref, ref_n = generate(
        t_params, TARGET_CFG, tokens, seq_lens, key, sampling, max_len=64,
        eos_id=7,
    )
    out, out_n = speculative_generate(
        t_params, TARGET_CFG, d_params, DRAFT_CFG, tokens, seq_lens, key,
        sampling, max_len=64, gamma=4, eos_id=7,
    )
    assert (out_n == ref_n).all(), (out_n, ref_n)
    # Streams match up to each row's own end; past-eos filler is eos.
    for b in range(out.shape[0]):
        n = int(ref_n[b])
        assert (out[b, :n] == ref[b, :n]).all()


def test_self_draft_acceptance_rate_is_perfect():
    """Draft == target greedy must accept EVERY proposal in EVERY round.
    This is the regression canary for draft-cache bookkeeping: a KV hole
    (e.g. the last accepted draft's slot never written) leaves outputs
    exact but collapses acceptance from round 2 on."""
    t_params, _, tokens, seq_lens = _setup()
    sampling = SamplingParams(max_new_tokens=24, temperature=0.0)
    out, n, acc, prop = speculative_generate(
        t_params, TARGET_CFG, t_params, TARGET_CFG, tokens, seq_lens,
        jax.random.PRNGKey(3), sampling, max_len=64, gamma=4,
        return_stats=True,
    )
    assert int(acc) == int(prop), (int(acc), int(prop))
    assert int(prop) > 0


def test_filtered_sampling_is_rejected():
    import pytest

    t_params, d_params, tokens, seq_lens = _setup()
    with pytest.raises(ValueError, match="top_k/top_p"):
        speculative_generate(
            t_params, TARGET_CFG, d_params, DRAFT_CFG, tokens, seq_lens,
            jax.random.PRNGKey(0),
            SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9),
            max_len=64,
        )
