"""Unit tests for the fault-injection subsystem (polykey_tpu/faults.py).

The contract under test: POLYKEY_FAULTS unset ⇒ no injector exists at
all (the zero-overhead guarantee — engine injection points reduce to an
`is None` check); specs parse strictly (unknown points fail fast); fire
counts are exact and thread-safe; the module-shared injector survives
`get_injector()` round-trips so counts persist across engine restarts.
"""

import threading
import time

import pytest

from polykey_tpu import faults


@pytest.fixture(autouse=True)
def _clean_injector(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.clear()
    yield
    faults.clear()


def test_unset_env_means_no_injector():
    # The zero-overhead guarantee: nothing armed, nothing constructed —
    # engine call sites see None and skip all fault work.
    assert faults.get_injector() is None
    # The None is cached; repeated calls stay cheap and stable.
    assert faults.get_injector() is None


def test_env_spec_arms_injector(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "slow-step=0.01@3")
    faults.clear()  # force a re-read of the env
    inj = faults.get_injector()
    assert inj is not None
    assert inj._take("slow-step") == 0.01
    # Same shared instance on every call (counts persist across engines).
    assert faults.get_injector() is inj


def test_spec_grammar_defaults():
    inj = faults.install("step-stall")
    assert inj._take("step-stall") == 1.0          # default value
    assert inj._take("step-stall") == 1.0          # default: unlimited


def test_spec_count_exhausts():
    inj = faults.install("alloc-fail@2")
    assert inj._take("alloc-fail") is not None
    assert inj._take("alloc-fail") is not None
    assert inj._take("alloc-fail") is None
    assert inj.fired("alloc-fail") == 2


def test_spec_multiple_entries_and_separators():
    inj = faults.install("step-stall=2.5@1; slow-step=0.1, prefill-error@4")
    assert inj._take("step-stall") == 2.5
    assert inj._take("slow-step") == 0.1
    assert inj._take("prefill-error") == 1.0
    assert inj._take("tokenizer-error") is None    # unarmed point


def test_unknown_point_rejected():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.install("step-stal=1")              # typo must fail fast


def test_maybe_raise_and_type():
    inj = faults.install("tokenizer-error@1")
    with pytest.raises(RuntimeError, match="injected fault"):
        inj.maybe_raise("tokenizer-error")
    inj.maybe_raise("tokenizer-error")             # exhausted: no-op

    class Boom(Exception):
        pass

    inj2 = faults.install("alloc-fail@1")
    with pytest.raises(Boom):
        inj2.maybe_raise("alloc-fail", Boom)


def test_maybe_sleep_sleeps_roughly_value():
    inj = faults.install("slow-step=0.05@1")
    t0 = time.monotonic()
    inj.maybe_sleep("slow-step")
    assert time.monotonic() - t0 >= 0.04
    t0 = time.monotonic()
    inj.maybe_sleep("slow-step")                   # exhausted: instant
    assert time.monotonic() - t0 < 0.04


def test_take_is_thread_safe_and_exact():
    inj = faults.install("prefill-error@50")
    hits = []

    def worker():
        for _ in range(50):
            if inj._take("prefill-error") is not None:
                hits.append(1)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 50                         # exactly the budget
    assert inj.fired("prefill-error") == 50


def test_clear_rearms_env_read(monkeypatch):
    faults.install("slow-step=1")
    faults.clear()
    assert faults.get_injector() is None           # env unset
    monkeypatch.setenv(faults.ENV_VAR, "slow-step=2")
    faults.clear()
    inj = faults.get_injector()
    assert inj is not None and inj._take("slow-step") == 2.0


# -- tier-scoped targeting (ISSUE 13) -----------------------------------------


def test_tier_qualifier_scopes_consumption():
    inj = faults.install("worker-exit=0@1:tier=prefill")
    # Untiered callers (in-process engines) never consume a tiered fault.
    assert inj._take("worker-exit") is None
    assert inj._take("worker-exit", tier="decode") is None
    assert inj._take("worker-exit", tier="prefill") == 0.0
    assert inj._take("worker-exit", tier="prefill") is None   # spent
    assert inj.fired("worker-exit") == 1


def test_tier_and_replica_qualifiers_compose():
    inj = faults.install("kv-handoff-drop=1@1:replica=1:tier=decode")
    assert inj._take("kv-handoff-drop", replica=1, tier="prefill") is None
    assert inj._take("kv-handoff-drop", replica=0, tier="decode") is None
    assert inj._take("kv-handoff-drop", replica=1, tier="decode") == 1.0
    # Order of qualifiers must not matter.
    inj2 = faults.install("handoff-delay=0.2@1:tier=decode:replica=2")
    assert inj2._take("handoff-delay", replica=2, tier="decode") == 0.2


def test_tier_grammar_rejects_unknown_tier():
    with pytest.raises(ValueError, match="unknown fault tier"):
        faults.install("worker-exit:tier=frontend")
    with pytest.raises(ValueError, match="unknown fault qualifier"):
        faults.install("worker-exit:shard=2")


def test_tier_budget_persists_across_get_injector(monkeypatch):
    # The module-shared injector keeps tier budgets across engine
    # restarts exactly like replica budgets (the @N-spent-stays-spent
    # contract the chaos suite relies on).
    monkeypatch.setenv(faults.ENV_VAR, "handoff-delay=0.1@1:tier=prefill")
    faults.clear()
    inj = faults.get_injector()
    assert inj._take("handoff-delay", tier="prefill") == 0.1
    again = faults.get_injector()
    assert again is inj
    assert again._take("handoff-delay", tier="prefill") is None


def test_untargeted_fault_fires_on_any_tier():
    inj = faults.install("worker-exit=3@2")
    assert inj._take("worker-exit", tier="prefill") == 3.0
    assert inj._take("worker-exit", tier="decode") == 3.0
    assert inj._take("worker-exit") is None
