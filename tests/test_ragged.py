"""Ragged paged attention tests (ISSUE 12).

Three layers:
- KERNEL: the ragged Pallas kernel (interpret mode on CPU) against the
  per-token gather reference across the feature matrix — mixed
  prefill+decode streams, GQA, soft-capping, sliding windows,
  multi-tile ranges, decode-only and prefill-only streams, the int8-KV
  quantized variant, and the token-tile alignment gate's teeth.
- ENGINE: greedy output streams BIT-IDENTICAL between the ragged and
  bucketed dispatch modes at lookahead depths 1 and 2 (the acceptance
  criterion), sampled streams identical (draws key on (seed, position),
  never on batch shape), mixed-batch edge cases (prefill-only cold
  burst, budget-clipped chunk tail, decode-only steady state), the
  padding-waste accounting, the kill-switch, and config validation.
- CHAOS: PR 3 supervisor restart and PR 7 replica failover/resume
  semantics unchanged with the ragged path enabled.
"""

import dataclasses
import queue
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polykey_tpu import faults
from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.ops.paged_attention import quantize_kv_rows
from polykey_tpu.ops.ragged_paged_attention_kernel import (
    ragged_gather_attention,
    ragged_paged_attention,
)

TOL = 2e-5


@pytest.fixture(autouse=True)
def _clean_injector(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.clear()
    yield
    faults.clear()


# -- kernel: interpret-mode parity vs the gather reference --------------------


def _ragged_case(seed, seq_lens, kv_lens, *, N=32, ps=8, Hk=2, Hq=4,
                 D=32, P=8, pad_to=8, dtype=jnp.float32):
    """Build a ragged stream: ascending contiguous ranges (row padding
    at the tail), random pools/tables, plus the per-token view the
    gather reference consumes."""
    rng = np.random.default_rng(seed)
    seq_lens = np.asarray(seq_lens, np.int32)
    kv_lens = np.asarray(kv_lens, np.int32)
    S = len(seq_lens)
    starts = np.concatenate([[0], np.cumsum(seq_lens)[:-1]]).astype(np.int32)
    used = int(seq_lens.sum())
    T = -(-used // pad_to) * pad_to
    kp = jnp.asarray(rng.normal(size=(N, ps, Hk, D)), dtype)
    vp = jnp.asarray(rng.normal(size=(N, ps, Hk, D)), dtype)
    tables = rng.integers(1, N, size=(S, P)).astype(np.int32)
    q = jnp.asarray(rng.normal(size=(T, Hq, D)), dtype)
    rows = np.arange(T)
    sid = np.clip(np.searchsorted(starts, rows, side="right") - 1, 0, S - 1)
    in_seq = (rows >= starts[sid]) & (rows < starts[sid] + seq_lens[sid])
    pos = np.where(in_seq, kv_lens[sid] - seq_lens[sid] + rows - starts[sid], 0)
    tok_tables = np.where(in_seq[:, None], tables[sid], 0)
    return dict(
        q=q, kp=kp, vp=vp, tables=jnp.asarray(tables),
        starts=jnp.asarray(starts), lens=jnp.asarray(seq_lens),
        kvs=jnp.asarray(kv_lens), in_seq=in_seq,
        tok_tables=jnp.asarray(tok_tables), pos=jnp.asarray(pos),
    )


def _kernel_vs_gather(case, **kw):
    out_k = ragged_paged_attention(
        case["q"], case["kp"], case["vp"], case["tables"],
        case["starts"], case["lens"], case["kvs"], interpret=True, **kw,
    )
    out_g = ragged_gather_attention(
        case["q"], case["kp"], case["vp"], case["tok_tables"],
        case["pos"], scale=kw["scale"],
        logit_softcap=kw.get("logit_softcap"), window=kw.get("window"),
    )
    err = np.abs(np.asarray(out_k) - np.asarray(out_g))[case["in_seq"]]
    return float(err.max())


@pytest.mark.parametrize("softcap,win", [
    (None, None), (30.0, None), (None, 16), (30.0, 16),
])
def test_ragged_kernel_matches_gather(softcap, win):
    """Mixed stream: decode singles + prefill chunks, across the
    softcap/window matrix."""
    case = _ragged_case(0, seq_lens=[1, 11, 1, 5], kv_lens=[37, 20, 5, 48])
    w = None if win is None else jnp.int32(win)
    assert _kernel_vs_gather(
        case, scale=0.125, logit_softcap=softcap, window=w,
    ) < TOL


def test_ragged_kernel_multi_tile_ranges():
    """A chunk spanning several token tiles, odd page-group divisor
    (P % G != 0 exercises the ceil grid arithmetic)."""
    case = _ragged_case(
        1, seq_lens=[1, 29, 3, 1], kv_lens=[11, 29, 40, 63],
        P=7, N=64,
    )
    assert _kernel_vs_gather(case, scale=0.2, pages_per_block=2) < TOL


def test_ragged_kernel_decode_only_stream():
    """48 decode singles pack ceil(48/8) tiles — the steady-state shape."""
    lens = [1] * 48
    kvs = list(np.random.default_rng(3).integers(1, 60, size=48))
    case = _ragged_case(2, seq_lens=lens, kv_lens=kvs, N=64)
    assert _kernel_vs_gather(case, scale=0.125) < TOL


def test_ragged_kernel_prefill_only_stream():
    """One cold chunk, no decode rows (kv_len == seq_len: pure prefill
    attending over its own freshly-written window)."""
    case = _ragged_case(4, seq_lens=[24], kv_lens=[24])
    assert _kernel_vs_gather(case, scale=0.125) < TOL


def test_ragged_kernel_gqa_no_grouping():
    case = _ragged_case(5, seq_lens=[1, 9], kv_lens=[33, 9], Hk=4, Hq=4)
    assert _kernel_vs_gather(case, scale=0.125) < TOL


def test_ragged_kernel_quantized_matches_gather():
    """int8-KV variant: scale-page DMA + in-kernel dequant must match
    the int8 gather path tightly, and the fp gather loosely (bounded
    quantization error)."""
    case = _ragged_case(6, seq_lens=[1, 11, 4], kv_lens=[37, 20, 30])
    k8, ks = quantize_kv_rows(case["kp"])
    v8, vs = quantize_kv_rows(case["vp"])
    out_k = ragged_paged_attention(
        case["q"], (k8, ks), (v8, vs), case["tables"],
        case["starts"], case["lens"], case["kvs"],
        scale=0.125, interpret=True,
    )
    out_g = ragged_gather_attention(
        case["q"], (k8, ks), (v8, vs), case["tok_tables"], case["pos"],
        scale=0.125,
    )
    err = np.abs(np.asarray(out_k) - np.asarray(out_g))[case["in_seq"]]
    assert float(err.max()) < TOL
    out_fp = ragged_gather_attention(
        case["q"], case["kp"], case["vp"], case["tok_tables"],
        case["pos"], scale=0.125,
    )
    qerr = np.abs(np.asarray(out_k) - np.asarray(out_fp))[case["in_seq"]]
    assert float(qerr.max()) < 0.05   # quantization error, not a bug


def test_ragged_kernel_tile_alignment_raises():
    case = _ragged_case(7, seq_lens=[1, 4], kv_lens=[9, 4])
    with pytest.raises(ValueError, match="token_tile"):
        ragged_paged_attention(
            case["q"][:5], case["kp"], case["vp"], case["tables"],
            case["starts"], case["lens"], case["kvs"],
            scale=0.125, interpret=True,
        )


# -- engine: ragged vs bucketed bit-identity ----------------------------------


BASE = EngineConfig(
    model="tiny-llama", tokenizer="byte", dtype="float32",
    max_decode_slots=4, page_size=8, num_pages=64, max_seq_len=64,
    prefill_buckets=(16, 32), max_new_tokens_cap=16,
    decode_block_steps=4, lookahead_blocks=2,
    compile_warmup=False, supervise=False, signals_interval_s=0,
)
RAGGED = dataclasses.replace(BASE, ragged_dispatch=True)


def _drain(request, timeout=60.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _serve(config, specs, depth=None, seed=0, monkeypatch=None):
    if depth is not None:
        monkeypatch.setenv("POLYKEY_DISPATCH_LOOKAHEAD", str(depth))
    engine = InferenceEngine(config, seed=seed)
    try:
        requests = [GenRequest(**s) for s in specs]
        for r in requests:
            engine.submit(r)
        outs = []
        for r in requests:
            tokens, done, error = _drain(r)
            assert error is None, error
            assert done is not None
            outs.append(tokens)
        stats = engine.stats()
    finally:
        engine.shutdown()
    return outs, stats


@pytest.mark.parametrize("depth", [1, 2])
def test_ragged_greedy_streams_bit_identical(depth, monkeypatch):
    """THE acceptance criterion: greedy output streams are bit-identical
    between the ragged and bucketed paths at lookahead depths 1 and 2 —
    short prompts (admissions), a beyond-bucket prompt (chunk
    advancement), and concurrent decode (mixed dispatches)."""
    specs = [
        dict(prompt="hi", max_new_tokens=8, seed=11),
        dict(prompt="abcdefgh" * 2, max_new_tokens=8, seed=11),
        dict(prompt="abcdefgh" * 6, max_new_tokens=8, seed=11),  # chunked
        dict(prompt="xyz", max_new_tokens=8, seed=11),
    ]
    bucketed, _ = _serve(BASE, specs, depth, monkeypatch=monkeypatch)
    ragged, stats = _serve(RAGGED, specs, depth, monkeypatch=monkeypatch)
    assert ragged == bucketed
    assert stats["ragged"] is True


def test_ragged_sampled_streams_identical():
    """Sampled draws key on fold_in(seed, position) — batch- and
    path-independent, so even sampled streams match across modes."""
    specs = [
        dict(prompt="hello world", max_new_tokens=6, temperature=0.9,
             top_p=0.8, top_k=5, seed=42),
        dict(prompt="abcdefgh" * 3, max_new_tokens=6, temperature=1.0,
             seed=7),
    ]
    bucketed, _ = _serve(BASE, specs)
    ragged, _ = _serve(RAGGED, specs)
    assert ragged == bucketed


def test_ragged_prefill_only_cold_burst():
    """Cold burst filling every slot from idle: more prompt tokens than
    one ragged stream holds, so admission ranges span several
    prefill-only dispatches — all streams complete and match the
    bucketed mode."""
    specs = [
        dict(prompt="abcdefgh" * 3, max_new_tokens=4, seed=3)
        for _ in range(4)
    ]
    bucketed, _ = _serve(BASE, specs)
    ragged, stats = _serve(RAGGED, specs)
    assert ragged == bucketed
    assert stats["tokens_useful"] > 0


def test_ragged_budget_clipped_chunk_tail(monkeypatch):
    """A long prompt whose chunk ranges clip against the stream width /
    budget while another lane decodes: the tail range is partial and
    the stream stays correct."""
    cfg_b = dataclasses.replace(BASE, prefill_budget=16, prefill_chunk=16)
    cfg_r = dataclasses.replace(cfg_b, ragged_dispatch=True)
    specs = [
        dict(prompt="warm", max_new_tokens=12, seed=9),
        dict(prompt="abcdefgh" * 7, max_new_tokens=6, seed=9),  # 56 > W=16
    ]
    bucketed, _ = _serve(cfg_b, specs)
    ragged, stats = _serve(cfg_r, specs)
    assert ragged == bucketed
    # The clipped tail means strictly more than one ragged dispatch
    # carried prefill tokens.
    assert stats["prefill_tokens_total"] >= 56


def test_ragged_decode_only_iterations_keep_block_path():
    """Steady-state decode (no prefill pending) must keep the K-step
    block path: steps_dispatched outgrows blocks_dispatched, which only
    multi-step blocks produce (a ragged dispatch is steps=1; adaptive
    blocking is pinned off so the solo stream doesn't shrink K)."""
    specs = [dict(prompt="abc", max_new_tokens=12, seed=1)]
    _, stats = _serve(
        dataclasses.replace(RAGGED, adaptive_block=False), specs
    )
    assert stats["steps_dispatched"] > stats["blocks_dispatched"]


def test_ragged_padding_waste_accounting():
    _, stats = _serve(RAGGED, [dict(prompt="abcd" * 4, max_new_tokens=4)])
    assert stats["tokens_dispatched"] >= stats["tokens_useful"] > 0
    assert 0.0 < stats["tokens_useful_fraction"] <= 1.0
    _, bstats = _serve(BASE, [dict(prompt="abcd" * 4, max_new_tokens=4)])
    assert bstats["tokens_dispatched"] >= bstats["tokens_useful"] > 0


def test_ragged_kill_switch(monkeypatch):
    monkeypatch.setenv("POLYKEY_DISABLE_RAGGED", "1")
    engine = InferenceEngine(RAGGED, seed=0)
    try:
        assert engine._ragged is False
        r = GenRequest(prompt="still serves", max_new_tokens=4)
        engine.submit(r)
        tokens, done, error = _drain(r)
        assert error is None and done is not None and len(tokens) == 4
    finally:
        engine.shutdown()


def test_ragged_config_validation():
    # Speculative decoding composes with ragged dispatch since ISSUE 19
    # (verify windows ride the flat stream) — the old refusal is gone.
    dataclasses.replace(RAGGED, draft_model="tiny-llama").validate()
    with pytest.raises(ValueError, match="tp-at-most"):
        dataclasses.replace(RAGGED, dp=2).validate()
    with pytest.raises(ValueError, match="tp-at-most"):
        dataclasses.replace(RAGGED, sp=2).validate()


# -- recompile stability (smoke-scale census) ---------------------------------


def test_ragged_engine_recompile_stable():
    """Warmed ragged engine: the serving sweep (admissions, chunked
    prompt, retires, both depths) compiles NOTHING new — the single
    resident ragged executable plus the decode blocks serve every
    shape; the bucketed prefill handle's cache never grows."""
    from polykey_tpu.analysis.graph import drive_engine, recompile_findings

    config = dataclasses.replace(
        RAGGED, compile_warmup=True, warm_sampled_variants=False,
    )
    engine = InferenceEngine(config, seed=0)
    try:
        handles = {
            "_jit_ragged": engine._jit_ragged,
            "_jit_decode": engine._jit_decode,
            "_jit_merge": engine._jit_merge,
            "_jit_retire": engine._jit_retire,
            "_jit_prefill": engine._jit_prefill,   # growth watch only
        }
        prefill_before = engine._jit_prefill._cache_size()
        waves = [
            [GenRequest(prompt="abc", max_new_tokens=4, seed=2),
             GenRequest(prompt="abcdefgh" * 2, max_new_tokens=4, seed=2)],
            [GenRequest(prompt="abcdefgh" * 6, max_new_tokens=4, seed=2)],
        ]

        def sweep():
            configured = engine._depth
            try:
                errors = []
                for depth in (1, 2):
                    engine._depth = depth
                    errors.extend(drive_engine(engine, waves))
                return errors
            finally:
                engine._depth = configured

        findings, sizes = recompile_findings("ragged-smoke", {
            k: v for k, v in handles.items() if k != "_jit_prefill"
        }, sweep)
        assert findings == [], [f.message for f in findings]
        # The bucketed prefill executables are GONE from this engine's
        # serving: nothing compiled them during the sweep.
        assert engine._jit_prefill._cache_size() == prefill_before
    finally:
        engine.shutdown()


# -- chaos: supervisor + failover semantics unchanged -------------------------


CHAOS_RAGGED = dataclasses.replace(
    RAGGED,
    max_decode_slots=1, max_seq_len=128, num_pages=32,
    prefill_buckets=(16,), max_new_tokens_cap=32,
    decode_block_steps=1, adaptive_block=False, lookahead_blocks=1,
    compile_warmup=True, warm_sampled_variants=False,
    watchdog_timeout_s=0.3, max_queue_depth=0, supervise=True,
)


def _await(predicate, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def test_ragged_supervisor_restart():
    """PR 3 semantics with ragged on: an injected step-stall trips the
    watchdog, the supervisor restarts into a FRESH ragged engine, and
    serving resumes — the ragged dispatch path changes nothing about
    trip/restart/recovery."""
    from polykey_tpu.engine.supervisor import EngineSupervisor
    from polykey_tpu.engine.watchdog import Watchdog
    from polykey_tpu.gateway.health import SERVING, HealthService

    faults.install("step-stall=1.0@1")
    engine = InferenceEngine(CHAOS_RAGGED)
    health = HealthService()
    health.set_serving_status("", SERVING)
    watchdog = Watchdog(engine, health=health, check_interval_s=0.05)
    watchdog.start()
    supervisor = EngineSupervisor(
        engine, lambda: InferenceEngine(CHAOS_RAGGED),
        watchdog=watchdog, health=health,
        max_restarts=2, restart_window_s=60.0,
        check_interval_s=0.05, join_timeout_s=5.0,
    ).start()
    try:
        victim = GenRequest(prompt="stall victim", max_new_tokens=8)
        engine.submit(victim)
        assert _await(lambda: watchdog.tripped or supervisor.restarts > 0,
                      timeout=10.0)
        _, done, error = _drain(victim, timeout=15.0)
        assert done is None and error is not None
        assert _await(lambda: supervisor.restarts == 1, timeout=15.0)
        fresh = supervisor.engine
        assert fresh is not engine and fresh._ragged
        ok = GenRequest(prompt="after restart", max_new_tokens=6)
        fresh.submit(ok)
        tokens, done, error = _drain(ok, timeout=15.0)
        assert error is None and done is not None and len(tokens) == 6
    finally:
        supervisor.stop()
        watchdog.stop()
        supervisor.engine.shutdown()


def test_ragged_pool_resume_bit_identical():
    """PR 7 semantics with ragged on: replica death mid-stream resumes
    the greedy stream bit-identically on the surviving replica."""
    from polykey_tpu.engine.replica_pool import ReplicaPool

    config = dataclasses.replace(
        CHAOS_RAGGED, max_decode_slots=2, replicas=2,
    )
    pool = ReplicaPool.create(
        config, watchdog_interval_s=0.05, supervisor_interval_s=0.05,
    )
    try:
        prompt = "ragged failover determinism probe"
        baseline = GenRequest(prompt=prompt, max_new_tokens=12)
        pool.submit(baseline)
        base_tokens, base_done, base_error = _drain(baseline)
        assert base_error is None and base_done is not None
        assert len(base_tokens) == 12

        # In ragged mode the PREFILL rides _dispatch_step (fault sleeps
        # included), so arming step-stall up front would wedge the
        # dispatch BEFORE the first token — a queued requeue, not the
        # mid-stream resume this test pins. Pace the replica, let a few
        # tokens flow, THEN wedge it.
        pool.replicas[0].engine._faults = faults.install(
            "slow-step=0.1:replica=0"
        )
        victim = GenRequest(prompt=prompt, max_new_tokens=12)
        pool.submit(victim)
        assert victim.replica == 0
        head = []
        for _ in range(3):
            kind, value = victim.out.get(timeout=30)
            assert kind == "token", value
            head.append(value)
        pool.replicas[0].engine._faults = faults.install(
            "slow-step=0.1:replica=0,step-stall=1.0@1:replica=0"
        )
        tokens, done, error = _drain(victim)
        assert error is None and done is not None
        assert head + tokens == base_tokens
        assert pool.stats()["streams_resumed"] >= 1
    finally:
        pool.shutdown()


# -- forward_ragged routes to gather under meshed extents ---------------------


def test_forward_ragged_gather_under_mesh(monkeypatch):
    """With any mesh extent > 1 the ragged kernel (un-shard_mapped)
    must NOT be chosen even where the geometry gate passes — the
    GSPMD-partitionable gather path serves instead."""
    from polykey_tpu.parallel.mesh import MeshConfig, create_mesh

    calls = {"kernel": 0}

    def fake_kernel(*a, **k):
        calls["kernel"] += 1
        raise AssertionError("kernel path must not be taken under mesh")

    monkeypatch.setattr(
        "polykey_tpu.ops.ragged_paged_attention_kernel.use_ragged_kernel",
        lambda *_: True,
    )

    from polykey_tpu.engine.kv_cache import init_paged_kv
    from polykey_tpu.models.config import get_config
    from polykey_tpu.models.transformer import forward_ragged, init_params

    cfg = get_config("tiny-llama")
    mesh = create_mesh(MeshConfig(tp=2), jax.devices()[:2]) \
        if len(jax.devices()) >= 2 else None
    if mesh is None:
        pytest.skip("needs >= 2 devices (conftest forces 8 CPU devices)")
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    paged = init_paged_kv(cfg, 16, 8, jnp.float32)
    T, P = 8, 4
    tokens = jnp.zeros((T,), jnp.int32)
    positions = jnp.zeros((T,), jnp.int32)
    token_tables = jnp.zeros((T, P), jnp.int32)
    starts = jnp.asarray([0, 1], jnp.int32)
    lens = jnp.asarray([1, 1], jnp.int32)
    kvs = jnp.asarray([1, 1], jnp.int32)
    seq_tables = jnp.zeros((2, P), jnp.int32)
    monkeypatch.setattr(
        "polykey_tpu.ops.ragged_paged_attention_kernel._ragged_call",
        fake_kernel,
    )
    hidden, _ = forward_ragged(
        params, cfg, tokens, positions, paged, token_tables,
        starts, lens, kvs, seq_tables, mesh=mesh,
    )
    assert hidden.shape == (T, cfg.hidden_size)
    assert calls["kernel"] == 0
