"""Concurrency soak (VERDICT r1 #8 — the race-detector tier at load).

32+ concurrent streaming clients with mixed prompt lengths, mid-stream
cancellations, and a page pool sized to exhaust (forcing the FIFO requeue
path to churn). Invariants at the end: every request reached a terminal
event, no slot is stuck, and — after dropping the prefix cache's own
references in the cached variant — the allocator's free count returns to
its initial value (no leaked pages or refcounts through any of the admit /
chunked-prefill / finish / cancel / requeue / cache-evict paths).
"""

import dataclasses
import queue
import threading
import time

import numpy as np
import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine

SOAK_CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    # Small pool on purpose: 4 slots * ~6 pages fits, but the 40-request
    # backlog repeatedly exhausts it → AllocationError → requeue-front.
    num_pages=48,
    max_seq_len=128,
    prefill_buckets=(16, 32),
    prefill_chunk=32,
    max_new_tokens_cap=16,
    default_max_new_tokens=8,
)

N_CLIENTS = 40
CANCEL_EVERY = 5


@pytest.mark.parametrize("prefix_cache", [False, True])
def test_soak_no_leaks_no_stuck_slots(prefix_cache):
    # The cached variant soaks the refcount lifecycle too: "x"*n prompts
    # share prefixes heavily, the tight pool forces allocation-pressure
    # eviction, and cancellations churn slot-held references.
    eng = InferenceEngine(dataclasses.replace(
        SOAK_CONFIG, prefix_cache=prefix_cache, prefix_cache_pages=8
    ))
    rng = np.random.default_rng(11)
    initial_free = eng.allocator.num_free
    results = {"done": 0, "error": 0, "cancelled": 0, "lost": 0}
    lock = threading.Lock()

    def client(idx: int) -> None:
        prompt_len = int(rng.integers(1, 90))
        r = GenRequest(
            prompt="x" * prompt_len,
            max_new_tokens=int(rng.integers(2, 14)),
            temperature=0.7 if idx % 3 == 0 else 0.0,
        )
        cancel_after = (
            int(rng.integers(1, 4)) if idx % CANCEL_EVERY == 0 else None
        )
        try:
            eng.submit(r)
        except Exception:
            with lock:
                results["error"] += 1
            return
        seen = 0
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            try:
                kind, value = r.out.get(timeout=deadline - time.monotonic())
            except queue.Empty:
                break
            if kind == "token":
                seen += 1
                if cancel_after is not None and seen >= cancel_after:
                    r.cancelled.set()
            elif kind == "done":
                with lock:
                    results["done"] += 1
                return
            else:
                with lock:
                    key = "cancelled" if value == "cancelled" else "error"
                    results[key] += 1
                return
        with lock:
            results["lost"] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(N_CLIENTS)
    ]
    for t in threads:
        t.start()
        if t is not threads[-1]:
            time.sleep(0.01)  # staggered arrivals → mixed batch composition
    for t in threads:
        t.join(timeout=300)

    try:
        # Every request reached a terminal event.
        assert results["lost"] == 0, results
        assert not any(t.is_alive() for t in threads)
        total = results["done"] + results["error"] + results["cancelled"]
        assert total == N_CLIENTS, results
        # Unexpected errors are zero (errors counts non-cancel failures).
        assert results["error"] == 0, results

        # Engine drains: no stuck slots, no queued leftovers.
        deadline = time.monotonic() + 30
        while eng.busy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not eng.busy
        assert all(s is None for s in eng._slots)

        # Every page is either back or held (accounted) by the cache —
        # and after dropping the cache's references, ALL pages are back
        # (catches a leaked extra retain hiding behind a cached page).
        held = len(eng._prefix) if eng._prefix is not None else 0
        assert eng.allocator.num_free == initial_free - held
        if eng._prefix is not None:
            eng._prefix.clear()
            assert eng.allocator.num_free == initial_free

        snap = eng.metrics.snapshot()
        assert snap["requests_admitted"] == N_CLIENTS
        assert snap["tokens_generated"] > 0
    finally:
        eng.shutdown()

    # Shutdown after drain leaves the engine dead but consistent.
    with pytest.raises(Exception):
        eng.submit(GenRequest(prompt="after shutdown"))
