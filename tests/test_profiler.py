"""jax.profiler trace endpoint (VERDICT r1 #7, SURVEY §5 tracing).

engine_profile start → serve a request (annotated prefill/decode steps) →
stop must leave a real trace artifact on disk.
"""

import glob
import os

import pytest
from google.protobuf import struct_pb2

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import InferenceEngine
from polykey_tpu.gateway.tpu_service import TpuService

CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=2,
    page_size=8,
    num_pages=32,
    max_seq_len=64,
    prefill_buckets=(16, 32),
    max_new_tokens_cap=16,
)


def _params(**kv) -> struct_pb2.Struct:
    s = struct_pb2.Struct()
    s.update(kv)
    return s


def test_profile_capture_roundtrip(tmp_path):
    engine = InferenceEngine(CONFIG)
    service = TpuService(engine)
    try:
        log_dir = str(tmp_path / "trace")
        resp = service.execute_tool(
            "engine_profile", _params(action="start", log_dir=log_dir),
            None, None,
        )
        assert resp.struct_output["profiling"] is True

        # Double-start is an error.
        with pytest.raises(ValueError):
            service.execute_tool(
                "engine_profile", _params(action="start"), None, None
            )

        # Generate under the trace so prefill/decode annotations land.
        resp = service.execute_tool(
            "llm_generate", _params(prompt="profile me", max_tokens=4),
            None, None,
        )
        assert resp.status.code == 200

        resp = service.execute_tool(
            "engine_profile", _params(action="stop"), None, None
        )
        assert resp.struct_output["profiling"] is False

        traces = glob.glob(
            os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True
        )
        assert traces, f"no trace artifact under {log_dir}"

        # Stop without start is an error; status is not.
        with pytest.raises(ValueError):
            service.execute_tool(
                "engine_profile", _params(action="stop"), None, None
            )
        resp = service.execute_tool(
            "engine_profile", _params(action="status"), None, None
        )
        assert resp.struct_output["profiling"] is False
    finally:
        engine.shutdown()
