"""Lookahead dispatch pipeline: the loop-trace regression tier (ISSUE 6).

The engine's steady-state contract is a two-frontier pipeline: the
DISPATCH frontier runs ahead (block N+1 dispatched before block N's
readback) while the PROCESSED frontier trails, so the host's scheduling
latency rides under the device's compute instead of serializing with it
(r03: roundtrip_ms 587 vs block_ms 62 — a 9x host tax per block when
synchronous). These tests pin that overlap on CPU so it cannot silently
regress before the next hardware window:

- the engine's flight-deck timeline (`engine.timeline`, the ISSUE 10
  TimelineRecorder that replaced the ad-hoc `_pipe_events` ring)
  must show dispatch N+1 happening-before process N under steady decode
  at depth 2, and EXACT dispatch-then-read synchrony at depth 1;
- greedy outputs must be bit-identical between depths (the pipeline is
  a scheduling change, never a numerics change);
- `POLYKEY_DISPATCH_LOOKAHEAD` overrides the config depth (the DEPLOY.md
  operator knob);
- the pipeline drains: an idle engine holds no in-flight blocks, and
  every dispatched block is eventually processed.
"""

import os
import queue
import time

import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine


def _config(depth: int) -> EngineConfig:
    return EngineConfig(
        model="tiny-llama",
        tokenizer="byte",
        dtype="float32",
        max_decode_slots=4,
        page_size=8,
        num_pages=64,
        max_seq_len=64,
        prefill_buckets=(16,),
        max_new_tokens_cap=32,
        default_max_new_tokens=8,
        decode_block_steps=4,
        lookahead_blocks=depth,
    )


def _collect(request: GenRequest, timeout: float = 60.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _run_greedy_burst(engine, n: int = 3, max_new: int = 24):
    """Steady decode: several concurrent greedy streams, long enough for
    many blocks per stream. Returns each request's token list."""
    requests = [
        GenRequest(prompt=f"pipeline probe {i}", max_new_tokens=max_new,
                   temperature=0.0)
        for i in range(n)
    ]
    for request in requests:
        engine.submit(request)
    outs = []
    for request in requests:
        tokens, done, error = _collect(request)
        assert error is None, error
        assert done is not None
        outs.append(tokens)
    return outs


def _events(engine) -> list[tuple]:
    """Legacy-shaped view of the timeline ring: ("dispatch", seq) and
    ("process", seq, lookahead, queued_after) tuples in record order —
    the happens-before assertions below predate the typed recorder and
    read event ORDER, which the promotion preserved."""
    out = []
    for event in engine.timeline.events():
        if event["kind"] == "dispatch":
            out.append(("dispatch", event["seq"]))
        elif event["kind"] == "process":
            out.append(("process", event["seq"], event["lookahead"],
                        event["queued_after"]))
    return out


def _drained(engine, timeout: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not engine._inflight_q and not engine.busy:
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(scope="module")
def depth2_engine():
    engine = InferenceEngine(_config(depth=2))
    yield engine
    engine.shutdown()


@pytest.fixture(scope="module")
def depth1_engine():
    engine = InferenceEngine(_config(depth=1))
    yield engine
    engine.shutdown()


def test_depth2_dispatch_runs_ahead_of_process(depth2_engine):
    """The overlap itself: under steady decode at depth 2, block N+1 is
    dispatched BEFORE block N's readback — provable from the flight
    recorder's event order, not just from a counter."""
    engine = depth2_engine
    _run_greedy_burst(engine)
    assert _drained(engine)
    events = _events(engine)
    processed = [e for e in events if e[0] == "process"]
    assert processed, "no blocks processed"
    overlapped = [e for e in processed if e[2] >= 1]
    # Every steady-state iteration dispatches N+1 then force-drains N;
    # only pipeline fill/drain edges may read back synchronously.
    assert overlapped, (
        "no processed block observed lookahead >= 1 — the dispatch "
        f"frontier never ran ahead: {processed[:10]}"
    )
    assert len(overlapped) >= len(processed) // 2, (
        f"overlap is the exception, not the steady state: "
        f"{len(overlapped)}/{len(processed)} blocks overlapped"
    )
    # Happens-before, from the event order: for an overlapped block N,
    # the ring shows ("dispatch", N+1) strictly before ("process", N).
    order = {}
    for position, event in enumerate(events):
        if event[0] == "dispatch":
            order[event[1]] = position
    for event in overlapped:
        seq = event[1]
        if seq + 1 in order:
            process_pos = events.index(event)
            assert order[seq + 1] < process_pos, (
                f"block {seq + 1} dispatched after block {seq} was "
                "processed despite recorded lookahead"
            )
    # The observability surface agrees with the recorder.
    assert engine.metrics.lookahead_max >= 1
    stats = engine.stats()
    assert stats["lookahead_depth"] == 2
    assert stats["lookahead_observed_max"] >= 1
    # blocks_processed counts every processed block since construction;
    # the ring is bounded, so >= is the honest comparison.
    assert engine.metrics.blocks_processed >= len(processed)
    if engine.metrics.host_stall_hist.count:
        assert "host_stall_ms_p50" in stats


def test_depth1_is_exactly_synchronous(depth1_engine):
    """Depth 1 restores dispatch-then-read: every processed block has
    observed lookahead 0 and an empty queue behind it."""
    engine = depth1_engine
    _run_greedy_burst(engine)
    assert _drained(engine)
    processed = [e for e in _events(engine) if e[0] == "process"]
    assert processed
    assert all(e[2] == 0 for e in processed), (
        f"depth 1 must never run ahead: {[e for e in processed if e[2]][:5]}"
    )
    assert all(e[3] == 0 for e in processed), (
        "depth 1 must never queue a second in-flight block"
    )
    assert engine.metrics.lookahead_max == 0
    assert engine.stats()["lookahead_depth"] == 1


def test_greedy_bit_identical_across_depths(depth1_engine, depth2_engine):
    """The pipeline is scheduling, not numerics: the same greedy prompts
    produce the same token streams at depth 1 and depth 2."""
    prompts = ["determinism alpha", "determinism beta", "determinism gamma"]

    def run(engine):
        requests = [
            GenRequest(prompt=p, max_new_tokens=16, temperature=0.0)
            for p in prompts
        ]
        for request in requests:
            engine.submit(request)
        outs = []
        for request in requests:
            tokens, done, error = _collect(request)
            assert error is None, error
            outs.append(tokens)
        return outs

    assert run(depth1_engine) == run(depth2_engine)


def test_env_override_sets_depth():
    """POLYKEY_DISPATCH_LOOKAHEAD overrides the config depth regardless
    of how the config was built — and depth 1 via env behaves like a
    depth-1 config (exact synchrony)."""
    os.environ["POLYKEY_DISPATCH_LOOKAHEAD"] = "1"
    try:
        engine = InferenceEngine(_config(depth=2))
    finally:
        del os.environ["POLYKEY_DISPATCH_LOOKAHEAD"]
    try:
        assert engine._depth == 1
        assert engine.stats()["lookahead_depth"] == 1
        _run_greedy_burst(engine, n=2, max_new=12)
        assert _drained(engine)
        processed = [e for e in _events(engine) if e[0] == "process"]
        assert processed and all(e[2] == 0 for e in processed)
    finally:
        engine.shutdown()


def test_depth1_never_deepens_under_adaptive_blocking(depth1_engine):
    """Adaptive blocking shrinks K for solo streams and deepens the
    pipeline to keep steps-in-flight constant — but only the LOOKAHEAD
    portion may scale. At depth 1 the target must stay 1 through a solo
    run (the case where K shrinks most), or the synchronous escape
    hatch silently runs ahead on any backend where readback isn't
    instant (the CPU ordering assertions can't see this: 1-step blocks
    land within the iteration here)."""
    engine = depth1_engine
    request = GenRequest(prompt="solo adaptive", max_new_tokens=24,
                         temperature=0.0)
    engine.submit(request)
    _, done, error = _collect(request)
    assert error is None and done is not None
    assert _drained(engine)
    assert engine._depth_target == 1


def test_pipeline_drains_idle_and_complete(depth2_engine):
    """Every dispatched block is processed once the engine goes idle —
    no hung readback, no in-flight leak across bursts."""
    engine = depth2_engine
    _run_greedy_burst(engine, n=2, max_new=8)
    assert _drained(engine)
    assert len(engine._inflight_q) == 0
    # Dispatch/process accounting balances: sequence numbers are dense,
    # and the last processed seq equals the dispatch frontier.
    assert engine.metrics.blocks_processed == engine._dispatch_seq
