"""ISSUE 4: occupancy tracker + interleaved chunked prefill.

Covers the always-on live-lane tracker (EngineMetrics.on_dispatch →
stats/exposition/roofline as avg_lanes_source: "measured"), the
POLYKEY_PREFILL_BUDGET interleaving discipline (a long-prompt admission
may not stall in-flight decode beyond the budgeted bound), and the
correctness pin: chunked-prefill-interleaved output is token-for-token
identical to a non-interleaved engine's.
"""

import queue
import time

import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.engine.metrics import EngineMetrics


def _collect(request: GenRequest, timeout=60.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


# -- tracker unit behavior ---------------------------------------------------


def test_tracker_counters_and_snapshot():
    m = EngineMetrics()
    snap = m.lanes_snapshot()
    assert snap["blocks_dispatched"] == 0
    assert snap["avg_lanes"] is None

    m.on_dispatch(4, 8)      # 4 lanes for an 8-step block
    m.on_dispatch(2, 4)      # 2 lanes for a 4-step block
    snap = m.lanes_snapshot()
    assert snap["blocks_dispatched"] == 2
    assert snap["lanes_dispatched"] == 6
    assert snap["lane_steps"] == 4 * 8 + 2 * 4
    assert snap["steps_dispatched"] == 12
    # Step-weighted mean: 40/12, not the block mean 3.0.
    assert snap["avg_lanes"] == pytest.approx(40 / 12, abs=0.01)

    full = m.snapshot()
    assert full["avg_lanes"] == snap["avg_lanes"]
    assert full["blocks_dispatched"] == 2
    assert full["lanes_ewma"] > 0
    # Histogram saw both dispatches.
    assert m.lanes_hist.count == 2


def test_tracker_interleave_accounting():
    m = EngineMetrics()
    m.on_prefill_interleave(128, decode_live=False)   # cold burst
    assert m.snapshot()["interleave_max_tokens"] == 0  # nothing to stall
    m.on_prefill_interleave(96, decode_live=True)
    m.on_prefill_interleave(64, decode_live=True)
    snap = m.snapshot()
    assert snap["prefill_tokens_total"] == 128 + 96 + 64
    assert snap["interleave_max_tokens"] == 96


# -- engine integration ------------------------------------------------------


def test_engine_stats_export_measured_lanes():
    cfg = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=4, page_size=8, num_pages=64, max_seq_len=64,
        prefill_buckets=(16, 32), max_new_tokens_cap=16,
    )
    engine = InferenceEngine(cfg)
    try:
        reqs = [GenRequest(prompt=f"occ {i}", max_new_tokens=8)
                for i in range(4)]
        for r in reqs:
            engine.submit(r)
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None and done is not None
        stats = engine.stats()
        assert stats["blocks_dispatched"] > 0
        assert stats["avg_lanes"] > 0
        assert 0 < stats["occupancy"] <= 1.0
        assert stats["avg_lanes"] <= cfg.max_decode_slots
        assert stats["prefill_tokens_total"] >= 4 * 16  # one bucket each
        assert stats["prefill_budget"] == 2 * 32        # auto: 2 x chunk
    finally:
        engine.shutdown()


def test_roofline_grades_measured_when_tracker_has_data():
    from polykey_tpu.engine.roofline import grade

    measured = grade(
        model="tiny-llama", dtype="float32", quantize=False,
        quantize_bits=8, kv_dtype="", tok_s=100.0,
        avg_lanes=3.4, avg_ctx=32.0,
    )
    assert measured["avg_lanes_source"] == "measured"
    assert measured["avg_lanes"] == 3.4

    assumed = grade(
        model="tiny-llama", dtype="float32", quantize=False,
        quantize_bits=8, kv_dtype="", tok_s=100.0,
        avg_lanes=None, avg_ctx=32.0, assumed_lanes=4.0,
    )
    assert assumed["avg_lanes_source"] == "assumed_full"
    assert assumed["avg_lanes"] == 4.0


def test_exposition_exports_lane_families():
    from polykey_tpu.obs.exposition import engine_collector

    cfg = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=2, page_size=8, num_pages=32, max_seq_len=64,
        prefill_buckets=(16,), max_new_tokens_cap=8,
    )
    engine = InferenceEngine(cfg)
    try:
        r = GenRequest(prompt="scrape", max_new_tokens=4)
        engine.submit(r)
        tokens, done, error = _collect(r)
        assert error is None
        text = "\n".join(engine_collector(engine)())
        for family in (
            "polykey_dispatched_blocks_total",
            "polykey_dispatched_steps_total",
            "polykey_lane_steps_total",
            "polykey_live_lanes",
            "polykey_decode_slots",
            "polykey_prefill_tokens_total",
            "polykey_prefill_interleave_max_tokens",
            "polykey_live_lanes_per_block_bucket",
        ):
            assert family in text, f"missing {family}"
    finally:
        engine.shutdown()


# -- interleaving discipline -------------------------------------------------


def _serve_all(engine, prompts, max_new, seeds=None):
    reqs = [
        GenRequest(prompt=p, max_new_tokens=max_new,
                   seed=None if seeds is None else seeds[i])
        for i, p in enumerate(prompts)
    ]
    for r in reqs:
        engine.submit(r)
    out = []
    for r in reqs:
        tokens, done, error = _collect(r, timeout=120.0)
        assert error is None, f"request failed: {error}"
        assert done is not None
        out.append(tokens)
    return out


def test_interleaved_greedy_equality():
    """Chunked-prefill-interleaved output must match the non-interleaved
    engine token-for-token: the budget changes WHEN prefill work is
    scheduled, never what any stream decodes (plain-engine greedy
    streams are batch- and schedule-independent by contract)."""
    base = dict(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=4, page_size=8, num_pages=128, max_seq_len=128,
        prefill_buckets=(16,), prefill_chunk=16, max_new_tokens_cap=24,
    )
    # Mixed workload: two long prompts (>bucket → chunked, different
    # lengths so their chunk counts differ) racing two short ones.
    prompts = ["L" * 70, "short a", "M" * 45, "short b"]

    tight = InferenceEngine(EngineConfig(**base, prefill_budget=16))
    try:
        streams_tight = _serve_all(tight, prompts, max_new=16)
        assert tight.stats()["prefill_budget"] == 16
    finally:
        tight.shutdown()

    loose = InferenceEngine(EngineConfig(**base, prefill_budget=100_000))
    try:
        streams_loose = _serve_all(loose, prompts, max_new=16)
    finally:
        loose.shutdown()

    assert streams_tight == streams_loose
    for s in streams_tight:
        assert len(s) == 16


def test_long_prompt_stall_bounded_by_budget():
    """A long-prompt admission mid-decode injects at most
    budget + bucket + chunk prefill tokens between two decode blocks
    (the documented overshoot bound) — the no-starved-decode pin."""
    cfg = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=4, page_size=8, num_pages=256, max_seq_len=256,
        prefill_buckets=(16,), prefill_chunk=16, prefill_budget=16,
        max_new_tokens_cap=64, decode_block_steps=4,
    )
    engine = InferenceEngine(cfg)
    try:
        # A running stream long enough to still be decoding while the
        # long prompts chunk through.
        runner = GenRequest(prompt="runner", max_new_tokens=64)
        engine.submit(runner)
        # Wait for its first token so decode is genuinely in flight.
        kind, _ = runner.out.get(timeout=60.0)
        assert kind == "token"
        # Three long prompts: 10+ chunks each at chunk=16.
        longs = [GenRequest(prompt=c * 170, max_new_tokens=4)
                 for c in "XYZ"]
        for r in longs:
            engine.submit(r)
        for r in longs:
            tokens, done, error = _collect(r, timeout=120.0)
            assert error is None and done is not None
            assert len(tokens) == 4
        tokens, done, error = _collect(runner, timeout=120.0)
        assert error is None and done is not None

        stats = engine.stats()
        budget, bucket, chunk = 16, 16, 16
        assert stats["interleave_max_tokens"] > 0
        assert stats["interleave_max_tokens"] <= budget + bucket + chunk, (
            f"prefill injection {stats['interleave_max_tokens']} exceeds "
            f"the budgeted bound {budget + bucket + chunk}"
        )
    finally:
        engine.shutdown()


def test_unbudgeted_cold_burst_still_fills_slots():
    """With NO live decode lanes the budget is waived: a cold burst must
    fill every free slot in one iteration (the occupancy fix from r3
    must not regress into budgeted trickle admission)."""
    cfg = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=8, page_size=8, num_pages=256, max_seq_len=64,
        prefill_buckets=(16,), prefill_budget=16,  # one bucket per gap
        max_new_tokens_cap=32,
    )
    engine = InferenceEngine(cfg)
    try:
        reqs = [GenRequest(prompt=f"cold {i}", max_new_tokens=24)
                for i in range(8)]
        for r in reqs:
            engine.submit(r)
        for r in reqs:
            tokens, done, error = _collect(r, timeout=120.0)
            assert error is None and done is not None
            assert len(tokens) == 24
        # All 8 admitted against a 16-token budget proves the cold path
        # ignored it; with the budget enforced cold, the first block
        # would have dispatched with ≤1 lane and the tracker's peak
        # would show it.
        assert engine.stats()["avg_lanes"] > 1.0
    finally:
        engine.shutdown()
