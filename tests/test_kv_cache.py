"""Block allocator + paged-KV correctness tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polykey_tpu.engine.kv_cache import (
    AllocationError,
    BlockAllocator,
    init_paged_kv,
)
from polykey_tpu.models.config import TINY_LLAMA
from polykey_tpu.models.transformer import forward, forward_paged, init_params
from polykey_tpu.ops.paged_attention import paged_gather_kv, paged_write


@pytest.fixture(params=["python", "native"])
def allocator_factory(request):
    prefer_native = request.param == "native"
    def make(num_pages):
        alloc = BlockAllocator(num_pages, prefer_native=prefer_native)
        if prefer_native and not alloc.is_native:
            pytest.skip("native allocator not built (run `make native`)")
        return alloc
    return make


def test_alloc_release_cycle(allocator_factory):
    alloc = allocator_factory(8)
    assert alloc.num_free == 7  # page 0 reserved
    pages = alloc.alloc(3)
    assert len(pages) == 3
    assert 0 not in pages
    assert alloc.num_free == 4
    alloc.release_all(pages)
    assert alloc.num_free == 7


def test_alloc_all_or_nothing(allocator_factory):
    alloc = allocator_factory(4)
    alloc.alloc(2)
    with pytest.raises(AllocationError):
        alloc.alloc(2)  # only 1 free
    assert alloc.num_free == 1  # failed alloc took nothing


def test_refcount_sharing(allocator_factory):
    alloc = allocator_factory(4)
    (page,) = alloc.alloc(1)
    alloc.retain(page)
    alloc.release(page)
    assert alloc.num_free == 2  # still held by the second reference
    alloc.release(page)
    assert alloc.num_free == 3


def test_double_release_rejected(allocator_factory):
    alloc = allocator_factory(4)
    (page,) = alloc.alloc(1)
    alloc.release(page)
    with pytest.raises(ValueError):
        alloc.release(page)
    with pytest.raises(ValueError):
        alloc.release(0)  # garbage page is never client-owned


def test_unique_pages(allocator_factory):
    alloc = allocator_factory(64)
    pages = alloc.alloc(63)
    assert len(set(pages)) == 63
    with pytest.raises(AllocationError):
        alloc.alloc(1)


def test_paged_write_and_gather_roundtrip():
    Hk, D, page_size = 2, 4, 4
    pools = jnp.zeros((8, page_size, Hk, D), dtype=jnp.float32)
    # One sequence using pages [3, 5]: positions 0..7.
    page_tables = jnp.array([[3, 5]], dtype=jnp.int32)
    positions = jnp.arange(8, dtype=jnp.int32)[None, :]
    k_new = jax.random.normal(jax.random.PRNGKey(0), (1, 8, Hk, D))
    v_new = jax.random.normal(jax.random.PRNGKey(1), (1, 8, Hk, D))
    k_pages, v_pages = paged_write(pools, pools, k_new, v_new, page_tables, positions)
    k_out, v_out = paged_gather_kv(k_pages, v_pages, page_tables)
    np.testing.assert_allclose(np.asarray(k_out[0]), np.asarray(k_new[0]))
    np.testing.assert_allclose(np.asarray(v_out[0]), np.asarray(v_new[0]))


def test_forward_paged_matches_contiguous():
    """The paged path must produce identical hidden states to the contiguous
    cache path — the oracle every kernel change is checked against."""
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    B, T, page_size = 2, 8, 4

    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)

    hidden_ref, _ = forward(params, cfg, tokens, positions, None)

    paged = init_paged_kv(cfg, num_pages=16, page_size=page_size, dtype=jnp.float32)
    # Row 0 → pages [1, 2]; row 1 → pages [7, 4] (deliberately non-contiguous).
    page_tables = jnp.array([[1, 2, 0], [7, 4, 0]], dtype=jnp.int32)
    hidden_paged, paged = forward_paged(
        params, cfg, tokens, positions, paged, page_tables
    )
    np.testing.assert_allclose(
        np.asarray(hidden_ref), np.asarray(hidden_paged), rtol=2e-4, atol=2e-4
    )


def test_forward_paged_incremental_decode():
    """Prefill + paged decode steps == one-shot no-cache forward."""
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(2), cfg, jnp.float32)
    T, page_size = 6, 4

    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T), (1, T)).astype(jnp.int32)
    hidden_ref, _ = forward(params, cfg, tokens, positions, None)

    paged = init_paged_kv(cfg, num_pages=8, page_size=page_size, dtype=jnp.float32)
    page_tables = jnp.array([[2, 5]], dtype=jnp.int32)

    # Prefill the first 3 tokens.
    hidden, paged = forward_paged(
        params, cfg, tokens[:, :3], positions[:, :3], paged, page_tables
    )
    # Decode the rest one token at a time.
    for t in range(3, T):
        hidden, paged = forward_paged(
            params, cfg, tokens[:, t : t + 1], positions[:, t : t + 1],
            paged, page_tables,
        )
    np.testing.assert_allclose(
        np.asarray(hidden_ref[:, -1]), np.asarray(hidden[:, 0]),
        rtol=2e-4, atol=2e-4,
    )


def _scatter_reference(k_pages, v_pages, k_new, v_new, page_tables, positions):
    """The original per-token XLA scatter, kept as the oracle for the
    faster write paths (page-granular cond path + Pallas DMA kernel)."""
    ps = k_pages.shape[1]
    bi = jnp.arange(page_tables.shape[0], dtype=jnp.int32)[:, None]
    page_ids = page_tables[bi, positions // ps]
    offsets = positions % ps
    return (
        k_pages.at[page_ids, offsets].set(k_new),
        v_pages.at[page_ids, offsets].set(v_new),
    )


def _write_fixture(B, T, P, start, seed=0):
    cfg = TINY_LLAMA
    ps = 16
    rng = np.random.default_rng(seed)
    pools = init_paged_kv(cfg, num_pages=1 + B * P, page_size=ps)
    kp = jnp.asarray(
        rng.normal(size=pools.k[0].shape).astype(np.float32), jnp.bfloat16
    )
    vp = kp * 2
    k_new = jnp.asarray(
        rng.normal(size=(B, T, cfg.num_kv_heads, cfg.head_dim)), jnp.bfloat16
    )
    v_new = k_new + 1
    pt = np.zeros((B, P), np.int32)
    for b in range(B):
        pt[b] = np.arange(P) + 1 + b * P
    positions = start[:, None] + np.arange(T)[None, :]
    return kp, vp, k_new, v_new, jnp.asarray(pt), jnp.asarray(positions, jnp.int32)


def test_paged_write_aligned_prefill_matches_scatter():
    """The page-granular cond path (aligned, consecutive rows — every
    engine prefill chunk) must be byte-identical to the token scatter."""
    B, T, P = 3, 32, 4
    start = np.array([0, 16, 32])          # all page-aligned
    kp, vp, kn, vn, pt, pos = _write_fixture(B, T, P, start)
    got_k, got_v = paged_write(kp, vp, kn, vn, pt, pos)
    want_k, want_v = _scatter_reference(kp, vp, kn, vn, pt, pos)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))


def test_paged_write_unaligned_prefill_matches_scatter():
    """Unaligned starts must fall back (runtime cond) to exact scatter."""
    B, T, P = 3, 32, 4
    start = np.array([0, 8, 17])           # rows 1, 2 unaligned
    kp, vp, kn, vn, pt, pos = _write_fixture(B, T, P, start)
    got_k, got_v = paged_write(kp, vp, kn, vn, pt, pos)
    want_k, want_v = _scatter_reference(kp, vp, kn, vn, pt, pos)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))


def test_paged_write_decode_kernel_interpret_matches_scatter():
    """The Pallas DMA write kernel (interpret mode on CPU) must match the
    scatter for a decode step, including the garbage-page-0 convention
    (inactive lanes all target page 0 — any value may land there)."""
    from polykey_tpu.ops.paged_write_kernel import paged_write_decode_kernel

    B, P = 4, 3
    start = np.array([5, 16, 31, 47])
    kp, vp, kn, vn, pt, pos = _write_fixture(B, 1, P, start)
    ps = kp.shape[1]
    bi = jnp.arange(B, dtype=jnp.int32)[:, None]
    page_ids = pt[bi, pos // ps][:, 0]
    offsets = (pos % ps)[:, 0]
    got_k, got_v = paged_write_decode_kernel(
        kp, vp, kn, vn, page_ids, offsets, interpret=True
    )
    want_k, want_v = _scatter_reference(kp, vp, kn, vn, pt, pos)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))


def test_paged_write_mesh_kernel_path_matches_scatter(monkeypatch):
    """The shard_map dispatch of the write kernel (dp all-gather of lane
    rows + tp head sharding) against the scatter oracle, on the virtual
    CPU mesh in interpret mode. On hardware this is the path every
    dp/tp-meshed decode step takes; nothing else exercises its
    collective wiring pre-hardware."""
    from functools import partial

    import polykey_tpu.ops.paged_attention as pa
    from polykey_tpu.ops import paged_write_kernel as pwk
    from polykey_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(tp=2, dp=2, sp=2))
    B, P = 4, 3
    start = np.array([5, 16, 31, 40])
    kp, vp, kn, vn, pt, pos = _write_fixture(B, 1, P, start)
    ps = kp.shape[1]
    bi = jnp.arange(B, dtype=jnp.int32)[:, None]
    page_ids = pt[bi, pos // ps][:, 0]
    offsets = (pos % ps)[:, 0]

    monkeypatch.setattr(
        pwk, "paged_write_rows_kernel",
        partial(pwk.paged_write_rows_kernel, interpret=True),
    )
    got_k, got_v = pa._write_decode_kernel(
        [(kp, kn), (vp, vn)], page_ids, offsets, mesh
    )
    want_k, want_v = _scatter_reference(kp, vp, kn, vn, pt, pos)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))


# ---- int8 KV cache ----


def test_quantize_kv_rows_roundtrip():
    from polykey_tpu.ops.paged_attention import (
        dequantize_kv,
        quantize_kv_rows,
    )

    rows = jax.random.normal(jax.random.PRNGKey(11), (3, 5, 4, 16))
    q, s = quantize_kv_rows(rows)
    assert q.dtype == jnp.int8 and s.shape == (3, 5, 4)
    back = dequantize_kv(q, s, jnp.float32)
    # q is computed against the bf16-ROUNDED scale (the one dequant
    # multiplies by), so per-entry error <= stored_scale/2; the stored
    # scale itself is within bf16 rounding of absmax/127.
    stored = np.asarray(s.astype(jnp.float32))
    err = np.asarray(jnp.abs(back - rows))
    assert (err <= stored[..., None] * 0.51 + 1e-7).all()


def test_forward_paged_int8_kv_tracks_fp():
    """Prefill + decode through int8 KV pools stay within quantization
    tolerance of the fp pools (the serving accuracy gate for
    EngineConfig.kv_dtype='int8')."""
    from polykey_tpu.models.transformer import forward_paged, init_params

    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    B, T, P, ps = 2, 16, 4, 16
    pt = np.zeros((B, P), np.int32)
    for b in range(B):
        pt[b] = np.arange(P) + 1 + b * P
    pt = jnp.asarray(pt)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)

    pool_fp = init_paged_kv(cfg, 1 + B * P, ps, jnp.float32)
    pool_q = init_paged_kv(cfg, 1 + B * P, ps, jnp.float32,
                           kv_dtype=jnp.int8)
    assert pool_q.quantized and pool_q.k.dtype == jnp.int8
    h_fp, pool_fp = forward_paged(params, cfg, tokens, positions, pool_fp, pt)
    h_q, pool_q = forward_paged(params, cfg, tokens, positions, pool_q, pt)
    scale = float(jnp.max(jnp.abs(h_fp))) + 1e-6
    assert float(jnp.max(jnp.abs(h_fp - h_q))) / scale < 0.05

    last = tokens[:, -1:]
    dpos = jnp.full((B, 1), T, jnp.int32)
    d_fp, _ = forward_paged(params, cfg, last, dpos, pool_fp, pt)
    d_q, _ = forward_paged(params, cfg, last, dpos, pool_q, pt)
    scale = float(jnp.max(jnp.abs(d_fp))) + 1e-6
    assert float(jnp.max(jnp.abs(d_fp - d_q))) / scale < 0.05


def test_paged_write_rows_kernel_with_scale_pools():
    """The generalized RMW kernel over four pools (int8 data + bf16
    scales) matches per-pool scatter in interpret mode."""
    from polykey_tpu.ops.paged_write_kernel import paged_write_rows_kernel

    B, P, ps, Hk, D = 4, 3, 16, 4, 32
    N = 1 + B * P
    rng = np.random.default_rng(5)
    kq = jnp.asarray(rng.integers(-127, 128, (N, ps, Hk, D)), jnp.int8)
    vq = kq * -1
    ks = jnp.asarray(rng.normal(size=(N, ps, Hk)), jnp.bfloat16)
    vs = ks + 1
    k8 = jnp.asarray(rng.integers(-127, 128, (B, 1, Hk, D)), jnp.int8)
    v8 = -k8
    ksr = jnp.asarray(rng.normal(size=(B, 1, Hk)), jnp.bfloat16)
    vsr = ksr * 2
    page_ids = jnp.asarray(rng.permutation(N - 1)[:B].astype(np.int32) + 1)
    offsets = jnp.asarray(rng.integers(0, ps, B).astype(np.int32))

    outs = paged_write_rows_kernel(
        [kq, vq, ks, vs], [k8, v8, ksr, vsr], page_ids, offsets,
        interpret=True,
    )
    for pool, rows, got in zip([kq, vq, ks, vs], [k8, v8, ksr, vsr], outs):
        want = pool.at[page_ids, offsets].set(
            rows.reshape(B, *rows.shape[2:]))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
