"""Block allocator + paged-KV correctness tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polykey_tpu.engine.kv_cache import (
    AllocationError,
    BlockAllocator,
    init_paged_kv,
)
from polykey_tpu.models.config import TINY_LLAMA
from polykey_tpu.models.transformer import forward, forward_paged, init_params
from polykey_tpu.ops.paged_attention import paged_gather_kv, paged_write


@pytest.fixture(params=["python", "native"])
def allocator_factory(request):
    prefer_native = request.param == "native"
    def make(num_pages):
        alloc = BlockAllocator(num_pages, prefer_native=prefer_native)
        if prefer_native and not alloc.is_native:
            pytest.skip("native allocator not built (run `make native`)")
        return alloc
    return make


def test_alloc_release_cycle(allocator_factory):
    alloc = allocator_factory(8)
    assert alloc.num_free == 7  # page 0 reserved
    pages = alloc.alloc(3)
    assert len(pages) == 3
    assert 0 not in pages
    assert alloc.num_free == 4
    alloc.release_all(pages)
    assert alloc.num_free == 7


def test_alloc_all_or_nothing(allocator_factory):
    alloc = allocator_factory(4)
    alloc.alloc(2)
    with pytest.raises(AllocationError):
        alloc.alloc(2)  # only 1 free
    assert alloc.num_free == 1  # failed alloc took nothing


def test_refcount_sharing(allocator_factory):
    alloc = allocator_factory(4)
    (page,) = alloc.alloc(1)
    alloc.retain(page)
    alloc.release(page)
    assert alloc.num_free == 2  # still held by the second reference
    alloc.release(page)
    assert alloc.num_free == 3


def test_double_release_rejected(allocator_factory):
    alloc = allocator_factory(4)
    (page,) = alloc.alloc(1)
    alloc.release(page)
    with pytest.raises(ValueError):
        alloc.release(page)
    with pytest.raises(ValueError):
        alloc.release(0)  # garbage page is never client-owned


def test_unique_pages(allocator_factory):
    alloc = allocator_factory(64)
    pages = alloc.alloc(63)
    assert len(set(pages)) == 63
    with pytest.raises(AllocationError):
        alloc.alloc(1)


def test_paged_write_and_gather_roundtrip():
    Hk, D, page_size = 2, 4, 4
    pools = jnp.zeros((8, page_size, Hk, D), dtype=jnp.float32)
    # One sequence using pages [3, 5]: positions 0..7.
    page_tables = jnp.array([[3, 5]], dtype=jnp.int32)
    positions = jnp.arange(8, dtype=jnp.int32)[None, :]
    k_new = jax.random.normal(jax.random.PRNGKey(0), (1, 8, Hk, D))
    v_new = jax.random.normal(jax.random.PRNGKey(1), (1, 8, Hk, D))
    k_pages, v_pages = paged_write(pools, pools, k_new, v_new, page_tables, positions)
    k_out, v_out = paged_gather_kv(k_pages, v_pages, page_tables)
    np.testing.assert_allclose(np.asarray(k_out[0]), np.asarray(k_new[0]))
    np.testing.assert_allclose(np.asarray(v_out[0]), np.asarray(v_new[0]))


def test_forward_paged_matches_contiguous():
    """The paged path must produce identical hidden states to the contiguous
    cache path — the oracle every kernel change is checked against."""
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    B, T, page_size = 2, 8, 4

    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)

    hidden_ref, _ = forward(params, cfg, tokens, positions, None)

    paged = init_paged_kv(cfg, num_pages=16, page_size=page_size, dtype=jnp.float32)
    # Row 0 → pages [1, 2]; row 1 → pages [7, 4] (deliberately non-contiguous).
    page_tables = jnp.array([[1, 2, 0], [7, 4, 0]], dtype=jnp.int32)
    hidden_paged, paged = forward_paged(
        params, cfg, tokens, positions, paged, page_tables
    )
    np.testing.assert_allclose(
        np.asarray(hidden_ref), np.asarray(hidden_paged), rtol=2e-4, atol=2e-4
    )


def test_forward_paged_incremental_decode():
    """Prefill + paged decode steps == one-shot no-cache forward."""
    cfg = TINY_LLAMA
    params = init_params(jax.random.PRNGKey(2), cfg, jnp.float32)
    T, page_size = 6, 4

    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T), (1, T)).astype(jnp.int32)
    hidden_ref, _ = forward(params, cfg, tokens, positions, None)

    paged = init_paged_kv(cfg, num_pages=8, page_size=page_size, dtype=jnp.float32)
    page_tables = jnp.array([[2, 5]], dtype=jnp.int32)

    # Prefill the first 3 tokens.
    hidden, paged = forward_paged(
        params, cfg, tokens[:, :3], positions[:, :3], paged, page_tables
    )
    # Decode the rest one token at a time.
    for t in range(3, T):
        hidden, paged = forward_paged(
            params, cfg, tokens[:, t : t + 1], positions[:, t : t + 1],
            paged, page_tables,
        )
    np.testing.assert_allclose(
        np.asarray(hidden_ref[:, -1]), np.asarray(hidden[:, 0]),
        rtol=2e-4, atol=2e-4,
    )
