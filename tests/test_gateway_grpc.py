"""Integration tests of the full gRPC stack against the mock backend —
the zero-TPU test discipline from SURVEY.md §4 (the mock is the fake
backend, as in the reference's integration tier)."""

import io
import json
import urllib.request

import grpc
import pytest

from polykey_tpu.gateway import server as gateway_server
from polykey_tpu.gateway.jsonlog import Logger
from polykey_tpu.gateway.mock_service import MockService
from polykey_tpu.gateway.service import Service
from polykey_tpu.obs import MetricsHTTPServer, Observability
from polykey_tpu.proto import health_v1_pb2 as health_pb
from polykey_tpu.proto import polykey_v2_pb2 as pk
from polykey_tpu.proto import reflection_v1alpha_pb2 as refl_pb
from polykey_tpu.proto.health_v1_grpc import HealthStub
from polykey_tpu.proto.polykey_v2_grpc import PolykeyServiceStub


class _FailingService(Service):
    def execute_tool(self, tool_name, parameters, secret_id, metadata):
        raise RuntimeError("backend exploded")


@pytest.fixture()
def stack():
    log_buffer = io.StringIO()
    logger = Logger(stream=log_buffer, level="debug")
    server, health, port = gateway_server.build_server(
        MockService(), logger, address="127.0.0.1:0"
    )
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield channel, health, log_buffer
    channel.close()
    server.stop(grace=None)


def test_execute_tool_roundtrip(stack):
    channel, _, log_buffer = stack
    stub = PolykeyServiceStub(channel)
    req = pk.ExecuteToolRequest(tool_name="example_tool", secret_id="secret-123")
    req.parameters.update({"example_param": "value"})
    req.metadata.fields["request_id"] = "r1"
    resp = stub.ExecuteTool(req, timeout=5)
    assert resp.status.code == 200
    assert resp.string_output.startswith("Mock execution of example_tool at ")
    logs = log_buffer.getvalue()
    # Interceptor parity: received + finished lines with OK code.
    assert '"msg":"gRPC call received"' in logs
    assert '"code":"OK"' in logs
    # Handler parity (server.go:28-33): request-shape log line.
    assert '"has_parameters":true' in logs
    assert '"has_secret_id":true' in logs


def test_execute_tool_stream(stack):
    channel, _, _ = stack
    stub = PolykeyServiceStub(channel)
    req = pk.ExecuteToolRequest(tool_name="file_tool")
    chunks = list(stub.ExecuteToolStream(req, timeout=5))
    assert chunks[-1].final
    assert chunks[-1].status.code == 200


def test_service_error_maps_to_unknown():
    # A bare service error surfaces as code Unknown, like a plain Go error.
    server, health, port = gateway_server.build_server(
        _FailingService(), Logger(stream=io.StringIO()), address="127.0.0.1:0"
    )
    server.start()
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
            stub = PolykeyServiceStub(channel)
            with pytest.raises(grpc.RpcError) as err:
                stub.ExecuteTool(pk.ExecuteToolRequest(tool_name="x"), timeout=5)
            assert err.value.code() == grpc.StatusCode.UNKNOWN
            assert "backend exploded" in err.value.details()
    finally:
        server.stop(grace=None)


def test_health_statuses(stack):
    channel, health, _ = stack
    stub = HealthStub(channel)
    # Both the service name and "" are SERVING (main.go:93-94 parity).
    for name in ("polykey.v2.PolykeyService", ""):
        resp = stub.Check(health_pb.HealthCheckRequest(service=name), timeout=5)
        assert resp.status == health_pb.HealthCheckResponse.SERVING
    # Unknown service → NOT_FOUND (grpc-go health server semantics).
    with pytest.raises(grpc.RpcError) as err:
        stub.Check(health_pb.HealthCheckRequest(service="nope"), timeout=5)
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_health_shutdown_forces_not_serving(stack):
    channel, health, _ = stack
    stub = HealthStub(channel)
    health.shutdown()
    resp = stub.Check(health_pb.HealthCheckRequest(service=""), timeout=5)
    assert resp.status == health_pb.HealthCheckResponse.NOT_SERVING
    # SetServingStatus after Shutdown is ignored.
    health.set_serving_status("", health_pb.HealthCheckResponse.SERVING)
    resp = stub.Check(health_pb.HealthCheckRequest(service=""), timeout=5)
    assert resp.status == health_pb.HealthCheckResponse.NOT_SERVING


def test_health_check_not_logged(stack):
    channel, _, log_buffer = stack
    stub = HealthStub(channel)
    stub.Check(health_pb.HealthCheckRequest(service=""), timeout=5)
    # Interceptor skips /grpc.health.v1.Health/Check (main.go:29-31 parity).
    assert "Health/Check" not in log_buffer.getvalue()


def test_reflection_list_and_lookup(stack):
    channel, _, _ = stack
    refl = channel.stream_stream(
        "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo",
        request_serializer=refl_pb.ServerReflectionRequest.SerializeToString,
        response_deserializer=refl_pb.ServerReflectionResponse.FromString,
    )
    requests = [
        refl_pb.ServerReflectionRequest(list_services=""),
        refl_pb.ServerReflectionRequest(
            file_containing_symbol="polykey.v2.PolykeyService"
        ),
    ]
    responses = list(refl.__call__(iter(requests), timeout=5))
    services = {s.name for s in responses[0].list_services_response.service}
    assert "polykey.v2.PolykeyService" in services
    assert "grpc.health.v1.Health" in services
    files = responses[1].file_descriptor_response.file_descriptor_proto
    assert len(files) >= 2  # polykey_v2.proto + its imports


@pytest.fixture()
def traced_stack():
    """Full stack with observability wired: interceptor tracing + RPC
    counters + the /metrics exposition endpoint."""
    obs = Observability()
    log_buffer = io.StringIO()
    logger = Logger(stream=log_buffer, level="debug")
    server, health, port = gateway_server.build_server(
        MockService(), logger, address="127.0.0.1:0", obs=obs
    )
    server.start()
    metrics = MetricsHTTPServer(obs.registry, host="127.0.0.1", port=0)
    metrics.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield channel, obs, metrics.port, log_buffer
    channel.close()
    metrics.stop()
    server.stop(grace=None)


def test_trace_id_logged_and_echoed(traced_stack):
    channel, obs, _, log_buffer = traced_stack
    stub = PolykeyServiceStub(channel)
    call = stub.ExecuteTool.with_call(
        pk.ExecuteToolRequest(tool_name="example_tool"),
        timeout=5,
        metadata=(("x-trace-id", "deadbeef01020304"),),
    )
    _, rpc = call
    # Client-supplied trace id is echoed in trailing metadata...
    trailing = {k: v for k, v in rpc.trailing_metadata()}
    assert trailing.get("x-trace-id") == "deadbeef01020304"
    # ...and appears on both interceptor log lines.
    lines = [json.loads(l) for l in log_buffer.getvalue().splitlines()]
    traced = [l for l in lines if l.get("trace_id")]
    assert any(l["msg"] == "gRPC call received" for l in traced)
    assert any(l["msg"] == "gRPC call finished" for l in traced)
    assert all(l["trace_id"] == "deadbeef01020304" for l in traced)
    # Childless OK RPCs are NOT filed in the flight recorder: routine
    # mock-tool / engine_stats polls must never evict the span trees the
    # recorder exists to preserve.
    assert obs.recorder.last() is None


def test_trace_id_minted_when_absent(traced_stack):
    channel, _, _, log_buffer = traced_stack
    stub = PolykeyServiceStub(channel)
    _, rpc = stub.ExecuteTool.with_call(
        pk.ExecuteToolRequest(tool_name="example_tool"), timeout=5
    )
    trailing = {k: v for k, v in rpc.trailing_metadata()}
    minted = trailing.get("x-trace-id")
    assert minted and len(minted) == 16
    assert minted in log_buffer.getvalue()


def test_oversized_trace_id_replaced(traced_stack):
    """Client-supplied ids outside 1-64 [A-Za-z0-9_-] are ignored: they
    fan out to trailers, logs, and recorded spans, so a hostile client
    must not control their size or charset."""
    channel, _, _, _ = traced_stack
    stub = PolykeyServiceStub(channel)
    _, rpc = stub.ExecuteTool.with_call(
        pk.ExecuteToolRequest(tool_name="example_tool"),
        timeout=5,
        metadata=(("x-trace-id", "x" * 500),),
    )
    trailing = {k: v for k, v in rpc.trailing_metadata()}
    echoed = trailing.get("x-trace-id")
    assert echoed and len(echoed) == 16 and echoed != "x" * 500


def test_metrics_endpoint_smoke(traced_stack):
    """Exposition smoke: hit RPCs, then scrape /metrics and check the
    gateway families render as valid Prometheus text."""
    channel, _, metrics_port, _ = traced_stack
    stub = PolykeyServiceStub(channel)
    stub.ExecuteTool(pk.ExecuteToolRequest(tool_name="example_tool"), timeout=5)
    list(stub.ExecuteToolStream(pk.ExecuteToolRequest(tool_name="file_tool"),
                                timeout=5))
    with urllib.request.urlopen(
        f"http://127.0.0.1:{metrics_port}/metrics", timeout=5
    ) as resp:
        assert resp.status == 200
        assert "text/plain" in resp.headers["Content-Type"]
        body = resp.read().decode()
    assert "# TYPE polykey_rpcs_total counter" in body
    assert (
        'polykey_rpcs_total{code="OK",'
        'method="/polykey.v2.PolykeyService/ExecuteTool"} 1'
    ) in body
    assert (
        'polykey_rpcs_total{code="OK",'
        'method="/polykey.v2.PolykeyService/ExecuteToolStream"} 1'
    ) in body


def test_failed_rpc_recorded_for_postmortem():
    """Non-OK RPCs are filed in the flight recorder even without child
    spans — failures are exactly what postmortems go looking for."""
    obs = Observability()
    server, health, port = gateway_server.build_server(
        _FailingService(), Logger(stream=io.StringIO()),
        address="127.0.0.1:0", obs=obs,
    )
    server.start()
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
            stub = PolykeyServiceStub(channel)
            with pytest.raises(grpc.RpcError):
                stub.ExecuteTool(
                    pk.ExecuteToolRequest(tool_name="x"), timeout=5
                )
        trace = obs.recorder.last()
        assert trace is not None
        assert trace["name"].endswith("ExecuteTool")
        assert trace["attrs"]["code"] != "OK"
    finally:
        server.stop(grace=None)


def test_reflection_v1_list_and_lookup(stack):
    """grpc-go's reflection.Register serves v1 AND v1alpha (modern grpcurl
    tries v1 first); the v1 protocol is wire-identical, so the same
    queries must succeed on the v1 method path and list both reflection
    service names."""
    channel, _, _ = stack
    refl = channel.stream_stream(
        "/grpc.reflection.v1.ServerReflection/ServerReflectionInfo",
        request_serializer=refl_pb.ServerReflectionRequest.SerializeToString,
        response_deserializer=refl_pb.ServerReflectionResponse.FromString,
    )
    requests = [
        refl_pb.ServerReflectionRequest(list_services=""),
        refl_pb.ServerReflectionRequest(
            file_containing_symbol="polykey.v2.PolykeyService"
        ),
        # Every ADVERTISED service must describe (grpcurl walks the list).
        refl_pb.ServerReflectionRequest(
            file_containing_symbol="grpc.reflection.v1.ServerReflection"
        ),
    ]
    responses = list(refl.__call__(iter(requests), timeout=5))
    services = {s.name for s in responses[0].list_services_response.service}
    assert "grpc.reflection.v1.ServerReflection" in services
    assert "grpc.reflection.v1alpha.ServerReflection" in services
    assert "polykey.v2.PolykeyService" in services
    files = responses[1].file_descriptor_response.file_descriptor_proto
    assert len(files) >= 2
    v1_files = responses[2].file_descriptor_response.file_descriptor_proto
    assert v1_files, "v1 reflection service descriptor must resolve"
    assert responses[2].WhichOneof("message_response") == (
        "file_descriptor_response"
    )
