"""Long-context at the design envelope: 16-32k through the ENGINE.

VERDICT r4 weak #4 / next #5: 8k was the tested ceiling and nothing
composed sequence-parallel prefill with the paged engine beyond the op
level. These tests drive 16k and 32k position budgets through the full
serving path — chunked prefill + prefix cache + sp-sharded prefill +
context-parallel decode (the paged kernel's page-axis shard with online
softmax merge, ops/paged_attention_kernel.py) — and pin exact greedy
equality against the unsharded engine, so the sp layout can never change
the math. SURVEY.md §5: "sequences beyond one chip's HBM" — on the CPU
mesh the scale is virtual, the code path is the real one.

Geometry: tiny-llama (byte tokenizer ⇒ 1 char ≈ 1 token), fp32 so
reduction-order drift can't flip an argmax. Chunk 512 keeps the host
loop to tens of iterations at 16k (the 8k tier's chunk-64 is a boundary
stress; here the subject is scale).
"""

import dataclasses
import queue
import time

import jax
import numpy as np
import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine

# The XL tier is the slowest block in the suite by far (~11 min of the
# ~32 min total on a 2-core box: 16-32k contexts through real chunked
# prefill are execution-bound, not compile-bound). The fast tier-1 gate
# (-m 'not slow') skips it; `make test` / `make ci-check` and any
# unfiltered pytest run still execute it in full.
pytestmark = pytest.mark.slow

XL16K = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=2,
    page_size=16,
    # 2 slots x 16k/16 pages + garbage page + prefix-cache headroom.
    num_pages=2 * 1024 + 512,
    max_seq_len=16384,
    prefill_buckets=(256, 512),
    prefill_chunk=512,
    max_new_tokens_cap=16,
    default_max_new_tokens=8,
)


def _prompt(n: int, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    return "".join(chr(c) for c in rng.integers(97, 123, n))


def _collect(request: GenRequest, timeout=900.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _run_prompts(config, prompts, max_new=8, sequential=False):
    """Serve prompts; return ([tokens...], engine_stats). sequential=True
    drains each request before submitting the next — a prefix inserted by
    request N is then visible to request N+1 (concurrent admission races
    past the insert, a load-pattern artifact, not a cache property)."""
    eng = InferenceEngine(config)
    try:
        outs = []
        reqs = [GenRequest(prompt=p, max_new_tokens=max_new) for p in prompts]
        if sequential:
            for r in reqs:
                eng.submit(r)
                tokens, done, error = _collect(r)
                assert error is None, error
                assert done is not None, "request did not finish"
                outs.append((tokens, done))
        else:
            for r in reqs:
                eng.submit(r)
            for r in reqs:
                tokens, done, error = _collect(r)
                assert error is None, error
                assert done is not None, "request did not finish"
                outs.append((tokens, done))
        return outs, eng.stats()
    finally:
        eng.shutdown()


_needs2 = pytest.mark.skipif(
    jax.device_count() < 2, reason="needs 2 devices")


# Two 12k prompts sharing an 8k (page-aligned) prefix: exercises chunked
# prefill, the prefix cache, and concurrent CP decode in ONE serving run.
_SHARED = _prompt(8192, seed=10)
_PROMPTS_16K = [_SHARED + _prompt(4096, seed=11),
                _SHARED + _prompt(4096, seed=12)]


@pytest.fixture(scope="module")
def ref_16k():
    """Unsharded, uncached reference streams for the 16k workload."""
    outs, _ = _run_prompts(XL16K, _PROMPTS_16K)
    return outs


def test_16k_chunked_serves_and_fits(ref_16k):
    for tokens, done in ref_16k:
        assert done.prompt_tokens >= 12 * 1024
        assert len(tokens) == 8


@_needs2
def test_16k_sp2_prefix_cache_matches_reference(ref_16k):
    """The full composition — sp=2 sequence-parallel chunked prefill,
    prefix-cache reuse of the shared 8k prefix, context-parallel paged
    decode — must reproduce the unsharded engine's exact greedy streams."""
    cfg = dataclasses.replace(XL16K, sp=2, prefix_cache=True)
    outs, stats = _run_prompts(cfg, _PROMPTS_16K, sequential=True)
    for (tokens, done), (ref_tokens, ref_done) in zip(outs, ref_16k):
        assert tokens == ref_tokens
        assert done.prompt_tokens == ref_done.prompt_tokens
    # The second prompt must have actually reused the shared prefix
    # (8192 chars / 16 page = 512 pages of cached KV).
    assert stats["prefix_hit_tokens"] >= 8192 - XL16K.page_size


@_needs2
def test_16k_sp2_int8_kv_serves():
    """sp-sharded prefill writing QUANTIZED pools at 16k: the int8 KV
    path (per-(token,head) scales) through the same composition. Greedy
    streams may legitimately differ from fp32 KV, so the assertion is
    completion + position accounting, not token equality."""
    cfg = dataclasses.replace(XL16K, sp=2, kv_dtype="int8")
    outs, _ = _run_prompts(cfg, [_PROMPTS_16K[0]])
    (tokens, done), = outs
    assert done.prompt_tokens >= 12 * 1024
    assert len(tokens) == 8


def test_32k_position_budget_single_stream():
    """The 32k tier: one 24k-token prompt chunk-prefills into a 32k
    position budget and decodes. Single stream + page_size 32 keeps the
    CPU wall-clock bounded; the position/page accounting at 32k is what
    8k could not cover."""
    cfg = dataclasses.replace(
        XL16K,
        max_decode_slots=1,
        page_size=32,
        num_pages=1024 + 32,         # 1 slot x 32k/32 + headroom
        max_seq_len=32768,
        prefill_buckets=(512, 1024),
        prefill_chunk=1024,
    )
    outs, _ = _run_prompts(cfg, [_prompt(24_000, seed=13)], max_new=4)
    (tokens, done), = outs
    assert done.prompt_tokens >= 24_000
    assert len(tokens) == 4
