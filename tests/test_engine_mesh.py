"""Meshed serving engine: tp/dp-sharded decode must match single-device.

VERDICT r1 #2: the engine's tp/dp knobs must actually shard params, page
pools, and the decode batch (parallel/sharding.py specs). The acceptance
check is exact greedy equality — same tokens from a tp=2 / dp=2 / tp×dp
engine as from the tp=dp=1 engine (fp32 on the simulated CPU mesh, so
reduction-order drift can't flip an argmax for these seeds).
"""

import dataclasses
import queue
import time

import jax
import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine

BASE_CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    num_pages=64,
    max_seq_len=64,
    prefill_buckets=(16, 32),
    max_new_tokens_cap=32,
    default_max_new_tokens=8,
)

PROMPTS = ["hello mesh", "sharded decoding", "a", "the quick brown fox"]


def _collect(request: GenRequest, timeout=60.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _run_prompts_for(config: EngineConfig, prompts):
    eng = InferenceEngine(config)
    try:
        requests = [GenRequest(prompt=p, max_new_tokens=8) for p in prompts]
        for r in requests:
            eng.submit(r)
        outs = []
        for r in requests:
            tokens, done, error = _collect(r)
            assert error is None, error
            assert done is not None
            outs.append(tokens)
        return outs
    finally:
        eng.shutdown()


def _run_prompts(config: EngineConfig, quantize: bool = False):
    return _run_prompts_for(
        dataclasses.replace(config, quantize=quantize), PROMPTS
    )


@pytest.fixture(scope="module")
def reference_outputs():
    return _run_prompts(BASE_CONFIG)


def _needs(n):
    return pytest.mark.skipif(
        jax.device_count() < n, reason=f"needs {n} devices"
    )


@_needs(2)
def test_tp2_matches_single_device(reference_outputs):
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, tp=2)
    ) == reference_outputs


@_needs(2)
def test_dp2_matches_single_device(reference_outputs):
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, dp=2)
    ) == reference_outputs


@_needs(4)
def test_tp2_dp2_matches_single_device(reference_outputs):
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, tp=2, dp=2)
    ) == reference_outputs


@_needs(2)
def test_tp2_quantized_matches_quantized(reference_outputs):
    # Quantized trees shard through the same specs (QuantizedTensor q/s
    # leaves — parallel/sharding._spec_for_path); equality target is the
    # single-device *quantized* engine since int8 changes the logits.
    ref = _run_prompts(BASE_CONFIG, quantize=True)
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, tp=2), quantize=True
    ) == ref


MOE_CONFIG = dataclasses.replace(BASE_CONFIG, model="tiny-mixtral")


@pytest.fixture(scope="module")
def moe_reference_outputs():
    return _run_prompts(MOE_CONFIG)


@_needs(2)
def test_ep2_moe_matches_single_device(moe_reference_outputs):
    """Expert-parallel serving (measurement config 4): expert weights shard
    over ep (parallel/sharding.py experts rules) and the engine's greedy
    output must match the unsharded MoE engine exactly."""
    assert _run_prompts(
        dataclasses.replace(MOE_CONFIG, ep=2)
    ) == moe_reference_outputs


@_needs(4)
def test_ep2_tp2_moe_matches_single_device(moe_reference_outputs):
    assert _run_prompts(
        dataclasses.replace(MOE_CONFIG, ep=2, tp=2)
    ) == moe_reference_outputs


@_needs(2)
def test_pp2_matches_single_device(reference_outputs):
    """Layer-sharded serving: params and both page pools shard their
    stacked-layer axis over pp (capacity for models beyond one chip's
    HBM); greedy output must match exactly."""
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, pp=2)
    ) == reference_outputs


@_needs(2)
def test_sp2_matches_single_device(reference_outputs):
    """Sequence-parallel prefill: the window's token axis shards over sp
    (compute spread + GSPMD KV exchange into the sp-replicated pools);
    decode is untouched. Greedy output must match exactly."""
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, sp=2)
    ) == reference_outputs


@_needs(2)
def test_sp2_chunked_long_prompt_matches():
    """Long prompts chunk through the same sp-sharded prefill window."""
    import numpy as np

    rng = np.random.default_rng(5)
    prompt = "".join(chr(c) for c in rng.integers(97, 123, 120))
    cfg = dataclasses.replace(
        BASE_CONFIG, max_seq_len=256, num_pages=128, prefill_chunk=32
    )
    ref = _run_prompts_for(cfg, [prompt])
    assert _run_prompts_for(
        dataclasses.replace(cfg, sp=2), [prompt]
    ) == ref


def test_bad_geometry_rejected():
    with pytest.raises(ValueError):
        InferenceEngine(dataclasses.replace(BASE_CONFIG, dp=3))  # 3 ∤ 4 slots
    with pytest.raises(ValueError):
        # tiny-llama has 2 kv heads; tp=4 can't shard them.
        InferenceEngine(dataclasses.replace(BASE_CONFIG, tp=4))
    with pytest.raises(ValueError):
        # ep requires an MoE model.
        InferenceEngine(dataclasses.replace(BASE_CONFIG, ep=2))
    with pytest.raises(ValueError):
        # sp must divide every prefill bucket (buckets are 16, 32).
        dataclasses.replace(BASE_CONFIG, sp=3).validate()
    with pytest.raises(ValueError):
        # Axis values below 1 (e.g. POLYKEY_SP=0 typo) must fail loudly,
        # not build a zero-device mesh.
        dataclasses.replace(BASE_CONFIG, sp=0).validate()


@_needs(8)
def test_hybrid_2slices_matches_single_device(reference_outputs):
    """num_slices=2: the engine builds a hybrid DCN mesh
    (parallel/distributed.py:create_hybrid_mesh) with per-slice dp=2
    folded into a dp axis of 4 across two simulated slices; greedy
    serving output must be bit-identical to the single-device engine."""
    assert _run_prompts(
        dataclasses.replace(BASE_CONFIG, tp=2, dp=2, num_slices=2)
    ) == reference_outputs


@_needs(4)
def test_tp4_matches_single_device(monkeypatch):
    """Config 3's axis at its real degree: tp=4 serving (Llama-3-8B has
    Hk=8; the tiny stand-in needs 4 kv heads for tp=4 to divide). Greedy
    output must equal the single-device engine's for the same model."""
    from polykey_tpu.models.config import MODEL_REGISTRY, TINY_LLAMA

    monkeypatch.setitem(
        MODEL_REGISTRY, "tiny-llama-4kv",
        dataclasses.replace(
            TINY_LLAMA, name="tiny-llama-4kv", num_heads=8, num_kv_heads=4
        ),
    )
    cfg = dataclasses.replace(BASE_CONFIG, model="tiny-llama-4kv")
    ref = _run_prompts(cfg)
    assert _run_prompts(dataclasses.replace(cfg, tp=4)) == ref


@_needs(2)
def test_tp2_int4_matches_int4(reference_outputs):
    """int4 trees shard through the same specs (group-wise scales take
    the weight's spec — the group axis sits in the contraction position,
    so row-parallel tp shards groups consistently). Greedy equality vs
    the single-device int4 engine."""
    del reference_outputs  # int4 logits differ from fp; compare int4 vs int4
    cfg_q4 = dataclasses.replace(BASE_CONFIG, quantize=True, quantize_bits=4)
    assert _run_prompts_for(
        dataclasses.replace(cfg_q4, tp=2), PROMPTS
    ) == _run_prompts_for(cfg_q4, PROMPTS)


def test_tp2_int8_kv_matches_single_device(reference_outputs):
    """int8 KV pools shard through the PagedKV sharding pytree (data
    pools head-sharded on dim 2, scale pools on their LAST dim) and the
    quantized write/read paths run under GSPMD. Greedy equality vs the
    single-device int8-KV engine (int8-KV logits differ from fp, so the
    comparison is int8-KV vs int8-KV)."""
    del reference_outputs
    cfg_kv = dataclasses.replace(BASE_CONFIG, kv_dtype="int8")
    assert _run_prompts_for(
        dataclasses.replace(cfg_kv, tp=2), PROMPTS
    ) == _run_prompts_for(cfg_kv, PROMPTS)


def test_sp2_int8_kv_matches_single_device(reference_outputs):
    """sp=2 with int8 KV: sequence-parallel prefill writes quantized
    pages into the sp-replicated (values, scales) pools via GSPMD, and
    context-parallel decode merges the quantized kernel's partial
    softmax states across the page sub-ranges. Greedy equality vs the
    single-device int8-KV engine."""
    del reference_outputs
    cfg_kv = dataclasses.replace(BASE_CONFIG, kv_dtype="int8")
    assert _run_prompts_for(
        dataclasses.replace(cfg_kv, sp=2), PROMPTS
    ) == _run_prompts_for(cfg_kv, PROMPTS)
