"""slog-style JSON logger format tests."""

import io
import json

from polykey_tpu.gateway.jsonlog import Logger, go_duration


def test_record_shape():
    buf = io.StringIO()
    Logger(stream=buf).info("hello", a=1, b="x", c=None, d=b"bytes")
    record = json.loads(buf.getvalue())
    assert record["level"] == "INFO"
    assert record["msg"] == "hello"
    assert record["a"] == 1 and record["b"] == "x" and record["c"] is None
    assert record["d"] == "bytes"
    assert "T" in record["time"]  # RFC3339


def test_level_filtering():
    buf = io.StringIO()
    log = Logger(stream=buf, level="info")
    log.debug("hidden")
    log.warn("shown")
    lines = buf.getvalue().splitlines()
    assert len(lines) == 1
    assert json.loads(lines[0])["level"] == "WARN"


def test_nonserializable_attr_stringified():
    buf = io.StringIO()
    Logger(stream=buf).info("x", obj=object())
    assert "object object" in json.loads(buf.getvalue())["obj"]


def test_go_duration_units():
    assert go_duration(5e-7).endswith("ns") or go_duration(5e-7).endswith("µs")
    assert go_duration(0.000160644) == "160.644µs"
    assert go_duration(0.0123).endswith("ms")
    assert go_duration(2.5) == "2.5s"
    assert go_duration(90) == "1m30s"
    assert go_duration(3725) == "1h2m5s"
