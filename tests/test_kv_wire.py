"""KV handoff wire format (ISSUE 13; engine/kv_cache.py).

The contracts under test: serialize → deserialize is BIT-identical for
fp32 and int8 pair-form pools (raw-byte round-trip, no dtype
conversion anywhere); version/magic/geometry mismatches reject with the
typed KVWireError BEFORE any target-pool write; a truncated payload
(partial write) is detected by framing/CRC and rejects cleanly — the
disagg coordinator turns that into a re-route, never a corrupted pool.
"""

import struct

import numpy as np
import pytest

from polykey_tpu.engine.kv_cache import (
    KV_WIRE_MAGIC,
    KV_WIRE_VERSION,
    KVHandoffState,
    KVWireError,
    deserialize_kv_state,
    serialize_kv_state,
    validate_kv_blob,
)
from polykey_tpu.models.config import get_config


def _state(quantized: bool = False, dtype=np.float32,
           prompt_len: int = 19, page_size: int = 8) -> KVHandoffState:
    cfg = get_config("tiny-llama")
    rng = np.random.default_rng(11)
    n_pages = -(-prompt_len // page_size)
    shape = (cfg.num_layers, n_pages, page_size, cfg.num_kv_heads,
             cfg.head_dim)
    if quantized:
        k = rng.integers(-127, 128, shape, dtype=np.int8)
        v = rng.integers(-127, 128, shape, dtype=np.int8)
        ks = rng.random(shape[:-1]).astype(np.float32)
        vs = rng.random(shape[:-1]).astype(np.float32)
    else:
        k = rng.random(shape).astype(dtype)
        v = rng.random(shape).astype(dtype)
        ks = vs = None
    return KVHandoffState(
        model=cfg.name, page_size=page_size, prompt_len=prompt_len,
        first_token=360, seed=0xDEADBEEFCAFE,
        prompt_ids=rng.integers(0, 500, prompt_len).astype(np.int32),
        k=k, v=v, ks=ks, vs=vs,
    )


def test_roundtrip_fp32_bit_identical():
    state = _state()
    blob = serialize_kv_state(state)
    back = deserialize_kv_state(blob)
    assert back.model == state.model
    assert back.prompt_len == state.prompt_len
    assert back.first_token == state.first_token
    assert back.seed == state.seed
    assert back.k.dtype == state.k.dtype
    assert back.k.tobytes() == state.k.tobytes()
    assert back.v.tobytes() == state.v.tobytes()
    assert back.ks is None and back.vs is None
    assert np.array_equal(back.prompt_ids, state.prompt_ids)


def test_roundtrip_int8_pair_form_bit_identical():
    state = _state(quantized=True)
    blob = serialize_kv_state(state)
    back = deserialize_kv_state(blob)
    assert back.quantized
    assert back.k.dtype == np.int8
    assert back.k.tobytes() == state.k.tobytes()
    assert back.ks.tobytes() == state.ks.tobytes()
    assert back.vs.tobytes() == state.vs.tobytes()


def test_version_mismatch_rejects():
    blob = bytearray(serialize_kv_state(_state()))
    head = len(KV_WIRE_MAGIC)
    blob[head:head + 2] = struct.pack("!H", KV_WIRE_VERSION + 1)
    with pytest.raises(KVWireError, match="version"):
        deserialize_kv_state(bytes(blob))


def test_bad_magic_rejects():
    blob = b"XXXX" + serialize_kv_state(_state())[4:]
    with pytest.raises(KVWireError, match="magic"):
        deserialize_kv_state(blob)


def test_truncated_payload_rejects_cleanly():
    blob = serialize_kv_state(_state())
    # Partial write at any cut point: framing (or CRC) must catch it.
    for cut in (8, len(blob) // 3, len(blob) // 2, len(blob) - 1):
        with pytest.raises(KVWireError):
            deserialize_kv_state(blob[:cut])
        with pytest.raises(KVWireError):
            validate_kv_blob(blob[:cut])


def test_corrupt_payload_fails_crc():
    blob = bytearray(serialize_kv_state(_state()))
    blob[-40] ^= 0xFF        # flip a payload byte, keep the length
    with pytest.raises(KVWireError, match="CRC"):
        validate_kv_blob(bytes(blob))


def test_geometry_mismatch_is_typed_not_corrupting():
    cfg = get_config("tiny-llama")
    state = _state()
    # Wrong model name.
    state.model = "tiny-gemma"
    with pytest.raises(KVWireError, match="model mismatch"):
        state.validate_for(cfg, page_size=8, quantized=False)
    # Wrong page size.
    state = _state()
    with pytest.raises(KVWireError, match="page_size"):
        state.validate_for(cfg, page_size=16, quantized=False)
    # Quantization mismatch (int8 blob into an fp pool and vice versa).
    with pytest.raises(KVWireError, match="dtype mismatch"):
        _state(quantized=True).validate_for(cfg, page_size=8,
                                            quantized=False)
    with pytest.raises(KVWireError, match="dtype mismatch"):
        _state().validate_for(cfg, page_size=8, quantized=True)
    # Page count must exactly cover prompt_len.
    state = _state()
    state.prompt_len = 40    # needs 5 pages, blob carries 3
    with pytest.raises(KVWireError, match="page count"):
        state.validate_for(cfg, page_size=8, quantized=False)
    # A matching state passes.
    _state().validate_for(cfg, page_size=8, quantized=False)


def test_engine_rejects_mismatched_handoff_without_pool_write():
    """End-to-end teeth: a decode engine receiving a geometry-mismatched
    blob fails the REQUEST with the typed kv-handoff marker and leaves
    its own pool/allocator untouched (no partial state)."""
    from polykey_tpu.engine.config import EngineConfig
    from polykey_tpu.engine.engine import GenRequest, InferenceEngine

    cfg = EngineConfig(
        model="tiny-llama", dtype="float32", max_decode_slots=2,
        page_size=16, num_pages=64, max_seq_len=64,
        prefill_buckets=(16,), supervise=False,
    )
    engine = InferenceEngine(cfg, seed=3)
    try:
        free_before = engine.allocator.num_free
        state = _state(page_size=8)           # pool runs page_size=16
        request = GenRequest(prompt="", max_new_tokens=4,
                             resume_state=state)
        engine.submit(request)
        kind, value = request.out.get(timeout=60)
        assert kind == "error"
        assert "kv-handoff" in value
        assert engine.allocator.num_free == free_before
    finally:
        engine.shutdown()
