"""Edge-case engine/sampling tests added from review findings."""

import queue
import time

import jax
import jax.numpy as jnp

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.engine.sampling import SamplingParams, sample, sample_dynamic


def test_top_p_zero_degrades_to_greedy():
    logits = jnp.array([[0.0, 3.0, 1.0, -2.0]], dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    # Static path.
    out = sample(logits, key, SamplingParams(temperature=1.0, top_p=0.0))
    assert int(out[0]) == 1
    # Dynamic (per-row) path.
    out = sample_dynamic(
        logits, key, jnp.array([1.0]), jnp.array([0.0], dtype=jnp.float32)
    )
    assert int(out[0]) == 1


def test_shutdown_fails_inflight_requests():
    config = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=2, page_size=8, num_pages=32, max_seq_len=64,
        prefill_buckets=(16,), max_new_tokens_cap=64,
        default_max_new_tokens=32,
    )
    engine = InferenceEngine(config)
    request = GenRequest(prompt="long", max_new_tokens=64, temperature=1.0)
    engine.submit(request)
    request.out.get(timeout=30)  # first token: the request is in-flight
    engine.shutdown()
    # The in-flight request must receive a terminal event promptly, not
    # block until the request timeout.
    deadline = time.monotonic() + 5
    terminal = None
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=0.5)
        except queue.Empty:
            continue
        if kind in ("done", "error"):
            terminal = (kind, value)
            break
    assert terminal is not None
    assert terminal[0] == "error"


def test_oversize_max_tokens_clamped():
    config = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=1, page_size=8, num_pages=32, max_seq_len=32,
        prefill_buckets=(16,), max_new_tokens_cap=1000,  # cap > max_seq_len
        default_max_new_tokens=4,
    )
    engine = InferenceEngine(config)
    try:
        request = GenRequest(prompt="x" * 100, max_new_tokens=1000)
        engine.submit(request)
        tokens, done, error = [], None, None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            kind, value = request.out.get(timeout=60)
            if kind == "token":
                tokens.append(value)
            else:
                done, error = (value, None) if kind == "done" else (None, value)
                break
        assert error is None, error
        assert done is not None
        # Never exceeds the position cap implied by max_seq_len.
        assert done.prompt_tokens + done.completion_tokens <= config.max_seq_len
    finally:
        engine.shutdown()
