"""Edge-case engine/sampling tests added from review findings."""

import queue
import time

import jax
import jax.numpy as jnp

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.engine.sampling import SamplingParams, sample, sample_dynamic


def test_top_p_zero_degrades_to_greedy():
    logits = jnp.array([[0.0, 3.0, 1.0, -2.0]], dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    # Static path.
    out = sample(logits, key, SamplingParams(temperature=1.0, top_p=0.0))
    assert int(out[0]) == 1
    # Dynamic (per-row) path.
    out = sample_dynamic(
        logits, key, jnp.array([1.0]), jnp.array([0.0], dtype=jnp.float32)
    )
    assert int(out[0]) == 1


def test_top_p_candidate_prefilter_matches_exact():
    """The lax.top_k prefilter path must match the exact full-vocab
    sampler in distribution when the candidate set covers the top-p
    support. Draws differ for the same key (categorical draws
    vocab-shaped vs candidate-shaped Gumbel noise), so the check is:
    greedy rows identical, degenerate p collapses to argmax, every
    prefiltered sample lands inside the exact keep-set, and empirical
    frequencies over many keys agree."""
    import numpy as np

    logits = jax.random.normal(jax.random.PRNGKey(4), (4, 64)) * 3.0
    temps = jnp.array([0.0, 1.0, 0.8, 1.2], jnp.float32)
    top_ps = jnp.array([1.0, 0.6, 0.9, 0.01], jnp.float32)

    # Exact keep-set per row (same math as _top_p_keep_mask, in numpy).
    ln = np.asarray(logits, np.float64) / np.maximum(np.asarray(temps), 1e-6)[:, None]
    order = np.argsort(-ln, axis=-1)
    keep_sets = []
    for b in range(ln.shape[0]):
        probs = np.exp(ln[b, order[b]] - ln[b, order[b]].max())
        probs /= probs.sum()
        cum = np.cumsum(probs)
        n_keep = max(1, int(np.sum(cum - probs < float(top_ps[b]))))
        keep_sets.append(set(order[b, :n_keep].tolist()))

    keys = jax.random.split(jax.random.PRNGKey(7), 384)
    exact = jax.vmap(lambda k: sample_dynamic(logits, k, temps, top_ps))(keys)
    pre = jax.vmap(
        lambda k: sample_dynamic(logits, k, temps, top_ps, candidates=32)
    )(keys)
    exact, pre = np.asarray(exact), np.asarray(pre)

    assert (exact[:, 0] == pre[:, 0]).all()          # greedy row
    assert (pre[:, 3] == exact[:, 3]).all()          # p=0.01 → argmax row
    # Row 1 has top_p < 1 and a candidate set covering its support; rows
    # with top_p >= 1 bypass the prefilter (untruncated full-vocab draw),
    # so their support is the whole vocabulary by construction.
    for b in (1, 2, 3):
        assert set(pre[:, b].tolist()) <= keep_sets[b]
        assert set(exact[:, b].tolist()) <= keep_sets[b]
        # Empirical distributions over the shared support agree loosely.
        for tok in keep_sets[b]:
            fe = float((exact[:, b] == tok).mean())
            fp = float((pre[:, b] == tok).mean())
            assert abs(fe - fp) < 0.12, (b, tok, fe, fp)


def test_top_p_candidate_boundary_token_normalization():
    """The prefilter's keep rule must use FULL-vocab probabilities (review
    finding: candidate-local renormalization shrinks the keep set). Head
    probs [0.3, 0.3, 0.28, 0.07], tail 0.05 across the rest, top_p=0.9:
    token 3's full-vocab cum-minus-own is 0.88 < 0.9 → exact keeps it.
    Candidate-local renormalization over the top-16 (mass ≈ 0.952) would
    compute 0.88/0.952 ≈ 0.924 ≥ 0.9 and drop it. So the check is sharp:
    token 3 must be reachable through the prefiltered path, and both
    paths must emit the same support over 512 draws."""
    import numpy as np

    V, C = 256, 16
    head = np.log(np.array([0.3, 0.3, 0.28, 0.07]))
    tail = np.log(np.full(V - 4, 0.05 / (V - 4)))
    logits = jnp.asarray(np.concatenate([head, tail])[None, :], jnp.float32)
    temps = jnp.array([1.0], jnp.float32)
    top_ps = jnp.array([0.9], jnp.float32)

    keys = jax.random.split(jax.random.PRNGKey(11), 512)
    exact = np.asarray(jax.vmap(
        lambda k: sample_dynamic(logits, k, temps, top_ps))(keys))[:, 0]
    pre = np.asarray(jax.vmap(
        lambda k: sample_dynamic(logits, k, temps, top_ps, candidates=C)
    )(keys))[:, 0]
    assert set(exact.tolist()) == {0, 1, 2, 3}, sorted(set(exact.tolist()))
    assert set(pre.tolist()) == {0, 1, 2, 3}, sorted(set(pre.tolist()))


def test_truncated_dist_wide_candidates_still_truncates():
    """candidates >= vocab must take the exact full-vocab truncation, not
    silently skip the requested nucleus (review finding): the result must
    equal the candidates=0 exact path, and tokens outside the top-p keep
    set must carry zero mass."""
    import numpy as np

    from polykey_tpu.engine.sampling import truncated_dist

    logits = jax.random.normal(jax.random.PRNGKey(9), (3, 32)) * 3.0
    temp = jnp.array([1.0, 0.8, 1.2], jnp.float32)
    top_p = jnp.array([0.6, 0.9, 1.0], jnp.float32)

    tk = jnp.zeros((3,), jnp.int32)
    exact = truncated_dist(logits, temp, top_p, tk, 0)
    wide = truncated_dist(logits, temp, top_p, tk, 64)     # > vocab
    narrow = truncated_dist(logits, temp, top_p, tk, 32)   # == vocab
    assert np.allclose(np.asarray(exact), np.asarray(wide), atol=1e-6)
    assert np.allclose(np.asarray(exact), np.asarray(narrow), atol=1e-6)
    # Row 0 (p=0.6) must have strictly truncated support; row 2 (p=1.0)
    # must be the plain softmax.
    assert int((np.asarray(exact)[0] > 0).sum()) < 32
    sm = np.asarray(jax.nn.softmax(logits[2] / temp[2]))
    assert np.allclose(np.asarray(exact)[2], sm, atol=1e-6)


def test_shutdown_fails_inflight_requests():
    config = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=2, page_size=8, num_pages=32, max_seq_len=64,
        prefill_buckets=(16,), max_new_tokens_cap=64,
        default_max_new_tokens=32,
    )
    engine = InferenceEngine(config)
    request = GenRequest(prompt="long", max_new_tokens=64, temperature=1.0)
    engine.submit(request)
    request.out.get(timeout=30)  # first token: the request is in-flight
    engine.shutdown()
    # The in-flight request must receive a terminal event promptly, not
    # block until the request timeout.
    deadline = time.monotonic() + 5
    terminal = None
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=0.5)
        except queue.Empty:
            continue
        if kind in ("done", "error"):
            terminal = (kind, value)
            break
    assert terminal is not None
    assert terminal[0] == "error"


def test_oversize_max_tokens_clamped():
    config = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=1, page_size=8, num_pages=32, max_seq_len=32,
        prefill_buckets=(16,), max_new_tokens_cap=1000,  # cap > max_seq_len
        default_max_new_tokens=4,
    )
    engine = InferenceEngine(config)
    try:
        request = GenRequest(prompt="x" * 100, max_new_tokens=1000)
        engine.submit(request)
        tokens, done, error = [], None, None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            kind, value = request.out.get(timeout=60)
            if kind == "token":
                tokens.append(value)
            else:
                done, error = (value, None) if kind == "done" else (None, value)
                break
        assert error is None, error
        assert done is not None
        # Never exceeds the position cap implied by max_seq_len.
        assert done.prompt_tokens + done.completion_tokens <= config.max_seq_len
    finally:
        engine.shutdown()


def test_top_k_masks_support_dynamic_paths():
    """Per-row top_k: sampled tokens must come from the row's k largest
    logits on BOTH dynamic paths (exact sort and candidates prefilter),
    rows with k<=0 are unrestricted, and k=1 is exactly argmax."""
    import numpy as np

    logits = jax.random.normal(jax.random.PRNGKey(21), (4, 64)) * 3.0
    temps = jnp.array([1.0, 1.0, 1.0, 1.0], jnp.float32)
    top_ps = jnp.ones((4,), jnp.float32)
    top_ks = jnp.array([1, 3, 8, 0], jnp.int32)

    order = np.argsort(-np.asarray(logits), axis=-1)
    keys = jax.random.split(jax.random.PRNGKey(22), 256)
    for cand in (0, 32):
        out = np.asarray(jax.vmap(
            lambda k: sample_dynamic(
                logits, k, temps, top_ps, top_ks, candidates=cand)
        )(keys))
        assert (out[:, 0] == order[0, 0]).all()              # k=1 → argmax
        assert set(out[:, 1]) <= set(order[1, :3].tolist())
        assert set(out[:, 2]) <= set(order[2, :8].tolist())
        assert len(set(out[:, 3].tolist())) > 8              # unrestricted


def test_top_k_composes_with_top_p():
    """top_k ∧ top_p: the support is the INTERSECTION of both keep sets
    (here p=0.999 keeps nearly everything, k=2 must still bind)."""
    import numpy as np

    logits = jnp.asarray(
        np.log(np.array([[0.4, 0.3, 0.2, 0.05, 0.05]])), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(23), 256)
    out = np.asarray(jax.vmap(
        lambda k: sample_dynamic(
            logits, k, jnp.array([1.0]), jnp.array([0.999]),
            jnp.array([2], jnp.int32))
    )(keys))[:, 0]
    assert set(out.tolist()) <= {0, 1}
