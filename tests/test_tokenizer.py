"""ByteTokenizer tests, including streaming UTF-8 boundary handling."""

from polykey_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer


def test_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello, world")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "hello, world"


def test_unicode_roundtrip():
    tok = ByteTokenizer()
    text = "héllo → 世界 🌍"
    assert tok.decode(tok.encode(text)) == text


def test_specials_skipped_in_decode():
    tok = ByteTokenizer()
    ids = [tok.bos_id] + tok.encode("hi")[1:] + [tok.eos_id, tok.pad_id]
    assert tok.decode(ids) == "hi"


def test_incremental_decode_splits_multibyte():
    tok = ByteTokenizer()
    ids = tok.encode("a→b")[1:]  # strip bos; '→' is 3 bytes
    # Feed one token at a time; concatenation must equal the full string and
    # no chunk may contain a replacement character.
    state = b""
    out = []
    for i in ids:
        chunk, state = tok.decode_incremental([i], state)
        assert "�" not in chunk
        out.append(chunk)
    assert "".join(out) == "a→b"
    assert state == b""


def test_load_tokenizer_byte():
    tok = load_tokenizer("byte")
    assert isinstance(tok, ByteTokenizer)


def test_incremental_detokenizer_context_dependent():
    """The bounded-window detokenizer must reproduce full-prefix decoding
    for a context-DEPENDENT tokenizer: this fake mixes whole-word pieces
    with UTF-8 byte-fallback ids (sentencepiece-style), so a multi-byte
    character's text only exists once all its bytes arrived, and partial
    sequences must be held back (never streamed as U+FFFD)."""
    from polykey_tpu.engine.tokenizer import IncrementalDetokenizer

    euro = "€".encode("utf-8")  # 3 bytes -> ids 100, 101, 102

    class ByteFallbackTok:
        pieces = {0: b"he", 1: b"llo", 2: b" wor", 3: b"ld", 4: b" ",
                  100: euro[0:1], 101: euro[1:2], 102: euro[2:3]}

        def decode(self, ids):
            return b"".join(self.pieces[i] for i in ids).decode(
                "utf-8", errors="replace"
            )

    tok = ByteFallbackTok()
    ids = [0, 1, 4, 100, 101, 102, 2, 3]
    detok = IncrementalDetokenizer(tok)
    chunks = [detok.push(i) for i in ids]
    assert "�" not in "".join(chunks)
    # Bytes of '€' are held until the character completes.
    assert chunks[3] == "" and chunks[4] == "" and chunks[5] == "€"
    assert "".join(chunks) + detok.flush() == tok.decode(ids) == "hello € world"
    # Trailing incomplete sequence: held back by push, surfaced by flush.
    detok2 = IncrementalDetokenizer(tok)
    out = "".join(detok2.push(i) for i in [0, 100, 101])
    assert out == "he"
    # Python collapses the incomplete trailing sequence to one U+FFFD.
    assert detok2.flush() == "�"
