"""ByteTokenizer tests, including streaming UTF-8 boundary handling."""

from polykey_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer


def test_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("hello, world")
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "hello, world"


def test_unicode_roundtrip():
    tok = ByteTokenizer()
    text = "héllo → 世界 🌍"
    assert tok.decode(tok.encode(text)) == text


def test_specials_skipped_in_decode():
    tok = ByteTokenizer()
    ids = [tok.bos_id] + tok.encode("hi")[1:] + [tok.eos_id, tok.pad_id]
    assert tok.decode(ids) == "hi"


def test_incremental_decode_splits_multibyte():
    tok = ByteTokenizer()
    ids = tok.encode("a→b")[1:]  # strip bos; '→' is 3 bytes
    # Feed one token at a time; concatenation must equal the full string and
    # no chunk may contain a replacement character.
    state = b""
    out = []
    for i in ids:
        chunk, state = tok.decode_incremental([i], state)
        assert "�" not in chunk
        out.append(chunk)
    assert "".join(out) == "a→b"
    assert state == b""


def test_load_tokenizer_byte():
    tok = load_tokenizer("byte")
    assert isinstance(tok, ByteTokenizer)
