"""Safetensors import round-trips for all three served families (VERDICT r1
#4): synthesize an HF-layout checkpoint from a known param tree, import it
back through models/loader.import_safetensors, and require exact tree
equality plus forward equality — per family, including the Mixtral expert
stacking/router and the Gemma-2 four-norm convention.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polykey_tpu.models.config import get_config
from polykey_tpu.models.loader import _hf_layer_map, import_safetensors
from polykey_tpu.models.transformer import forward, init_params, unembed

safetensors_np = pytest.importorskip("safetensors.numpy")


def _export_hf(params: dict, cfg) -> dict:
    """Reverse of import_safetensors: our stacked [L(,E),in,out] tree → flat
    HF state dict with [out, in] linears."""
    tensors = {}

    def emit(name, arr, transpose):
        arr = np.asarray(arr, dtype=np.float32)
        # safetensors serializes the raw buffer: a .T view would silently
        # write untransposed data under transposed shape metadata.
        tensors[name] = np.ascontiguousarray(arr.T) if transpose else arr

    for key_path, (pattern, transpose) in _hf_layer_map(cfg).items():
        node = params["layers"]
        for k in key_path:
            node = node[k]
        for i in range(cfg.num_layers):
            if "{e}" in pattern:
                for e in range(cfg.num_experts):
                    emit(pattern.format(i=i, e=e), node[i, e], transpose)
            else:
                emit(pattern.format(i=i), node[i], transpose)
    emit("model.embed_tokens.weight", params["embed"], False)
    emit("model.norm.weight", params["final_norm"], False)
    if not cfg.tie_embeddings:
        emit("lm_head.weight", params["lm_head"], True)
    return tensors


def _roundtrip(model_name: str, tmp_path):
    cfg = get_config(model_name)
    params = init_params(jax.random.PRNGKey(3), cfg, jnp.float32)
    ckpt_dir = os.path.join(tmp_path, model_name)
    os.makedirs(ckpt_dir)
    safetensors_np.save_file(
        _export_hf(params, cfg),
        os.path.join(ckpt_dir, "model.safetensors"),
    )

    imported = import_safetensors(ckpt_dir, cfg, dtype=jnp.float32)

    flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(imported)[0]
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (path, a), (_, b) in zip(flat_a, flat_b):
        assert a.shape == b.shape, path
        np.testing.assert_allclose(a, b, rtol=1e-6, err_msg=str(path))

    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0,
                                cfg.vocab_size, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (2, 8))
    h_a, _ = forward(params, cfg, tokens, positions, None)
    h_b, _ = forward(imported, cfg, tokens, positions, None)
    np.testing.assert_allclose(
        unembed(params, cfg, h_a[:, -1]),
        unembed(imported, cfg, h_b[:, -1]),
        rtol=1e-5, atol=1e-5,
    )


def test_llama_roundtrip(tmp_path):
    _roundtrip("tiny-llama", tmp_path)


def test_mixtral_roundtrip(tmp_path):
    _roundtrip("tiny-mixtral", tmp_path)


def test_gemma_roundtrip(tmp_path):
    _roundtrip("tiny-gemma", tmp_path)


def test_sharded_files_with_index(tmp_path):
    # HF checkpoints ship sharded with model.safetensors.index.json; the
    # importer must follow the weight_map.
    import json

    cfg = get_config("tiny-llama")
    params = init_params(jax.random.PRNGKey(5), cfg, jnp.float32)
    tensors = _export_hf(params, cfg)
    names = sorted(tensors)
    half = len(names) // 2
    ckpt_dir = os.path.join(tmp_path, "sharded")
    os.makedirs(ckpt_dir)
    shards = {
        "model-00001-of-00002.safetensors": names[:half],
        "model-00002-of-00002.safetensors": names[half:],
    }
    weight_map = {}
    for fname, keys in shards.items():
        safetensors_np.save_file(
            {k: tensors[k] for k in keys}, os.path.join(ckpt_dir, fname)
        )
        weight_map.update({k: fname for k in keys})
    with open(os.path.join(ckpt_dir, "model.safetensors.index.json"), "w") as f:
        json.dump({"weight_map": weight_map}, f)

    imported = import_safetensors(ckpt_dir, cfg, dtype=jnp.float32)
    np.testing.assert_allclose(imported["embed"], params["embed"], rtol=1e-6)
    np.testing.assert_allclose(
        imported["layers"]["attn"]["wq"], params["layers"]["attn"]["wq"],
        rtol=1e-6,
    )
