"""Speculative decoding in the serving path (VERDICT r1 #3).

The acceptance bar: a spec-decode engine (draft model mounted, paged
draft/verify rounds — engine/spec_decode.py) emits EXACTLY the same greedy
stream as the plain engine, and the full gRPC streaming path works with a
tiny draft+target pair.
"""

import dataclasses
import io
import queue
import time

import grpc
import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.gateway import server as gateway_server
from polykey_tpu.gateway.jsonlog import Logger
from polykey_tpu.gateway.tpu_service import TpuService
from polykey_tpu.proto import polykey_v2_pb2 as pk
from polykey_tpu.proto.polykey_v2_grpc import PolykeyServiceStub

BASE_CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    num_pages=64,
    max_seq_len=64,
    prefill_buckets=(16, 32),
    max_new_tokens_cap=32,
    default_max_new_tokens=8,
)
# Draft = same architecture at a different seed (engine inits the draft from
# seed+2): a *wrong* draft model, which is exactly the point — greedy output
# must still be the target's chain no matter how bad the drafts are.
SPEC_CONFIG = dataclasses.replace(BASE_CONFIG, draft_model="tiny-llama",
                                  spec_gamma=3)

PROMPTS = ["hello spec", "draft and verify", "q", "the quick brown fox"]


def _collect(request: GenRequest, timeout=60.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _run_prompts(config, temperature=0.0, top_p=1.0, max_new=8):
    eng = InferenceEngine(config)
    try:
        reqs = [
            GenRequest(prompt=p, max_new_tokens=max_new,
                       temperature=temperature, top_p=top_p)
            for p in PROMPTS
        ]
        for r in reqs:
            eng.submit(r)
        outs = []
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None, error
            assert done is not None
            outs.append(tokens)
        return outs, eng.metrics.snapshot()
    finally:
        eng.shutdown()


def test_spec_greedy_matches_plain_engine():
    plain, _ = _run_prompts(BASE_CONFIG)
    spec, snap = _run_prompts(SPEC_CONFIG)
    assert spec == plain
    # The rounds really were speculative: proposals were counted, and the
    # batch advanced in multi-token rounds (fewer steps than tokens).
    assert snap["drafts_proposed"] > 0
    assert snap["decode_steps"] < snap["tokens_generated"]


def test_spec_good_draft_accepts():
    # Draft == target weights (same seed: draft inits from seed+2, so seed
    # target at seed+2 ≡ draft) would be ideal; approximate with the real
    # guarantee instead: acceptance is in [0, 1] and counted consistently.
    _, snap = _run_prompts(SPEC_CONFIG)
    assert 0.0 <= snap["spec_acceptance"] <= 1.0
    assert snap["drafts_accepted"] <= snap["drafts_proposed"]


def test_spec_sampled_completes():
    outs, snap = _run_prompts(SPEC_CONFIG, temperature=0.8)
    assert all(len(t) >= 1 for t in outs)
    assert snap["requests_failed"] == 0
    assert snap["drafts_proposed"] > 0


def test_spec_top_p_falls_back_to_plain():
    # Without the top-k prefilter (top_p_candidates=0) top_p<1 rows take
    # the plain step (full-vocab truncation inside the spec round would
    # need per-step sorts); the request still completes. Matching the
    # plain engine's sampled path seed-for-seed is not guaranteed, so
    # assert completion only.
    outs, snap = _run_prompts(SPEC_CONFIG, temperature=0.8, top_p=0.9)
    assert all(len(t) >= 1 for t in outs)
    assert snap["requests_failed"] == 0
    # Every decode step had a top_p<1 batch → zero speculative rounds.
    assert "drafts_proposed" not in snap


def test_spec_top_p_speculates_with_prefilter():
    """With top_p_candidates set, top_p<1 batches stay on the speculative
    path (truncated rejection sampling, sampling.truncated_dist) —
    the batch-wide plain-step fallback and its acceptance collapse are
    gone. Mixed greedy + sampled batches round through spec too."""
    cfg = dataclasses.replace(SPEC_CONFIG, top_p_candidates=32)
    outs, snap = _run_prompts(cfg, temperature=0.8, top_p=0.9)
    assert all(len(t) >= 1 for t in outs)
    assert snap["requests_failed"] == 0
    assert snap.get("drafts_proposed", 0) > 0

    # Mixed batch: one greedy + sampled rows concurrently.
    eng = InferenceEngine(cfg)
    try:
        reqs = [
            GenRequest(prompt="greedy row", max_new_tokens=6),
            GenRequest(prompt="sampled row", max_new_tokens=6,
                       temperature=0.9, top_p=0.8),
        ]
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None and done is not None and tokens
        assert eng.metrics.snapshot().get("drafts_proposed", 0) > 0
    finally:
        eng.shutdown()


def test_spec_top_p_truncated_acceptance_is_exact():
    """Sharp identity check: with draft == target, the truncated
    acceptance ratio p'/q' is exactly 1 for every draft, so a top_p<1
    sampled stream must accept ALL drafts (acceptance 1.0) — any
    asymmetry between the draft-side and verify-side truncation would
    show up as rejections."""
    import jax
    import jax.numpy as jnp

    from polykey_tpu.models.config import get_config
    from polykey_tpu.models.transformer import init_params

    cfg = dataclasses.replace(
        SPEC_CONFIG, top_p_candidates=32, max_decode_slots=2
    )
    params = init_params(
        jax.random.PRNGKey(5), get_config("tiny-llama"), jnp.float32
    )
    eng = InferenceEngine(cfg, params=params, draft_params=params)
    try:
        reqs = [GenRequest(prompt=f"identical {i}", max_new_tokens=12,
                           temperature=1.0, top_p=0.7) for i in range(2)]
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None and done is not None
        snap = eng.metrics.snapshot()
        assert snap["drafts_proposed"] > 0
        assert snap["spec_acceptance"] == 1.0, snap
    finally:
        eng.shutdown()


def test_spec_long_generation_budget_cap():
    # Budget/EOS truncation mid-window: max_new not a multiple of gamma+1
    # forces the final round to truncate on host.
    eng = InferenceEngine(SPEC_CONFIG)
    try:
        r = GenRequest(prompt="truncate me", max_new_tokens=10)
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None
        assert done is not None
        assert len(tokens) <= 10
    finally:
        eng.shutdown()


def test_spec_grpc_streaming_e2e():
    logger = Logger(stream=io.StringIO())
    eng = InferenceEngine(SPEC_CONFIG)
    try:
        service = TpuService(eng)
        server, health, port = gateway_server.build_server(
            service, logger, address="127.0.0.1:0"
        )
        server.start()
        try:
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            stub = PolykeyServiceStub(channel)
            request = pk.ExecuteToolRequest(tool_name="llm_generate")
            request.parameters.fields["prompt"].string_value = "stream spec"
            request.parameters.fields["max_new_tokens"].number_value = 8
            chunks = list(stub.ExecuteToolStream(request, timeout=120))
            assert chunks, "no stream chunks"
            final = chunks[-1]
            assert final.status.code == 200
            channel.close()
        finally:
            server.stop(grace=None)
    finally:
        eng.shutdown()


def test_spec_compile_warmup_matches_cold():
    """Spec engines now take compile warmup (spec prefill groups + the
    spec round); warmed output must equal the cold engine's bit-for-bit
    and the merge/prefill caches must cover the first admission."""
    cold, _ = _run_prompts(SPEC_CONFIG)
    warm_cfg = dataclasses.replace(SPEC_CONFIG, compile_warmup=True)
    eng = InferenceEngine(warm_cfg)
    try:
        n_prefill = eng._jit_spec_prefill._cache_size()
        n_merge = eng._jit_merge._cache_size()
        n_round = eng._jit_spec_decode._cache_size()
        reqs = [GenRequest(prompt=p, max_new_tokens=8) for p in PROMPTS]
        for r in reqs:
            eng.submit(r)
        outs = []
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None and done is not None
            outs.append(tokens)
        assert outs == cold
        # No new greedy compiles after warmup.
        assert eng._jit_spec_prefill._cache_size() == n_prefill
        assert eng._jit_merge._cache_size() == n_merge
        # The spec ROUND is the heavy compile - it must be warmed too.
        assert eng._jit_spec_decode._cache_size() == n_round
    finally:
        eng.shutdown()


def test_spec_compile_warmup_covers_top_p_candidates():
    """With top_p_candidates>0, the spec round dispatches with BOTH
    candidates=0 (all-greedy batches) and candidates=top_p_candidates
    (any truncated-top-p row) — warmup must pre-compile both variants so
    the first sampled batch never stalls on a serving-time compile."""
    # Unique shape key (slots/buckets used by no other test): jit caches
    # are shared across engine instances, so shared shapes could be
    # pre-populated by earlier tests and mask a warmup regression.
    cfg = dataclasses.replace(
        SPEC_CONFIG, top_p_candidates=32, compile_warmup=True,
        max_decode_slots=7, prefill_buckets=(48,),
    )
    eng = InferenceEngine(cfg)
    try:
        n_round = eng._jit_spec_decode._cache_size()
        n_prefill = eng._jit_spec_prefill._cache_size()
        r = GenRequest(
            prompt="warm top-p probe", max_new_tokens=8,
            temperature=0.9, top_p=0.8, seed=7,
        )
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None and done is not None and tokens
        assert eng._jit_spec_decode._cache_size() == n_round
        assert eng._jit_spec_prefill._cache_size() == n_prefill
    finally:
        eng.shutdown()


def test_spec_compile_warmup_covers_plain_fallback():
    """With top_p_candidates=0 a sampled top_p<1 batch leaves the spec
    path and takes the PLAIN decode block — warmup must pre-compile that
    fallback variant too (greedy=False, candidates=0)."""
    cfg = dataclasses.replace(
        SPEC_CONFIG, compile_warmup=True,
        # Unique shape key — see test_spec_compile_warmup_covers_top_p_candidates.
        max_decode_slots=9, prefill_buckets=(56,),
    )
    assert cfg.top_p_candidates == 0
    eng = InferenceEngine(cfg)
    try:
        n_plain = eng._jit_decode._cache_size()
        r = GenRequest(
            prompt="plain fallback probe", max_new_tokens=8,
            temperature=0.9, top_p=0.8, seed=3,
        )
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None and done is not None and tokens
        assert eng._jit_decode._cache_size() == n_plain
    finally:
        eng.shutdown()


def test_adaptive_gamma_drops_on_bad_draft():
    """The per-lane gamma dial (ISSUE 19, superseding the VERDICT r2 #8
    engine-global ladder): a draft that keeps getting rejected drags the
    lane's acceptance EWMA under the low-water mark and clamps that
    lane's dial — the dispatch width follows the widest ACTIVE lane down
    to the low rung mid-stream, and a drained engine resets optimistic
    (fresh lanes boot at gamma_max). Greedy output stays the target's
    chain regardless (the core spec guarantee)."""
    plain, _ = _run_prompts(BASE_CONFIG, max_new=24)
    cfg = dataclasses.replace(SPEC_CONFIG, spec_gamma=4)
    eng = InferenceEngine(cfg)
    try:
        assert eng._gamma == 4 and eng._gamma_low == 2
        reqs = [GenRequest(prompt=p, max_new_tokens=24) for p in PROMPTS]
        for r in reqs:
            eng.submit(r)
        # Poll the dispatch width and the per-lane stats while tokens
        # stream: with a terrible draft (~zero acceptance) each lane's
        # EWMA falls under GAMMA_ACCEPT_FLOOR within a handful of
        # rounds, so the dial drop MUST be observable mid-flight.
        width_dropped = lane_dropped = False
        outs = []
        for r in reqs:
            tokens = []
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                kind, value = r.out.get(timeout=60.0)
                if kind == "token":
                    tokens.append(value)
                    if eng._gamma == eng._gamma_low:
                        width_dropped = True
                    if eng.stats().get("spec_gamma_min") == eng._gamma_low:
                        lane_dropped = True
                elif kind == "done":
                    break
                else:
                    raise AssertionError(f"request error: {value}")
            outs.append(tokens)
        assert width_dropped, "dispatch width never followed lanes down"
        assert lane_dropped, "no lane dial reached the low rung"
        # Aggregate EWMA (observability mirror of the per-lane blend)
        # agrees the draft is bad.
        assert eng._accept_ewma < 0.35
        assert outs == plain
        # Drained: per-lane state resets optimistic, so the next
        # admission dispatches at full width again. ("done" lands before
        # the round's width recompute — give the loop a beat.)
        deadline = time.monotonic() + 10.0
        while eng._gamma != eng._gamma_max and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng._gamma == eng._gamma_max == 4
    finally:
        eng.shutdown()


def test_adaptive_gamma_stays_high_with_perfect_draft():
    """draft == target ⇒ acceptance 1.0 ⇒ the dial never leaves the full
    gamma."""
    import jax
    import jax.numpy as jnp

    from polykey_tpu.models.config import get_config
    from polykey_tpu.models.transformer import init_params

    params = init_params(
        jax.random.PRNGKey(5), get_config("tiny-llama"), jnp.float32
    )
    cfg = dataclasses.replace(SPEC_CONFIG, spec_gamma=4)
    eng = InferenceEngine(cfg, params=params, draft_params=params)
    try:
        reqs = [GenRequest(prompt=p, max_new_tokens=12) for p in PROMPTS]
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None and done is not None
        assert eng._gamma == 4
        assert eng.metrics.snapshot()["spec_acceptance"] == 1.0
    finally:
        eng.shutdown()


def test_adaptive_gamma_off_pins_full_gamma():
    """POLYKEY_ADAPTIVE_GAMMA=0 semantics: the ladder collapses to the
    configured gamma and the dial never moves."""
    cfg = dataclasses.replace(
        SPEC_CONFIG, spec_gamma=4, adaptive_gamma=False
    )
    eng = InferenceEngine(cfg)
    try:
        assert eng._gamma_low == eng._gamma_max == 4
        reqs = [GenRequest(prompt=p, max_new_tokens=8) for p in PROMPTS]
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None and done is not None
        assert eng._gamma == 4
    finally:
        eng.shutdown()


def test_spec_heterogeneous_draft_architecture(monkeypatch):
    """A REAL draft is a smaller model of the same family (config 5:
    gemma-2-2b drafting for 9b) — different depth/heads/widths, same
    vocab. The engine's draft pool must size itself from the DRAFT
    config, and greedy output must still equal the plain engine's."""
    from polykey_tpu.models.config import MODEL_REGISTRY, TINY_GEMMA

    monkeypatch.setitem(
        MODEL_REGISTRY, "tiny-gemma-draft",
        dataclasses.replace(
            TINY_GEMMA, name="tiny-gemma-draft",
            num_layers=1, num_heads=2, num_kv_heads=1,
            hidden_size=32, intermediate_size=64,
            query_pre_attn_scalar=16.0,
        ),
    )
    base = dataclasses.replace(BASE_CONFIG, model="tiny-gemma")
    plain, _ = _run_prompts(base)
    spec_cfg = dataclasses.replace(
        base, draft_model="tiny-gemma-draft", spec_gamma=3
    )
    spec, snap = _run_prompts(spec_cfg)
    assert spec == plain
    assert snap["drafts_proposed"] > 0


def test_spec_quantized_engine_greedy_matches_quantized_plain():
    """int8 weight-only target + int8 draft (the phase-C2 serving shape):
    the quantized spec engine's greedy stream must equal the quantized
    PLAIN engine's — quantization changes the logits, so the reference
    is the quantized plain engine, not the fp32 one."""
    plain_q, _ = _run_prompts(
        dataclasses.replace(BASE_CONFIG, quantize=True)
    )
    spec_q, snap = _run_prompts(
        dataclasses.replace(SPEC_CONFIG, quantize=True)
    )
    assert spec_q == plain_q
    assert snap["drafts_proposed"] > 0


def test_spec_top_k_one_is_greedy_end_to_end():
    """top_k=1 on the SPECULATIVE truncated path (top_p_candidates>0):
    draft and verify dists both collapse to the argmax, so the stream
    must equal the plain engine's greedy stream — a sharp check that the
    rank mask is applied identically on both sides of the rejection
    sampler."""
    plain, _ = _run_prompts(BASE_CONFIG)
    cfg = dataclasses.replace(SPEC_CONFIG, top_p_candidates=32)
    eng = InferenceEngine(cfg)
    try:
        outs = []
        for p in PROMPTS:
            r = GenRequest(prompt=p, max_new_tokens=8,
                           temperature=1.0, top_k=1, seed=5)
            eng.submit(r)
            tokens, done, error = _collect(r)
            assert error is None and done is not None
            outs.append(tokens)
        snap = eng.metrics.snapshot()
        assert snap.get("drafts_proposed", 0) > 0   # really speculative
        assert outs == plain
    finally:
        eng.shutdown()


# -- spec × ragged unification (ISSUE 19) -------------------------------------
#
# The acceptance bar: gamma-token verify windows ride the flat token
# stream as ordinary per-sequence ranges, so ONE mixed dispatch serves
# prefill chunks, decode lanes, and spec verify lanes — and the greedy
# stream stays bit-identical to the bucketed spec path AND the plain
# engine at both lookahead depths, with chunked prompts in the mix.

SPEC_RAGGED_CONFIG = dataclasses.replace(SPEC_CONFIG, ragged_dispatch=True)
# Chunked prompt: 48 bytes > max bucket 32, so admission spans several
# ragged/bucketed prefill dispatches while other lanes decode.
MIXED_PROMPTS = ["hi", "abcdefgh" * 6, "draft and verify", "q"]


def _serve_specs(config, depth=None, monkeypatch=None, max_new=8):
    if depth is not None:
        monkeypatch.setenv("POLYKEY_DISPATCH_LOOKAHEAD", str(depth))
    eng = InferenceEngine(config)
    try:
        reqs = [
            GenRequest(prompt=p, max_new_tokens=max_new, seed=11)
            for p in MIXED_PROMPTS
        ]
        for r in reqs:
            eng.submit(r)
        outs = []
        for r in reqs:
            tokens, done, error = _collect(r)
            assert error is None, error
            assert done is not None
            outs.append(tokens)
        stats = eng.stats()
    finally:
        eng.shutdown()
    return outs, stats


@pytest.mark.parametrize("depth", [1, 2])
def test_spec_ragged_greedy_bit_identical(depth, monkeypatch):
    """THE unification acceptance criterion: greedy streams are
    bit-identical across plain / spec-on-bucketed / spec-on-ragged at
    lookahead depths 1 and 2, with a chunked prompt in the batch."""
    plain, _ = _serve_specs(BASE_CONFIG, depth, monkeypatch)
    bucketed, bsnap = _serve_specs(SPEC_CONFIG, depth, monkeypatch)
    ragged, rsnap = _serve_specs(SPEC_RAGGED_CONFIG, depth, monkeypatch)
    assert bucketed == plain
    assert ragged == plain
    assert rsnap["ragged"] is True
    # Both spec paths really speculated.
    assert bsnap["drafts_proposed"] > 0
    assert rsnap["drafts_proposed"] > 0


def test_spec_ragged_kill_switch(monkeypatch):
    """POLYKEY_DISABLE_RAGGED on a spec+ragged config: the engine falls
    back to the bucketed SPEC path (speculation survives, the flat
    stream doesn't)."""
    monkeypatch.setenv("POLYKEY_DISABLE_RAGGED", "1")
    eng = InferenceEngine(SPEC_RAGGED_CONFIG)
    try:
        assert eng._ragged is False
        assert eng._spec is True
        r = GenRequest(prompt="still speculates", max_new_tokens=6)
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None and done is not None and len(tokens) == 6
        assert eng.metrics.snapshot().get("drafts_proposed", 0) > 0
    finally:
        eng.shutdown()


def test_spec_ragged_mid_stream_supervisor_restart():
    """Mid-stream supervisor restart on the unified path: an injected
    step stall wedges the spec×ragged engine, the watchdog trips, the
    supervisor swaps in a fresh engine — and the restarted engine's
    greedy stream is STILL bit-identical to the plain engine's (restart
    must not perturb determinism: seeds key on fold_in(seed, position),
    not on engine lifetime)."""
    from polykey_tpu import faults
    from polykey_tpu.engine.supervisor import EngineSupervisor
    from polykey_tpu.engine.watchdog import Watchdog
    from polykey_tpu.gateway.health import SERVING, HealthService

    plain, _ = _serve_specs(BASE_CONFIG)
    cfg = dataclasses.replace(
        SPEC_RAGGED_CONFIG, watchdog_timeout_s=0.25, supervise=False
    )
    faults.clear()
    faults.install("step-stall=1.0@1")
    engine = InferenceEngine(cfg)
    health = HealthService()
    health.set_serving_status("", SERVING)
    watchdog = Watchdog(engine, health=health, check_interval_s=0.05)
    watchdog.start()
    supervisor = EngineSupervisor(
        engine, lambda: InferenceEngine(cfg),
        watchdog=watchdog, health=health,
        max_restarts=2, restart_window_s=60.0,
        check_interval_s=0.05, join_timeout_s=5.0,
    ).start()
    try:
        victim = GenRequest(prompt=MIXED_PROMPTS[1], max_new_tokens=8,
                            seed=11)
        engine.submit(victim)
        _, done_v, error_v = _collect(victim, timeout=15.0)
        assert done_v is None and error_v is not None   # failed cleanly
        deadline = time.monotonic() + 10.0
        while supervisor.restarts < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert supervisor.restarts == 1
        outs = []
        for p in MIXED_PROMPTS:
            r = GenRequest(prompt=p, max_new_tokens=8, seed=11)
            supervisor.engine.submit(r)
            tokens, done, error = _collect(r, timeout=60.0)
            assert error is None and done is not None
            outs.append(tokens)
        assert outs == plain
        assert supervisor.engine.stats()["ragged"] is True
        assert supervisor.engine.metrics.snapshot()["drafts_proposed"] > 0
    finally:
        faults.clear()
        supervisor.stop()
        watchdog.stop()
        supervisor.engine.shutdown()
