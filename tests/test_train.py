"""Training-step tests: loss sanity, improvement, sharded execution."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from polykey_tpu.models.config import TINY_LLAMA
from polykey_tpu.models.transformer import init_params
from polykey_tpu.parallel.mesh import MeshConfig, create_mesh
from polykey_tpu.train import cross_entropy_loss, make_train_step

CFG = dataclasses.replace(
    TINY_LLAMA, hidden_size=128, intermediate_size=256, num_heads=8,
    num_kv_heads=4, head_dim=16,
)


def _toy_batch(key, B=4, T=16):
    tokens = jax.random.randint(key, (B, T), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1).at[:, -1].set(-1)  # mask last
    positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
    return tokens, targets, positions


def test_loss_is_near_uniform_at_init():
    params = init_params(jax.random.PRNGKey(0), CFG, jnp.float32)
    tokens, targets, positions = _toy_batch(jax.random.PRNGKey(1))
    loss = float(cross_entropy_loss(params, CFG, tokens, targets, positions))
    # Random init ≈ uniform over vocab.
    assert abs(loss - np.log(CFG.vocab_size)) < 1.0


def test_masked_positions_do_not_contribute():
    params = init_params(jax.random.PRNGKey(0), CFG, jnp.float32)
    tokens, targets, positions = _toy_batch(jax.random.PRNGKey(1))
    all_masked = jnp.full_like(targets, -1)
    loss = float(cross_entropy_loss(params, CFG, tokens, all_masked, positions))
    assert loss == 0.0


def test_train_step_reduces_loss_on_fixed_batch():
    mesh = create_mesh(MeshConfig(dp=2, tp=2), jax.devices()[:4])
    init_state, train_step, shard_batch = make_train_step(CFG, mesh)
    state = init_state(init_params(jax.random.PRNGKey(0), CFG, jnp.float32))
    batch = shard_batch(*_toy_batch(jax.random.PRNGKey(1)))

    losses = []
    for _ in range(8):
        state, loss = train_step(state, *batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizing a fixed batch must improve
    assert int(state.step) == 8


def test_ring_attention_loss_matches_unsharded():
    """sp>1 routes attention through the ring path (ops/ring_attention.py);
    the loss must match the single-device reference computation."""
    params = init_params(jax.random.PRNGKey(0), CFG, jnp.float32)
    tokens, targets, positions = _toy_batch(jax.random.PRNGKey(1), B=4, T=32)

    ref = float(cross_entropy_loss(params, CFG, tokens, targets, positions))

    mesh = create_mesh(MeshConfig(dp=2, sp=4), jax.devices()[:8])
    ring = float(
        cross_entropy_loss(
            params, CFG, tokens, targets, positions, sp_mesh=mesh
        )
    )
    assert abs(ref - ring) < 1e-4, (ref, ring)


def test_ulysses_attention_loss_matches_unsharded():
    """sp_impl='ulysses' re-shards heads via all-to-all
    (ops/ulysses_attention.py); loss must match the reference too."""
    params = init_params(jax.random.PRNGKey(0), CFG, jnp.float32)
    tokens, targets, positions = _toy_batch(jax.random.PRNGKey(1), B=4, T=32)

    ref = float(cross_entropy_loss(params, CFG, tokens, targets, positions))

    mesh = create_mesh(MeshConfig(dp=2, sp=4), jax.devices()[:8])
    uly = float(
        cross_entropy_loss(
            params, CFG, tokens, targets, positions, sp_mesh=mesh,
            sp_impl="ulysses",
        )
    )
    assert abs(ref - uly) < 1e-4, (ref, uly)


def test_train_step_improves_under_sp_ring():
    mesh = create_mesh(MeshConfig(dp=2, sp=2, tp=2), jax.devices()[:8])
    init_state, train_step, shard_batch = make_train_step(CFG, mesh)
    state = init_state(init_params(jax.random.PRNGKey(0), CFG, jnp.float32))
    batch = shard_batch(*_toy_batch(jax.random.PRNGKey(1), B=4, T=32))

    losses = []
    for _ in range(6):
        state, loss = train_step(state, *batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
