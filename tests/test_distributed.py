"""Hybrid DCN-mesh + bootstrap tests (simulated slices on the CPU mesh)."""


import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from polykey_tpu.parallel.distributed import (
    create_hybrid_mesh,
    initialize_from_env,
    mesh_from_env,
)
from polykey_tpu.parallel.mesh import MeshConfig


def test_hybrid_mesh_folds_slices_into_dp():
    """2 simulated slices × (dp=2, tp=2) → one mesh with dp=4, tp=2; the
    slice dimension is outermost in dp so only grad-reduce crosses 'DCN'."""
    mesh = create_hybrid_mesh(MeshConfig(dp=2, tp=2), num_slices=2,
                              devices=jax.devices()[:8])
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "dp": 4, "pp": 1, "sp": 1, "ep": 1, "tp": 2,
    }
    # Verify a dp-sharded computation runs and reduces across all 8 devices.
    x = jnp.arange(8.0).reshape(4, 2)
    x = jax.device_put(x, NamedSharding(mesh, P("dp", "tp")))
    total = jax.jit(lambda x: jnp.sum(x))(x)
    assert float(total) == sum(range(8))


def test_hybrid_mesh_single_slice_is_plain_mesh():
    mesh = create_hybrid_mesh(MeshConfig(dp=2, tp=2), num_slices=1,
                              devices=jax.devices()[:4])
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2


def test_hybrid_mesh_device_count_validation():
    with pytest.raises(ValueError, match="hybrid mesh needs"):
        create_hybrid_mesh(MeshConfig(dp=2), num_slices=3,
                           devices=jax.devices()[:4])


def test_mesh_from_env(monkeypatch):
    monkeypatch.setenv("POLYKEY_TP", "2")
    monkeypatch.setenv("POLYKEY_NUM_SLICES", "2")
    monkeypatch.delenv("POLYKEY_DP", raising=False)
    mesh = mesh_from_env(jax.devices()[:8])
    # dp absorbs the remainder: 8 / (tp=2 × slices=2) = 2 per slice → dp=4.
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_initialize_from_env_is_noop_without_config(monkeypatch):
    monkeypatch.delenv("POLYKEY_COORDINATOR", raising=False)
    monkeypatch.delenv("POLYKEY_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("POLYKEY_PROCESS_ID", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert initialize_from_env() is False


def test_initialize_from_env_partial_config_raises(monkeypatch):
    """ANY of the three knobs set = explicit config; half-set, empty, or
    non-integer values must raise the named error, not fall through to
    the auto branch or die inside jax.distributed (ADVICE r4)."""
    for env in (
        {"POLYKEY_PROCESS_ID": "0"},                    # lone rank
        {"POLYKEY_COORDINATOR": "localhost:9999"},      # lone coordinator
        {"POLYKEY_COORDINATOR": "localhost:9999",       # empty rank
         "POLYKEY_NUM_PROCESSES": "2", "POLYKEY_PROCESS_ID": ""},
        {"POLYKEY_COORDINATOR": "localhost:9999",       # non-integer count
         "POLYKEY_NUM_PROCESSES": "two", "POLYKEY_PROCESS_ID": "0"},
    ):
        for k in ("POLYKEY_COORDINATOR", "POLYKEY_NUM_PROCESSES",
                  "POLYKEY_PROCESS_ID"):
            monkeypatch.delenv(k, raising=False)
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        with pytest.raises(ValueError, match="partial distributed config"):
            initialize_from_env()


def test_hybrid_mesh_train_step_matches_flat_mesh():
    """A FULL train step executes on the 2-slice hybrid mesh (not just an
    axis-shape check) and produces the same loss as the flat dp×tp mesh —
    the slice layout changes device placement, never the math."""
    import jax.numpy as jnp

    from polykey_tpu.models.config import TINY_LLAMA
    from polykey_tpu.models.transformer import init_params
    from polykey_tpu.parallel.mesh import create_mesh
    from polykey_tpu.train import make_train_step

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 simulated devices")

    cfg = TINY_LLAMA
    B, T = 4, 16
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (B, T), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    losses = {}
    for name, mesh in (
        ("flat", create_mesh(MeshConfig(dp=4, tp=2), jax.devices()[:8])),
        ("hybrid", create_hybrid_mesh(
            MeshConfig(dp=2, tp=2), num_slices=2,
            devices=jax.devices()[:8])),
    ):
        init_state, train_step, shard_batch = make_train_step(cfg, mesh)
        state = init_state(
            init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        )
        t, tg, p = shard_batch(tokens, targets, positions)
        state, loss = train_step(state, t, tg, p)
        losses[name] = float(jax.block_until_ready(loss))
    assert losses["hybrid"] == pytest.approx(losses["flat"], rel=1e-6)
