"""MockService behavior parity with the reference mock
(/root/reference/internal/service/mock.go:22-66)."""

import re

from polykey_tpu.gateway.mock_service import MockService


def _call(tool_name):
    return MockService().execute_tool(tool_name, None, None, None)


def test_status_always_200():
    for tool in ("example_tool", "struct_tool", "file_tool", "nope"):
        resp = _call(tool)
        assert resp.status.code == 200
        assert resp.status.message == "Tool executed successfully"


def test_example_tool_string_output():
    resp = _call("example_tool")
    assert resp.WhichOneof("output") == "string_output"
    # "Mock execution of example_tool at <RFC3339>" (mock.go:34)
    assert re.fullmatch(
        r"Mock execution of example_tool at "
        r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}(Z|[+-]\d{2}:\d{2})",
        resp.string_output,
    )


def test_struct_tool_output():
    resp = _call("struct_tool")
    assert resp.WhichOneof("output") == "struct_output"
    out = dict(resp.struct_output)
    assert out["result"] == "success"
    assert isinstance(out["timestamp"], float)  # struct numbers are doubles
    data = dict(out["data"])
    assert data["processed"] is True
    assert data["count"] == 42


def test_file_tool_output():
    resp = _call("file_tool")
    assert resp.WhichOneof("output") == "file_output"
    f = resp.file_output
    assert f.file_name == "example.txt"
    assert f.mime_type == "text/plain"
    assert f.content == b"This is mock file content"


def test_unknown_tool_is_success_not_error():
    # mock.go:60-63: unknown tools return 200 with a string, NOT an error.
    resp = _call("does_not_exist")
    assert resp.status.code == 200
    assert resp.string_output == "Unknown tool: does_not_exist"


def test_stream_reassembles_to_unary_text():
    chunks = list(MockService().execute_tool_stream("other_tool", None, None, None))
    assert chunks[-1].final
    assert chunks[-1].status.code == 200
    text = "".join(c.delta for c in chunks)
    assert text == "Unknown tool: other_tool"
