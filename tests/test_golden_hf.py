"""Golden-model parity vs an independent implementation (HF transformers).

Every other model test checks internal consistency (kernel vs jnp oracle,
mesh vs single device); this one pins the math to the ecosystem reference:
tiny random-init REAL-architecture HF models (LlamaForCausalLM,
MixtralForCausalLM, Gemma2ForCausalLM on torch CPU) are exported to
safetensors, imported through models/loader.py:import_safetensors, and the
logits of models/transformer.py:forward must match HF's forward within
fp32 tolerance — including Llama GQA/RoPE, Mixtral top-2 routing, and
Gemma-2's post-norms, logit soft-caps, query_pre_attn_scalar, scaled
embeddings, and even/odd sliding-window interleaving. A drift in any of
those would pass the internal tests and fail here.

Also covers the serving path (forward with a KV cache: batched prefill +
per-token decode equals HF's full-sequence logits) and an HFTokenizer +
IncrementalDetokenizer round-trip on a real locally-built BPE tokenizer
(tokenizers lib), per VERDICT r2 missing #3.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from polykey_tpu.models.config import (  # noqa: E402
    TINY_GEMMA,
    TINY_LLAMA,
    TINY_MIXTRAL,
    ModelConfig,
)
from polykey_tpu.models.loader import import_safetensors  # noqa: E402
from polykey_tpu.models.transformer import (  # noqa: E402
    forward,
    init_cache,
    unembed,
)

B, T = 2, 12


def _hf_config(cfg: ModelConfig):
    """Mirror a ModelConfig into the matching HF config class."""
    common = dict(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        max_position_embeddings=cfg.max_seq_len,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps,
        tie_word_embeddings=cfg.tie_embeddings,
    )
    if cfg.use_post_norms:  # Gemma-2
        return transformers.Gemma2Config(
            **common,
            hidden_activation="gelu_pytorch_tanh",
            attn_logit_softcapping=cfg.attn_logit_softcap,
            final_logit_softcapping=cfg.final_logit_softcap,
            sliding_window=cfg.sliding_window,
            query_pre_attn_scalar=cfg.query_pre_attn_scalar,
            attention_bias=False,
        )
    if cfg.is_moe:  # Mixtral
        common.pop("head_dim")  # MixtralConfig derives it
        return transformers.MixtralConfig(
            **common,
            num_local_experts=cfg.num_experts,
            num_experts_per_tok=cfg.num_experts_per_tok,
            hidden_act="silu",
        )
    return transformers.LlamaConfig(
        **common, hidden_act="silu", attention_bias=False, mlp_bias=False
    )


def _export_hf(cfg: ModelConfig, tmp_path, seed: int = 0):
    """Random-init the HF twin, save safetensors, import as our pytree."""
    torch.manual_seed(seed)
    hf_cfg = _hf_config(cfg)
    # eager: Gemma-2's soft-caps only exist on the eager attention path,
    # and it keeps the comparison implementation-explicit for all families.
    model = transformers.AutoModelForCausalLM.from_config(
        hf_cfg, attn_implementation="eager"
    )
    model = model.to(torch.float32).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    params = import_safetensors(str(tmp_path), cfg, dtype=jnp.float32)
    return model, params


def _hf_logits(model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        out = model(input_ids=torch.from_numpy(tokens).to(torch.long))
    return out.logits.float().numpy()


def _our_logits(params, cfg: ModelConfig, tokens: np.ndarray) -> np.ndarray:
    toks = jnp.asarray(tokens, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    hidden, _ = forward(params, cfg, toks, positions, cache=None)
    return np.asarray(unembed(params, cfg, hidden), np.float32)


def _tokens(cfg: ModelConfig, seed: int = 1) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)


# Gemma's tiny config needs T > sliding_window to actually exercise the
# window mask; widen the window assertion by using a long-enough T.
assert TINY_GEMMA.sliding_window is not None and TINY_GEMMA.sliding_window > 0


@pytest.mark.parametrize(
    "cfg",
    [TINY_LLAMA, TINY_MIXTRAL, TINY_GEMMA],
    ids=lambda c: c.name,
)
def test_logits_match_hf(cfg, tmp_path):
    model, params = _export_hf(cfg, tmp_path)
    tokens = _tokens(cfg)
    ours = _our_logits(params, cfg, tokens)
    theirs = _hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)
    # Greedy continuations agree everywhere, not just within tolerance.
    assert (ours.argmax(-1) == theirs.argmax(-1)).all()


def test_gemma_sliding_window_is_exercised(tmp_path):
    """The parity run must actually cross the sliding-window boundary:
    with T > window, even (sliding) layers mask differently from odd
    (global) layers, so agreement here pins the interleaving convention."""
    cfg = dataclasses.replace(TINY_GEMMA, sliding_window=4)
    assert T > cfg.sliding_window
    model, params = _export_hf(cfg, tmp_path)
    tokens = _tokens(cfg, seed=2)
    ours = _our_logits(params, cfg, tokens)
    theirs = _hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)
    # Counter-check: breaking the window (global everywhere) must diverge,
    # or the assertion above proves nothing at this size.
    broken = dataclasses.replace(cfg, sliding_window=None)
    ours_broken = _our_logits(params, broken, tokens)
    assert not np.allclose(ours_broken, theirs, atol=2e-4, rtol=2e-3)


@pytest.mark.parametrize(
    "cfg", [TINY_LLAMA, TINY_MIXTRAL, TINY_GEMMA], ids=lambda c: c.name
)
def test_serving_cache_path_matches_hf(cfg, tmp_path):
    """The SERVING path (forward with KV cache: prefill then one-token
    decode steps) must also reproduce HF's logits — this is the code the
    engine actually runs (flash-attention fallback + cache writes), not
    the no-cache training attend."""
    model, params = _export_hf(cfg, tmp_path)
    tokens = _tokens(cfg, seed=3)
    theirs = _hf_logits(model, tokens)

    split = T // 2
    cache = init_cache(cfg, B, T, jnp.float32)
    toks = jnp.asarray(tokens, jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(split, dtype=jnp.int32), (B, split))
    hidden, cache = forward(params, cfg, toks[:, :split], pos, cache=cache)
    got = [np.asarray(unembed(params, cfg, hidden), np.float32)]
    for t in range(split, T):
        pos_t = jnp.full((B, 1), t, jnp.int32)
        hidden, cache = forward(params, cfg, toks[:, t : t + 1], pos_t, cache=cache)
        got.append(np.asarray(unembed(params, cfg, hidden), np.float32))
    ours = np.concatenate(got, axis=1)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)


def test_hf_tokenizer_roundtrip(tmp_path):
    """HFTokenizer on a REAL tokenizer file: train a tiny byte-level BPE
    locally (tokenizers lib — no network), load it through the
    transformers adapter, and require encode/decode round-trips plus
    IncrementalDetokenizer streaming equality (''.join of deltas ==
    full decode), including multi-byte UTF-8."""
    tokenizers = pytest.importorskip("tokenizers")

    from polykey_tpu.engine.tokenizer import (
        HFTokenizer,
        IncrementalDetokenizer,
    )

    tok = tokenizers.Tokenizer(tokenizers.models.BPE(unk_token=None))
    tok.pre_tokenizer = tokenizers.pre_tokenizers.ByteLevel(
        add_prefix_space=False
    )
    tok.decoder = tokenizers.decoders.ByteLevel()
    trainer = tokenizers.trainers.BpeTrainer(
        vocab_size=384,
        special_tokens=["<s>", "</s>"],
        initial_alphabet=tokenizers.pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "pack my box with five dozen liquor jugs",
        "víða fóru þeir — über die Brücke, наконец 你好",
    ] * 4
    tok.train_from_iterator(corpus, trainer)
    tok.save(str(tmp_path / "tokenizer.json"))
    (tmp_path / "tokenizer_config.json").write_text(
        '{"tokenizer_class": "PreTrainedTokenizerFast", '
        '"bos_token": "<s>", "eos_token": "</s>"}'
    )

    ht = HFTokenizer(str(tmp_path))
    assert ht.vocab_size == tok.get_vocab_size()
    for text in [
        "the quick brown fox",
        "boxes of jugs over the bridge",
        "über die Brücke 你好 дог",
    ]:
        ids = ht.encode(text)
        assert ids and all(isinstance(i, int) for i in ids)
        assert ht.decode(ids) == text

        det = IncrementalDetokenizer(ht)
        deltas = [det.push(i) for i in ids]
        streamed = "".join(d for d in deltas if d) + det.flush()
        assert streamed == ht.decode(ids)


def test_tied_llama_matches_hf(tmp_path):
    """llama-3.2-1b's shape: tie_word_embeddings=True means HF writes NO
    lm_head tensor and import_safetensors must skip it — the tied-llama
    import path is distinct from both untied llama and gemma."""
    cfg = dataclasses.replace(
        TINY_LLAMA, name="tiny-llama-tied", tie_embeddings=True
    )
    model, params = _export_hf(cfg, tmp_path, seed=4)
    assert "lm_head" not in params
    tokens = _tokens(cfg, seed=5)
    ours = _our_logits(params, cfg, tokens)
    theirs = _hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)
    assert (ours.argmax(-1) == theirs.argmax(-1)).all()


@pytest.mark.parametrize(
    "cfg",
    [TINY_LLAMA, TINY_GEMMA],
    ids=lambda c: c.name,
)
def test_paged_int8_kv_tracks_hf(cfg, tmp_path):
    """The PAGED serving path with int8 KV pools (the engine's
    kv_dtype='int8' configuration) against HF's full-precision logits on
    real architectures: agreement within quantization tolerance — the
    bound that catches a wrong-axis scale or a mask regression, which
    land orders of magnitude past it."""
    from polykey_tpu.engine.kv_cache import init_paged_kv
    from polykey_tpu.models.transformer import forward_paged

    model, params = _export_hf(cfg, tmp_path)
    tokens = _tokens(cfg, seed=5)
    theirs = _hf_logits(model, tokens)

    ps = 4
    P = (T + ps - 1) // ps + 1
    pool = init_paged_kv(cfg, 1 + B * P, ps, jnp.float32, kv_dtype=jnp.int8)
    pt = np.zeros((B, P), np.int32)
    page = 1
    for b in range(B):
        for j in range(P):
            pt[b, j] = page
            page += 1
    pt = jnp.asarray(pt)
    toks = jnp.asarray(tokens, jnp.int32)

    split = T // 2
    pos = jnp.broadcast_to(jnp.arange(split, dtype=jnp.int32), (B, split))
    hidden, pool = forward_paged(params, cfg, toks[:, :split], pos, pool, pt)
    got = [np.asarray(unembed(params, cfg, hidden), np.float32)]
    for t in range(split, T):
        pos_t = jnp.full((B, 1), t, jnp.int32)
        hidden, pool = forward_paged(
            params, cfg, toks[:, t:t + 1], pos_t, pool, pt)
        got.append(np.asarray(unembed(params, cfg, hidden), np.float32))
    ours = np.concatenate(got, axis=1)

    denom = np.max(np.abs(theirs)) + 1e-6
    rel = np.max(np.abs(ours - theirs)) / denom
    assert rel < 0.08, f"int8-KV drift vs HF: {rel:.3f}"
