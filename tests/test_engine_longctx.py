"""Long-context serving: chunked prefill + 8k positions (VERDICT r1 #5).

Prompts longer than the largest prefill bucket must (a) be served at all,
(b) produce EXACTLY the same greedy stream as a single-window prefill of
the same prompt (the chunk boundary is invisible to the math — KV lands at
the same (page, offset) either way), and (c) not starve concurrent short
streams (one chunk per engine iteration).
"""

import dataclasses
import queue
import time

import numpy as np

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine

LONG_CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    num_pages=1600,
    max_seq_len=8192,
    prefill_buckets=(16, 32),
    prefill_chunk=64,
    max_new_tokens_cap=32,
    default_max_new_tokens=8,
)
# Same model/seed, one bucket wide enough to take the same prompt in a
# single window — the equality reference.
WIDE_CONFIG = dataclasses.replace(
    LONG_CONFIG, prefill_buckets=(704,), prefill_chunk=0
)


def _prompt(n: int, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    return "".join(chr(c) for c in rng.integers(97, 123, n))


def _collect(request: GenRequest, timeout=300.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _run_one(config, prompt, max_new=8):
    eng = InferenceEngine(config)
    try:
        r = GenRequest(prompt=prompt, max_new_tokens=max_new)
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None, error
        assert done is not None
        return tokens, done
    finally:
        eng.shutdown()


def test_chunked_matches_single_window():
    prompt = _prompt(600)
    chunked, done_c = _run_one(LONG_CONFIG, prompt)
    wide, done_w = _run_one(WIDE_CONFIG, prompt)
    assert chunked == wide
    # Tokenizer may add BOS; both paths must agree and cover the prompt.
    assert done_c.prompt_tokens == done_w.prompt_tokens >= 600


def test_chunk_boundary_edge():
    # Prompt exactly on a chunk boundary: the final chunk is full-width and
    # the sampling index is its last position.
    prompt = _prompt(128, seed=1)       # == 2 * prefill_chunk
    chunked, _ = _run_one(LONG_CONFIG, prompt)
    wide, _ = _run_one(WIDE_CONFIG, prompt)
    assert chunked == wide


def test_long_prompt_8k():
    cfg = dataclasses.replace(LONG_CONFIG, prefill_chunk=512)
    prompt = _prompt(7900)
    tokens, done = _run_one(cfg, prompt, max_new=4)
    assert len(tokens) >= 1
    # Position budget: prompt tail kept, 7900(+BOS) + 4 fits in 8192.
    assert done.prompt_tokens >= 7900


def test_long_prompt_does_not_block_short_streams():
    eng = InferenceEngine(LONG_CONFIG)
    try:
        long_r = GenRequest(prompt=_prompt(600, seed=2), max_new_tokens=4)
        eng.submit(long_r)
        short_rs = [
            GenRequest(prompt=f"short {i}", max_new_tokens=6)
            for i in range(3)
        ]
        for r in short_rs:
            eng.submit(r)
        for r in short_rs + [long_r]:
            tokens, done, error = _collect(r)
            assert error is None, error
            assert done is not None
            assert len(tokens) >= 1
        # All pages returned (no leak through the chunked path).
        deadline = time.monotonic() + 10
        while eng.busy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.allocator.num_free == LONG_CONFIG.num_pages - 1
    finally:
        eng.shutdown()


def test_chunked_prefill_unharmed_by_concurrent_decode():
    """Regression: while a long prompt chunk-prefills, concurrent decode
    blocks run its slot as an inactive lane and write garbage KV at
    position 0 through whatever page table the device holds. The pending
    slot's real table must stay out of the device mirrors until activation
    (slot transitions mid-prefill force re-uploads), or the prompt's first
    page is corrupted and the greedy output diverges."""
    prompt = _prompt(600, seed=4)
    ref, _ = _run_one(LONG_CONFIG, prompt)

    eng = InferenceEngine(LONG_CONFIG)
    try:
        # Shorts first: they occupy the decode batch, and their staggered
        # finishes mark the device state dirty mid-prefill (the trigger).
        shorts = [
            GenRequest(prompt=f"noise {i}", max_new_tokens=4 + 6 * i)
            for i in range(3)
        ]
        for r in shorts:
            eng.submit(r)
        long_r = GenRequest(prompt=prompt, max_new_tokens=8)
        eng.submit(long_r)
        tokens, done, error = _collect(long_r)
        for r in shorts:
            _collect(r)
        assert error is None, error
        assert tokens == ref
    finally:
        eng.shutdown()


def test_cancel_during_chunked_prefill():
    eng = InferenceEngine(LONG_CONFIG)
    try:
        r = GenRequest(prompt=_prompt(600, seed=3), max_new_tokens=4)
        eng.submit(r)
        r.cancelled.set()
        tokens, done, error = _collect(r, timeout=60)
        # Either it finished before the cancel landed or it was cancelled;
        # pages must come back in both cases.
        deadline = time.monotonic() + 10
        while eng.busy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.allocator.num_free == LONG_CONFIG.num_pages - 1
    finally:
        eng.shutdown()


def test_spec_engine_chunked_prefill():
    # Chunked prefill fills BOTH caches under speculation; greedy equality
    # against the plain chunked engine still holds.
    spec_cfg = dataclasses.replace(
        LONG_CONFIG, draft_model="tiny-llama", spec_gamma=3
    )
    prompt = _prompt(600, seed=4)
    plain, _ = _run_one(LONG_CONFIG, prompt)
    spec, _ = _run_one(spec_cfg, prompt)
    assert spec == plain


def test_int8_kv_chunked_matches_single_window():
    """Chunked prefill writes through the quantized page-granular path
    (aligned chunk starts) and decodes through the int8 window: chunked
    and single-window int8-KV engines must agree exactly."""
    prompt = _prompt(600, seed=3)
    chunked, _ = _run_one(
        dataclasses.replace(LONG_CONFIG, kv_dtype="int8"), prompt)
    wide, _ = _run_one(
        dataclasses.replace(WIDE_CONFIG, kv_dtype="int8"), prompt)
    assert chunked == wide
