"""graphlint (the compiled-graph analysis tier) — a firing AND a
non-firing fixture for every GL check, plus the suppression/baseline
machinery and an engine-backed integration tier.

Unit fixtures exercise the check cores directly (synthetic jits and
jaxprs — fast); the integration tests run the real checks against a
smoke-profile CPU engine, and the full-profile self-run (what `make
graphlint` gates on) is marked slow.
"""

import contextlib
import json
import warnings

import jax
import jax.numpy as jnp
import pytest

from polykey_tpu.analysis import graph
from polykey_tpu.analysis.baseline import apply_baseline, write_baseline
from polykey_tpu.analysis.graph import (
    GraphEnv,
    abstract_contract,
    apply_check_suppressions,
    audit_donation_site,
    callback_findings,
    dtype_findings,
    gate_consistency_findings,
    graph_finding,
    recompile_findings,
    sharding_divisibility,
)


# -- GL001: recompile stability ----------------------------------------------


def _jit_square():
    return jax.jit(lambda x: x * x)


def test_gl001_fires_on_shape_unstable_jit():
    handle = _jit_square()
    handle(jnp.ones((4,)))  # "warmup"

    def drive():
        # A deliberately shape-unstable serving sweep: every new shape is
        # a new executable.
        handle(jnp.ones((8,)))
        handle(jnp.ones((16,)))
        return []

    findings, sizes = recompile_findings("fixture", {"square": handle}, drive)
    grew = [f for f in findings if f.rule == "GL001"
            and f.snippet.endswith(":grew")]
    assert len(grew) == 1
    assert "2 new executable" in grew[0].message
    assert sizes["square"] == (1, 3)


def test_gl001_clean_on_shape_stable_jit():
    handle = _jit_square()
    handle(jnp.ones((4,)))

    def drive():
        for _ in range(3):
            handle(jnp.ones((4,)))
        return []

    findings, sizes = recompile_findings("fixture", {"square": handle}, drive)
    assert findings == []
    assert sizes["square"] == (1, 1)


def test_gl001_fires_on_warmup_gap():
    handle = _jit_square()  # never warmed
    findings, _ = recompile_findings(
        "fixture", {"square": handle}, lambda: [])
    assert any(f.snippet.endswith(":cold") for f in findings)


def test_gl001_surfaces_drive_errors_as_gl000():
    handle = _jit_square()
    handle(jnp.ones((4,)))
    findings, _ = recompile_findings(
        "fixture", {"square": handle}, lambda: ["engine wedged"])
    assert any(f.rule == "GL000" and "engine wedged" in f.message
               for f in findings)


# -- GL002: donation audit ----------------------------------------------------


def test_gl002_fires_when_donation_dropped():
    # The donated arg's dtype matches no output → XLA cannot alias it and
    # warns; the audit must fail on that warning.
    fn = jax.jit(
        lambda x, y: (x + y).astype(jnp.bfloat16), donate_argnames=("x",))
    args = (jnp.ones((64, 64)), jnp.ones((64, 64)))
    findings = audit_donation_site(
        "fixture.dropped", lambda: fn.lower(*args), donated_big_leaves=1)
    assert any(f.rule == "GL002" and "dropped" in f.snippet
               for f in findings)


def test_gl002_fires_on_alias_deficit():
    # No donation at all (the "removed donate_argnames" regression): the
    # compiled executable aliases nothing, so auditing it against one
    # expected donated buffer must fail.
    fn = jax.jit(lambda x, y: x + y)
    args = (jnp.ones((64, 64)), jnp.ones((64, 64)))
    findings = audit_donation_site(
        "fixture.nodonate", lambda: fn.lower(*args), donated_big_leaves=1)
    assert any(f.rule == "GL002" and "alias-deficit" in f.snippet
               for f in findings)


def test_gl002_clean_on_aliased_donation():
    fn = jax.jit(lambda x, y: x + y, donate_argnames=("x",))
    args = (jnp.ones((64, 64)), jnp.ones((64, 64)))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a dropped donation would raise
        findings = audit_donation_site(
            "fixture.good", lambda: fn.lower(*args), donated_big_leaves=1)
    assert findings == []


def test_gl002_lower_failure_is_blocking_gl000():
    def broken_lower():
        raise RuntimeError("no such handle")

    findings = audit_donation_site("fixture.broken", broken_lower, 1)
    assert any(f.rule == "GL000" for f in findings)


# -- GL003: dtype policy ------------------------------------------------------

_W_SHAPE = (32, 64)


def test_gl003_fires_on_weight_upcast_in_bf16_path():
    def fn(w, x):
        return x @ w.astype(jnp.float32)  # the classic silent upcast

    jaxpr = jax.make_jaxpr(fn)(
        jnp.zeros(_W_SHAPE, jnp.bfloat16), jnp.zeros((4, 32), jnp.float32))
    findings = dtype_findings("fixture", jaxpr, {_W_SHAPE}, bf16_path=True)
    assert any(f.rule == "GL003" and "upcast" in f.snippet
               for f in findings)


def test_gl003_activation_upcast_does_not_fire():
    # Mixed-precision activations (norm/softmax in f32) are the design;
    # only weight-shaped operands may fire.
    def fn(w, x):
        h = (x.astype(jnp.float32) ** 2).astype(jnp.bfloat16)
        return h @ w

    jaxpr = jax.make_jaxpr(fn)(
        jnp.zeros(_W_SHAPE, jnp.bfloat16), jnp.zeros((4, 32), jnp.bfloat16))
    assert dtype_findings("fixture", jaxpr, {_W_SHAPE}, bf16_path=True) == []


def test_gl003_fires_on_f64_anywhere():
    with jax.experimental.enable_x64():
        jaxpr = jax.make_jaxpr(
            lambda x: x.astype(jnp.float64) * 2.0)(jnp.zeros((8,)))
    findings = dtype_findings("fixture", jaxpr, set(), bf16_path=False)
    assert any(f.rule == "GL003" and ":f64:" in f.snippet
               for f in findings)


def test_gl003_walks_nested_jaxprs():
    # The f64 hides inside a scan body — the walk must descend.
    with jax.experimental.enable_x64():
        def fn(x):
            def body(carry, _):
                return carry + x.astype(jnp.float64).sum(), None
            out, _ = jax.lax.scan(body, 0.0, None, length=3)
            return out

        jaxpr = jax.make_jaxpr(fn)(jnp.zeros((8,)))
    findings = dtype_findings("fixture", jaxpr, set(), bf16_path=False)
    assert any(":f64:" in f.snippet for f in findings)


# -- GL004: host-transfer guard -----------------------------------------------


def test_gl004_fires_on_debug_callback_in_step():
    def fn(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    jaxpr = jax.make_jaxpr(fn)(jnp.ones((4,)))
    findings = callback_findings("fixture", jaxpr)
    assert any(f.rule == "GL004" and "callback" in f.message
               for f in findings)


def test_gl004_clean_on_pure_step():
    jaxpr = jax.make_jaxpr(lambda x: x * 2 + 1)(jnp.ones((4,)))
    assert callback_findings("fixture", jaxpr) == []


# -- GL005: shape/layout contracts --------------------------------------------


def _mesh_tp2():
    from polykey_tpu.parallel.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(tp=2), jax.devices()[:2])


def test_gl005_fires_on_indivisible_sharded_dim():
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(_mesh_tp2(), PartitionSpec(None, "tp"))
    findings = sharding_divisibility("fixture", (4, 3), sharding)
    assert len(findings) == 1 and findings[0].rule == "GL005"
    assert "3 % 2" in findings[0].message


def test_gl005_clean_on_divisible_sharded_dim():
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(_mesh_tp2(), PartitionSpec(None, "tp"))
    assert sharding_divisibility("fixture", (4, 6), sharding) == []


def test_gl005_abstract_contract_fires_on_mismatch():
    findings = abstract_contract(
        "fixture", lambda x: x[:2], (jnp.zeros((4, 4)),),
        [((4, 4), "float32")])
    assert any("out-contract" in f.snippet for f in findings)


def test_gl005_abstract_contract_fires_on_trace_error():
    def broken(x):
        raise ValueError("block shape does not divide grid")

    findings = abstract_contract(
        "fixture", broken, (jnp.zeros((4,)),), [((4,), "float32")])
    assert any("abstract-eval" in f.snippet for f in findings)


def test_gl005_abstract_contract_clean():
    assert abstract_contract(
        "fixture", lambda x: x * 2, (jnp.zeros((4, 4)),),
        [((4, 4), "float32")]) == []


def test_gl005_gate_consistency_firing_and_clean():
    from dataclasses import replace

    from polykey_tpu.models.config import TINY_LLAMA

    # folded lanes 32*4=128 → gate-eligible, but head_dim 4 mis-tiles.
    bad = replace(TINY_LLAMA, name="bad-geom", num_kv_heads=32, head_dim=4)
    findings = gate_consistency_findings([bad])
    assert any("paged-gate:bad-geom" == f.snippet for f in findings)
    assert gate_consistency_findings([TINY_LLAMA]) == []


# -- suppressions + baseline --------------------------------------------------


def test_check_suppression_marks_finding(monkeypatch):
    finding = graph_finding("GL003", "graph:x", "x:upcast:(1, 2)", "msg")
    check = graph._GRAPH_REGISTRY["GL003"]
    monkeypatch.setattr(
        check, "SUPPRESSIONS",
        {"x:upcast:(1, 2)": "reviewed: deliberate f32 residual"})
    out = apply_check_suppressions([finding])
    assert out[0].suppressed and "reviewed" in out[0].reason
    assert not out[0].blocking


def test_unsuppressed_finding_stays_blocking():
    finding = graph_finding("GL001", "graph:x", "x:key", "msg")
    out = apply_check_suppressions([finding])
    assert not out[0].suppressed and out[0].blocking


def test_graph_findings_roundtrip_the_baseline(tmp_path):
    findings = [
        graph_finding("GL001", "graph:engine.plain", "k1", "grew"),
        graph_finding("GL002", "graph:train", "k2", "dropped"),
    ]
    path = tmp_path / "graphlint-baseline.json"
    assert write_baseline(path, findings) == 2
    from polykey_tpu.analysis.baseline import load_baseline

    marked, stale = apply_baseline(findings, load_baseline(path))
    assert all(f.baselined for f in marked) and stale == []
    # A fixed finding's entry goes stale (prune signal).
    marked, stale = apply_baseline(findings[:1], load_baseline(path))
    assert len(stale) == 1


def test_cli_list_checks(capsys):
    assert graph.main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for check_id in ("GL001", "GL002", "GL003", "GL004", "GL005"):
        assert check_id in out


def test_cli_only_rejects_unknown_check_id(capsys):
    # A typo'd id silently running zero checks would read as a clean
    # graph; the CLI must refuse instead.
    assert graph.main(["--only", "GL01,GL004"]) == 2
    err = capsys.readouterr().err
    assert "unknown check id" in err and "GL01" in err


def test_cli_prune_requires_full_run(capsys):
    assert graph.main(["--only", "GL003", "--prune"]) == 2
    assert "full run" in capsys.readouterr().err


def test_cli_write_baseline_requires_full_run(capsys):
    # Rewriting the baseline from a partial run would silently discard
    # every other check's grandfathered entries.
    assert graph.main(["--only", "GL003", "--write-baseline"]) == 2
    assert "full run" in capsys.readouterr().err


def test_cli_write_baseline_refuses_gl000(tmp_path, monkeypatch, capsys):
    # GL000 = the analyzer itself is broken (a partial run in disguise);
    # grandfathering from it would drop the crashed check's live entries
    # and make graphlint exit 0 forever. The file must stay untouched.
    path = tmp_path / "graphlint-baseline.json"
    write_baseline(
        path, [graph_finding("GL001", "graph:engine.plain", "k1", "grew")])
    findings = [
        graph_finding("GL000", "graph:GL001", "GL001:crashed", "probe gone"),
        graph_finding("GL005", "graph:flash", "k5", "bad block"),
    ]
    monkeypatch.setattr(
        graph, "run_graph_checks",
        lambda env, only=None: (findings, env))
    assert graph.main(["--root", str(tmp_path), "--write-baseline"]) == 1
    assert "refusing to write" in capsys.readouterr().err
    from polykey_tpu.analysis.baseline import load_baseline

    entries = load_baseline(path)["findings"]
    assert len(entries) == 1  # pre-existing GL001 entry untouched
    assert all(e["rule"] == "GL001" for e in entries.values()), entries


def test_cli_prune_refuses_on_gl000(tmp_path, monkeypatch, capsys):
    # A crashed check replaced its real findings with GL000; pruning
    # against that run would drop the crashed check's live entries.
    findings = [
        graph_finding("GL000", "graph:GL001", "GL001:crashed", "probe gone"),
    ]
    path = tmp_path / "graphlint-baseline.json"
    write_baseline(
        path, [graph_finding("GL001", "graph:engine.plain", "k1", "grew")])
    monkeypatch.setattr(
        graph, "run_graph_checks",
        lambda env, only=None: (findings, env))
    assert graph.main(["--root", str(tmp_path), "--prune"]) == 1
    assert "refusing to prune" in capsys.readouterr().err
    from polykey_tpu.analysis.baseline import load_baseline

    assert len(load_baseline(path)["findings"]) == 1  # untouched


def test_cli_only_does_not_report_unrun_checks_stale(
        tmp_path, monkeypatch, capsys):
    # Baseline holds GL001 debt; an --only GL003 run must not claim the
    # GL001 entry is a fixed finding (false debt-paid signal).
    path = tmp_path / "graphlint-baseline.json"
    write_baseline(
        path, [graph_finding("GL001", "graph:engine.plain", "k1", "grew")])
    monkeypatch.setattr(
        graph, "run_graph_checks", lambda env, only=None: ([], env))
    assert graph.main(
        ["--root", str(tmp_path), "--only", "GL003", "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)["summary"]
    assert summary["stale_baseline_entries"] == []


def test_cli_prune_drops_stale_graph_entries(tmp_path, monkeypatch, capsys):
    # Baseline two findings, then monkeypatch the run to produce only one:
    # --prune must drop exactly the stale entry and keep the live one.
    findings = [
        graph_finding("GL001", "graph:engine.plain", "k1", "grew"),
        graph_finding("GL002", "graph:train", "k2", "dropped"),
    ]
    path = tmp_path / "graphlint-baseline.json"
    assert write_baseline(path, findings) == 2
    monkeypatch.setattr(
        graph, "run_graph_checks",
        lambda env, only=None: (findings[:1], env))
    assert graph.main(["--root", str(tmp_path), "--prune"]) == 0
    assert "pruned 1 stale" in capsys.readouterr().out
    from polykey_tpu.analysis.baseline import load_baseline

    assert len(load_baseline(path).get("findings", {})) == 1


# -- integration: the real checks against a smoke-profile engine --------------


@pytest.fixture(scope="module")
def smoke_env():
    env = GraphEnv(profile="smoke")
    yield env
    env.close()


def test_gl001_real_engine_is_compile_stable(smoke_env):
    check = graph._GRAPH_REGISTRY["GL001"]
    findings = check.run(smoke_env)
    assert findings == [], [f.render() for f in findings]


def test_gl002_real_donation_sites_are_aliased(smoke_env):
    check = graph._GRAPH_REGISTRY["GL002"]
    findings = check.run(smoke_env)
    assert findings == [], [f.render() for f in findings]


def test_gl004_guard_smoke_clean_and_guard_restored(smoke_env):
    # Preset a per-direction guard: the smoke's save/restore must not
    # wipe it (restoring only the umbrella would, since the umbrella
    # propagates into the per-direction options on update).
    prev = jax.config.jax_transfer_guard_device_to_device
    jax.config.update("jax_transfer_guard_device_to_device", "log")
    try:
        check = graph._GRAPH_REGISTRY["GL004"]
        findings = check._guarded_smoke(smoke_env)
        assert findings == [], [f.render() for f in findings]
        # The guard must be restored — later tests upload numpy freely.
        assert jax.config.jax_transfer_guard in (None, "allow")
        assert jax.config.jax_transfer_guard_device_to_device == "log"
    finally:
        jax.config.update("jax_transfer_guard_device_to_device", prev)


def test_host_crossing_honors_per_direction_guard():
    """The nullcontext fast path must NOT engage when a per-direction
    guard option is set (the umbrella propagates into the directions on
    update, but a per-direction update never reflects back)."""
    from polykey_tpu.engine import engine as engine_mod

    assert isinstance(engine_mod._host_crossing(), contextlib.nullcontext)
    prev = jax.config.jax_transfer_guard_device_to_host
    jax.config.update("jax_transfer_guard_device_to_host", "disallow")
    try:
        assert not isinstance(
            engine_mod._host_crossing(), contextlib.nullcontext)
    finally:
        jax.config.update("jax_transfer_guard_device_to_host", prev)


def test_gl004_trips_without_host_crossing_annotations():
    """Removing the engine's _host_crossing annotations must trip the
    guarded smoke — proves the guard has teeth end-to-end (a sacrificial
    engine: the tripped merges poison its slots)."""
    from polykey_tpu.engine import engine as engine_mod

    def _no_annotation(site: str = "unlabeled"):
        return contextlib.nullcontext()

    original = engine_mod._host_crossing
    engine_mod._host_crossing = _no_annotation
    env = GraphEnv(profile="smoke")
    try:
        check = graph._GRAPH_REGISTRY["GL004"]
        findings = check._guarded_smoke(env)
        assert any(f.rule == "GL004" for f in findings)
    finally:
        engine_mod._host_crossing = original
        env.close()


@pytest.mark.slow
def test_full_graphlint_self_run_clean():
    """The `make graphlint` gate: every check, full profile, zero
    blocking findings on this repo."""
    findings, env = graph.run_graph_checks()
    try:
        blocking = [f for f in findings if f.blocking]
        assert blocking == [], [f.render() for f in blocking]
    finally:
        env.close()
