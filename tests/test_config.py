"""Config loader parity tests (/root/reference/internal/config/config.go)."""

import pytest

from polykey_tpu.gateway.config import (
    ConfigLoader,
    NetworkTester,
    RuntimeDetector,
    RuntimeEnvironment,
    parse_duration,
)


class _FixedDetector(RuntimeDetector):
    def __init__(self, runtime):
        self._runtime = runtime

    def detect_runtime(self):
        return self._runtime


def _clear_env(monkeypatch):
    for var in (
        "POLYKEY_SERVER_ADDR",
        "POLYKEY_TIMEOUT",
        "POLYKEY_LOG_LEVEL",
        "POLYKEY_ENV",
        "KUBERNETES_SERVICE_HOST",
        "container",
    ):
        monkeypatch.delenv(var, raising=False)


def test_defaults(monkeypatch):
    _clear_env(monkeypatch)
    cfg = ConfigLoader(_FixedDetector(RuntimeEnvironment.LOCAL)).load([])
    assert cfg.timeout == 5.0
    assert cfg.log_level == "info"
    assert cfg.environment == "development"
    assert cfg.server_address == "localhost:50051"


def test_flags(monkeypatch):
    _clear_env(monkeypatch)
    cfg = ConfigLoader(_FixedDetector(RuntimeEnvironment.LOCAL)).load(
        ["-server", "example:1234", "-timeout", "10s", "-log-level", "debug",
         "-env", "production"]
    )
    assert cfg.server_address == "example:1234"
    assert cfg.timeout == 10.0
    assert cfg.log_level == "debug"
    assert cfg.environment == "production"


def test_env_overrides_flags(monkeypatch):
    # Load() applies env after flags, so env wins (config.go Load()).
    _clear_env(monkeypatch)
    monkeypatch.setenv("POLYKEY_SERVER_ADDR", "env-host:9")
    monkeypatch.setenv("POLYKEY_TIMEOUT", "500ms")
    cfg = ConfigLoader(_FixedDetector(RuntimeEnvironment.LOCAL)).load(
        ["-server", "flag-host:8", "-timeout", "10s"]
    )
    assert cfg.server_address == "env-host:9"
    assert cfg.timeout == 0.5


def test_malformed_env_timeout_is_ignored(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv("POLYKEY_TIMEOUT", "not-a-duration")
    cfg = ConfigLoader(_FixedDetector(RuntimeEnvironment.LOCAL)).load([])
    assert cfg.timeout == 5.0


@pytest.mark.parametrize(
    "runtime,expected",
    [
        (RuntimeEnvironment.KUBERNETES, "polykey-service:50051"),
        (RuntimeEnvironment.DOCKER, "polykey-server:50051"),
        (RuntimeEnvironment.CONTAINERD, "polykey-server:50051"),
        (RuntimeEnvironment.PODMAN, "polykey-server:50051"),
        (RuntimeEnvironment.LOCAL, "localhost:50051"),
    ],
)
def test_address_autodetection(monkeypatch, runtime, expected):
    _clear_env(monkeypatch)
    cfg = ConfigLoader(_FixedDetector(runtime)).load([])
    assert cfg.server_address == expected


def test_k8s_detection_via_env(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    assert RuntimeDetector().detect_runtime() == RuntimeEnvironment.KUBERNETES


def test_podman_detection_via_env(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv("container", "podman")
    assert RuntimeDetector().detect_runtime() == RuntimeEnvironment.PODMAN


@pytest.mark.parametrize(
    "text,seconds",
    [("5s", 5.0), ("500ms", 0.5), ("1m30s", 90.0), ("2h", 7200.0),
     ("250us", 0.00025), ("3", 3.0)],
)
def test_parse_duration(text, seconds):
    assert parse_duration(text) == pytest.approx(seconds)


def test_parse_duration_rejects_garbage():
    with pytest.raises(ValueError):
        parse_duration("10 parsecs")


def test_network_tester_refused():
    with pytest.raises(ConnectionError):
        # Port 1 on localhost is essentially guaranteed closed.
        NetworkTester().test_connection("127.0.0.1:1", timeout=0.5)


def test_engine_config_from_env(monkeypatch):
    """Every POLYKEY_* engine knob must actually reach EngineConfig —
    a knob that parses to nowhere silently misleads operators."""
    from polykey_tpu.engine.config import EngineConfig

    env = {
        "POLYKEY_MODEL": "tiny-mixtral",
        "POLYKEY_DTYPE": "float32",
        "POLYKEY_QUANTIZE": "1",
        "POLYKEY_MAX_DECODE_SLOTS": "8",
        "POLYKEY_PAGE_SIZE": "32",
        "POLYKEY_NUM_PAGES": "256",
        "POLYKEY_MAX_SEQ_LEN": "1024",
        "POLYKEY_PREFILL_BUCKETS": "64,256",
        "POLYKEY_PREFILL_CHUNK": "64",
        "POLYKEY_DECODE_BLOCK": "4",
        "POLYKEY_COMPILE_WARMUP": "true",
        "POLYKEY_TP": "2",
        "POLYKEY_DP": "2",
        "POLYKEY_EP": "2",
        "POLYKEY_SP": "2",
        "POLYKEY_DRAFT_MODEL": "tiny-llama",
        "POLYKEY_SPEC_GAMMA": "3",
        "POLYKEY_NUM_SLICES": "2",
        "POLYKEY_ADAPTIVE_BLOCK": "0",
        "POLYKEY_ADAPTIVE_GAMMA": "0",
    }
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    cfg = EngineConfig.from_env()
    assert cfg.model == "tiny-mixtral"
    assert cfg.quantize and cfg.compile_warmup
    assert (cfg.max_decode_slots, cfg.page_size, cfg.num_pages) == (8, 32, 256)
    assert cfg.prefill_buckets == (64, 256)
    assert (cfg.prefill_chunk, cfg.decode_block_steps) == (64, 4)
    assert (cfg.tp, cfg.dp, cfg.ep, cfg.sp) == (2, 2, 2, 2)
    assert (cfg.draft_model, cfg.spec_gamma) == ("tiny-llama", 3)
    assert cfg.num_slices == 2
    assert cfg.quantize_bits == 8
    # The adaptive knobs default ON; "0" must pin them off.
    assert not cfg.adaptive_block and not cfg.adaptive_gamma
    cfg.validate()


def test_engine_config_int4_env(monkeypatch):
    """POLYKEY_QUANTIZE=int4 selects 4-bit weight-only quantization."""
    from polykey_tpu.engine.config import EngineConfig

    monkeypatch.setenv("POLYKEY_QUANTIZE", "int4")
    cfg = EngineConfig.from_env()
    assert cfg.quantize and cfg.quantize_bits == 4
    cfg.validate()


def test_persistent_compile_cache(monkeypatch, tmp_path):
    """enable_persistent_compile_cache points JAX's durable cache at the
    configured directory and populates it (min-compile-time forced to 0 so
    even a trivial CPU jit writes an entry). Restarts and bench retries
    after a tunnel flap reuse these entries instead of recompiling."""
    import polykey_tpu.engine.config as ec

    cache_dir = tmp_path / "xla_cache"
    monkeypatch.setenv("POLYKEY_COMPILE_CACHE_DIR", str(cache_dir))
    monkeypatch.setenv("POLYKEY_COMPILE_CACHE_MIN_SECS", "0")
    monkeypatch.setattr(ec, "_compile_cache_dir", None)
    got = ec.enable_persistent_compile_cache()
    assert got == str(cache_dir)

    import jax
    import jax.numpy as jnp

    try:
        # A fresh shape so the in-memory jit cache can't satisfy it.
        jax.jit(lambda x: (x * 3 + 1).sum())(jnp.arange(1237.0)).block_until_ready()
        assert any(cache_dir.iterdir()), "compile cache wrote no entries"
    finally:
        # Detach the global cache dir so later tests don't write into the
        # (deleted) tmp_path. Setting the config option back to None is NOT
        # enough: once initialized, jax's compilation cache object keeps
        # reading/writing the old directory, and with min_compile_time_secs
        # still 0 every later compile in this process round-trips through the
        # stale tmp cache (which destabilizes later engine tests). Reset the
        # cache object itself and restore the min-compile threshold.
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass
        monkeypatch.setattr(ec, "_compile_cache_dir", None)


def test_persistent_compile_cache_opt_out(monkeypatch):
    """POLYKEY_COMPILE_CACHE=0 disables the cache entirely."""
    import polykey_tpu.engine.config as ec

    monkeypatch.setenv("POLYKEY_COMPILE_CACHE", "0")
    monkeypatch.setattr(ec, "_compile_cache_dir", None)
    assert ec.enable_persistent_compile_cache() is None
