"""Test-session bootstrap.

Forces JAX onto a simulated 8-device CPU platform *before* jax is imported
anywhere, so multi-chip sharding (tp/dp/ep/sp axes over a Mesh) is exercised
without TPU hardware — the strategy SURVEY.md §4 prescribes for this
framework's multi-node tier.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
