"""Test-session bootstrap.

Forces JAX onto a simulated 8-device CPU platform so multi-chip sharding
(tp/dp/ep/sp axes over a Mesh) is exercised without TPU hardware — the
strategy SURVEY.md §4 prescribes for this framework's multi-node tier.

Note: this image pre-imports a TPU platform plugin and pins JAX_PLATFORMS in
the environment, so plain env vars are not enough — XLA_FLAGS must be set
before backend init AND the platform must be overridden via jax.config.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
