"""Test-session bootstrap.

Forces JAX onto a simulated 8-device CPU platform so multi-chip sharding
(tp/dp/ep/sp axes over a Mesh) is exercised without TPU hardware — the
strategy SURVEY.md §4 prescribes for this framework's multi-node tier.

Note: this image pre-imports a TPU platform plugin and pins JAX_PLATFORMS in
the environment, so plain env vars are not enough — XLA_FLAGS must be set
before backend init AND the platform must be overridden via jax.config.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import gc

import pytest


def pytest_configure(config):
    # The tier-1 gate runs `-m 'not slow'`; anything heavier (e.g. the
    # full-profile graphlint self-run) opts out with this marker.
    config.addinivalue_line(
        "markers", "slow: excluded from the fast tier-1 run (-m 'not slow')"
    )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """XLA's CPU JIT segfaults deterministically late in the FULL suite
    (inside backend_compile_and_load for the ring-attention train step;
    the same test passes in isolation and the full suite passed before
    the suite grew past ~270 tests) — compile-state accumulated across
    hundreds of in-process executables eventually corrupts a compile.
    Dropping the compiled-executable caches at module boundaries keeps
    the accumulation bounded; modules recompile their own shapes anyway,
    so the cost is small and per-module behavior is unchanged."""
    yield
    jax.clear_caches()
    gc.collect()
