"""MoE op tests: routing, dense vs dispatch formulations."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from polykey_tpu.models.config import TINY_MIXTRAL
from polykey_tpu.models.layers import init_mlp_params
from polykey_tpu.ops.moe import moe_mlp, moe_mlp_dispatch

CFG = dataclasses.replace(TINY_MIXTRAL, hidden_size=32, intermediate_size=64)


def _layer(key):
    k_router, k_experts = jax.random.split(key)
    return {
        "router": jax.random.normal(
            k_router, (CFG.hidden_size, CFG.num_experts), jnp.float32
        )
        * CFG.hidden_size**-0.5,
        "experts": jax.vmap(
            lambda kk: init_mlp_params(
                kk, CFG.hidden_size, CFG.intermediate_size, jnp.float32
            )
        )(jax.random.split(k_experts, CFG.num_experts)),
    }


def test_dense_moe_shapes_and_finite():
    layer = _layer(jax.random.PRNGKey(0))
    h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, CFG.hidden_size))
    out = moe_mlp(layer, h, CFG)
    assert out.shape == h.shape
    assert np.isfinite(np.asarray(out)).all()


def test_dispatch_matches_dense_with_ample_capacity():
    """With capacity ≥ tokens·k no token drops, so the bucketed dispatch must
    reproduce the dense formulation exactly."""
    layer = _layer(jax.random.PRNGKey(2))
    h = jax.random.normal(jax.random.PRNGKey(3), (2, 8, CFG.hidden_size))
    dense = moe_mlp(layer, h, CFG)
    dispatched = moe_mlp_dispatch(layer, h, CFG, capacity_factor=float(CFG.num_experts))
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(dispatched), rtol=1e-4, atol=1e-4
    )


def test_dispatch_drops_overflow_gracefully():
    """Tiny capacity: output stays finite and bounded (dropped tokens ride
    the residual, they must not produce NaNs or garbage)."""
    layer = _layer(jax.random.PRNGKey(4))
    h = jax.random.normal(jax.random.PRNGKey(5), (2, 16, CFG.hidden_size))
    out = moe_mlp_dispatch(layer, h, CFG, capacity_factor=0.25)
    assert np.isfinite(np.asarray(out)).all()
    # Dropped tokens contribute zero; the norm can only shrink vs ample capacity.
    full = moe_mlp_dispatch(layer, h, CFG, capacity_factor=float(CFG.num_experts))
    assert float(jnp.linalg.norm(out)) <= float(jnp.linalg.norm(full)) + 1e-3


def test_router_weights_differentiable():
    layer = _layer(jax.random.PRNGKey(6))
    h = jax.random.normal(jax.random.PRNGKey(7), (1, 4, CFG.hidden_size))

    def loss(layer):
        return jnp.sum(moe_mlp(layer, h, CFG) ** 2)

    grads = jax.grad(loss)(layer)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree_util.tree_leaves(grads)]
    assert all(np.isfinite(norms))
    assert any(n > 0 for n in norms)  # router grads flow
