"""Cross-process flight deck (ISSUE 16): clock alignment, merged
timelines with handoff arcs, crash-durable black boxes, and the
postmortem reconstruction path.

Pinned contracts:
- `ClockSync` recovers a known skew within its stated uncertainty and
  ages a stale estimate by the drift bound (fresh mediocre beats stale
  perfect, eventually);
- `merge_timelines` + `to_perfetto` render ONE valid Perfetto trace
  with per-process monotone slices and a handoff flow arc connecting
  the prefill worker's serialize end to the decode worker's scatter
  start (causally ordered after alignment);
- `BlackBox` checkpoints are amortized, atomic (tmp→rename, no torn
  reads), and round-trip through `load_blackboxes`;
- the postmortem CLI reconstructs a death: triage names the stalest
  member first, surfaces in-flight trace ids, and emits a merged
  Perfetto file;
- live pool: trace ids thread end-to-end across a disagg re-route,
  `merged_perfetto()` shows all three process rows + the arc, and
  black-box checkpointing on vs off changes NOTHING about the stream
  (the overhead gate).
"""

import json
import os
import threading
import time

import pytest

from polykey_tpu import faults
from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.disagg_pool import DECODE, PREFILL, DisaggPool
from polykey_tpu.engine.engine import GenRequest
from polykey_tpu.engine.worker import WorkerServer
from polykey_tpu.obs import Span, signals_snapshot
from polykey_tpu.obs.clocks import ClockSync
from polykey_tpu.obs.postmortem import (
    BlackBox,
    blackbox_path,
    load_blackboxes,
    main as postmortem_main,
    merged_perfetto,
    triage_report,
)
from polykey_tpu.obs.timeline import (
    TimelineRecorder,
    merge_timelines,
    to_perfetto,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- clock alignment ----------------------------------------------------------


def _exchange(sync: ClockSync, local_t: float, skew: float,
              rtt: float = 0.002) -> None:
    """One ideal ping at local time `local_t` against a remote whose
    clock reads local - skew; the reply is stamped at the midpoint."""
    t_send = local_t
    t_recv = local_t + rtt
    remote_mono = (t_send + t_recv) / 2.0 - skew
    sync.update(t_send, t_recv, remote_mono)


def test_clock_recovers_known_skew_within_bound():
    skew = 123.456789
    sync = ClockSync()
    for i in range(10):
        _exchange(sync, 100.0 + i, skew, rtt=0.004)
    assert sync.offset is not None
    bound = sync.uncertainty(now=110.0)
    # Best sample: rtt/2, drift-aged over the ~10 s since it landed.
    assert bound <= 0.002 + 200e-6 * 10.0 + 1e-9
    assert abs(sync.offset - skew) <= bound
    # to_local maps a remote stamp back onto the local axis.
    assert sync.to_local(50.0 - skew) == pytest.approx(50.0, abs=bound)


def test_clock_recovers_offset_under_asymmetric_noise():
    # Midpoint stamping is the NTP assumption; asymmetric service time
    # shifts the estimate by at most rtt/2 — the stated uncertainty.
    skew = -7.25
    sync = ClockSync()
    for i in range(20):
        t_send = 10.0 + i
        rtt = 0.001 + (i % 5) * 0.002
        # Remote stamps at 80% through the exchange, not the midpoint.
        remote_mono = t_send + 0.8 * rtt - skew
        sync.update(t_send, t_send + rtt, remote_mono)
    bound = sync.uncertainty(now=30.0)
    assert abs(sync.offset - skew) <= bound


def test_clock_drift_ages_stale_estimate():
    sync = ClockSync(drift_ppm=200.0)
    _exchange(sync, 0.0, 5.0, rtt=0.0001)      # near-perfect sample
    tight = sync.uncertainty(now=0.0001)
    # 10000 s later the 200 ppm budget has grown the bound by ~2 s …
    aged = sync.uncertainty(now=10000.0)
    assert aged > 1.9 and aged > tight
    # … so a mediocre-but-fresh sample wins.
    assert sync.update(10000.0, 10000.5, 10000.25 - 5.0) is True
    assert sync.uncertainty(now=10000.5) <= 0.25 + 1e-9


def test_clock_rejects_worse_samples_and_resets():
    sync = ClockSync()
    _exchange(sync, 0.0, 1.0, rtt=0.001)
    assert sync.update(0.1, 0.5, 0.3 - 1.0) is False   # fatter rtt loses
    assert sync.update(1.0, 0.9, 0.95) is False        # negative rtt
    assert sync.accepted == 1 and sync.samples == 2
    sync.reset()
    assert sync.offset is None and sync.uncertainty() is None
    assert sync.to_local(42.0) == 42.0                 # identity fallback


# -- merged timeline + handoff arcs -------------------------------------------


def _note(t: float, kind: str, **attrs) -> dict:
    return {"kind": "note", "t": t, "note_kind": kind, "attrs": attrs}


def _synthetic_groups(handoff_id: str = "h1"):
    """Coordinator + prefill + decode rings for one handoff, each on its
    own clock: prefill runs 10 s behind the coordinator, decode 3 s
    ahead. After alignment the serialize end precedes the scatter start
    by 50 ms of wire time."""
    coord = [
        _note(100.00, "handoff_start", handoff_id=handoff_id, trace="t-1"),
        _note(100.20, "handoff_ack", handoff_id=handoff_id, trace="t-1"),
    ]
    prefill = [
        _note(90.05, "prefill_op", handoff_id=handoff_id, trace="t-1"),
        _note(90.10, "handoff_serialize", handoff_id=handoff_id,
              trace="t-1", bytes=4096),
    ]
    decode = [
        _note(103.12, "decode_op", handoff_id=handoff_id, trace="t-1"),
        _note(103.15, "handoff_scatter", handoff_id=handoff_id,
              trace="t-1"),
    ]
    return [
        (0, "coordinator", coord, 0.0),
        (1, "prefill-0", prefill, 10.0),
        (2, "decode-0", decode, -3.0),
    ]


def _arc_pair(trace: dict):
    starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    ends = [e for e in trace["traceEvents"] if e.get("ph") == "f"]
    return starts, ends


def test_merged_timeline_golden():
    merged = merge_timelines(_synthetic_groups())
    # Shift applied, input order preserved, originals untouched.
    assert [pid for pid, _, _ in merged] == [0, 1, 2]
    prefill_events = merged[1][2]
    assert prefill_events[1]["t"] == pytest.approx(100.10)
    trace = to_perfetto(merged, meta={"clock_offsets": {"prefill-0": 10.0}})
    json.loads(json.dumps(trace))                     # Perfetto-loadable
    assert trace["otherData"]["clock_offsets"]["prefill-0"] == 10.0
    # One process row per member.
    process_names = {
        e["pid"]: e["args"]["name"]
        for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert process_names == {0: "polykey coordinator",
                             1: "polykey prefill-0",
                             2: "polykey decode-0"}
    starts, ends = _arc_pair(trace)
    assert len(starts) == 1 and len(ends) == 1
    start, end = starts[0], ends[0]
    assert start["id"] == end["id"] == "h1"
    assert start["pid"] == 1 and end["pid"] == 2      # prefill → decode
    assert end["bp"] == "e"
    # Causal order after alignment: serialize end <= scatter start.
    assert start["ts"] <= end["ts"]
    assert end["ts"] - start["ts"] == pytest.approx(50e3, rel=0.01)  # µs


def test_merged_timeline_input_not_mutated():
    groups = _synthetic_groups()
    before = json.dumps(groups[1][2])
    merge_timelines(groups)
    assert json.dumps(groups[1][2]) == before


def test_one_sided_arc_is_skipped():
    groups = _synthetic_groups()
    # Drop the decode ring: an abort mid-wire leaves serialize only.
    trace = to_perfetto(merge_timelines(groups[:2]))
    starts, ends = _arc_pair(trace)
    assert starts == [] and ends == []


# -- black boxes --------------------------------------------------------------


def test_blackbox_roundtrip_and_amortization(tmp_path):
    state_dir = str(tmp_path)
    ring = TimelineRecorder(capacity=64)
    box = BlackBox(state_dir, "decode-0", timeline=ring, every=8,
                   meta={"tier": "decode"})
    assert box.tick() is True            # first tick writes the baseline
    for i in range(7):
        ring.note("warmup", i=i)
        assert box.tick() is False       # amortized: under the budget
    ring.note("edge", i=7)
    assert box.tick() is True            # 8th append crosses it
    ring.note("fatal", trace="t-dead")
    assert box.tick(force=True) is True  # forced beats the budget
    assert box.flushes == 3
    assert not os.path.exists(box.path + ".tmp")   # atomic: no tmp left

    boxes = load_blackboxes(state_dir)
    assert len(boxes) == 1
    loaded = boxes[0]
    assert loaded["role"] == "decode-0"
    assert loaded["pid"] == os.getpid()
    assert loaded["meta"] == {"tier": "decode"}
    assert loaded["_path"] == blackbox_path(state_dir, "decode-0")
    kinds = [e["attrs"].get("trace") for e in loaded["timeline"]
             if e["kind"] == "note"]
    assert "t-dead" in kinds


def test_blackbox_rebind_resets_mark(tmp_path):
    ring_a = TimelineRecorder(capacity=8)
    for _ in range(5):
        ring_a.note("old")
    box = BlackBox(str(tmp_path), "prefill-0", timeline=ring_a, every=100)
    assert box.tick() is True
    assert box.tick() is False
    ring_b = TimelineRecorder(capacity=8)
    box.rebind(ring_b)
    assert box.tick() is True            # fresh ring: baseline again


def test_blackbox_rotation_preserves_dead_incarnation(tmp_path):
    """A respawned worker binds the same role/path; the dead
    incarnation's final checkpoint must survive as .prev.json and both
    must load (the postmortem reads the death, not the boot baseline)."""
    state_dir = str(tmp_path)
    dead_ring = TimelineRecorder(capacity=8)
    dead_ring.note("decode_op", trace="t-fatal")
    BlackBox(state_dir, "decode-0", timeline=dead_ring).flush()

    fresh = BlackBox(state_dir, "decode-0",
                     timeline=TimelineRecorder(capacity=8))
    fresh.flush()
    boxes = load_blackboxes(state_dir)
    assert [b["role"] for b in boxes] == ["decode-0", "decode-0"]
    traces = [
        e.get("attrs", {}).get("trace")
        for b in boxes for e in b["timeline"] if e["kind"] == "note"
    ]
    assert "t-fatal" in traces


def test_load_blackboxes_orders_and_skips_garbage(tmp_path):
    state_dir = str(tmp_path)
    for role in ("decode-0", "coordinator", "prefill-0"):
        BlackBox(state_dir, role, timeline=None).flush()
    with open(os.path.join(state_dir, "blackbox-squatter.json"), "w") as f:
        f.write("{not json")
    with open(os.path.join(state_dir, "unrelated.json"), "w") as f:
        json.dump({"timeline": []}, f)
    boxes = load_blackboxes(state_dir)
    assert [b["role"] for b in boxes] == \
        ["coordinator", "decode-0", "prefill-0"]
    assert load_blackboxes(os.path.join(state_dir, "missing")) == []


# -- postmortem reconstruction ------------------------------------------------


def _shift_ring(ring: TimelineRecorder, delta: float) -> None:
    """Move a recorder's events into another monotonic epoch — the
    rings all come from THIS process, but the scene fabricates three
    processes whose clocks disagree by the coordinator's offsets."""
    ring._ring = type(ring._ring)(
        ((entry[0], entry[1] + delta) + entry[2:] for entry in ring._ring),
        maxlen=ring._ring.maxlen,
    )


def _write_crash_scene(state_dir: str) -> None:
    """Fabricate the boxes a killed-mid-handoff run leaves behind."""
    coord_ring = TimelineRecorder(capacity=32)
    coord_ring.note("handoff_start", handoff_id="h9", trace="t-fatal")
    coord = BlackBox(state_dir, "coordinator", timeline=coord_ring,
                     meta={"clock_offsets": {
                         "prefill-0": {"offset_s": 10.0,
                                       "uncertainty_s": 0.001,
                                       "samples": 4, "accepted": 2},
                         "decode-0": {"offset_s": -3.0,
                                      "uncertainty_s": 0.001,
                                      "samples": 4, "accepted": 2},
                     }})
    prefill_ring = TimelineRecorder(capacity=32)
    prefill_ring.note("handoff_serialize", handoff_id="h9",
                      trace="t-fatal", bytes=1024)
    # local = remote + offset, so each worker's ring lives at
    # local − offset in its own epoch; the merge must undo this.
    _shift_ring(prefill_ring, -10.0)
    prefill = BlackBox(state_dir, "prefill-0", timeline=prefill_ring)
    decode_ring = TimelineRecorder(capacity=32)
    time.sleep(0.002)   # real wire time: serialize end < scatter start
    decode_ring.note("decode_op", handoff_id="h9", trace="t-fatal")
    decode_ring.note("handoff_scatter", handoff_id="h9", trace="t-fatal")
    decode_ring.admit(0, "t-fatal", 16)       # admitted, never retired
    _shift_ring(decode_ring, 3.0)
    decode = BlackBox(state_dir, "decode-0", timeline=decode_ring)
    # Decode dies FIRST (stalest checkpoint), survivors keep flushing.
    decode.flush()
    time.sleep(0.01)
    prefill.flush()
    coord.flush()


def test_postmortem_reconstructs_death(tmp_path, capsys):
    state_dir = str(tmp_path)
    _write_crash_scene(state_dir)
    boxes = load_blackboxes(state_dir)
    report = triage_report(boxes)
    assert "3 black box(es)" in report
    assert "likely first casualty: decode-0" in report
    assert "in-flight traces: t-fatal" in report

    trace = merged_perfetto(boxes)
    json.loads(json.dumps(trace))
    assert trace["otherData"]["clock_offsets"] == \
        {"prefill-0": 10.0, "decode-0": -3.0}
    roles = {b["role"] for b in trace["otherData"]["boxes"]}
    assert roles == {"coordinator", "prefill-0", "decode-0"}
    starts, ends = _arc_pair(trace)
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["ts"] <= ends[0]["ts"]

    out_path = os.path.join(state_dir, "merged.json")
    rc = postmortem_main([state_dir, "--out", out_path, "--last", "4"])
    assert rc == 0
    stdout = capsys.readouterr().out
    assert "t-fatal" in stdout
    with open(out_path) as f:
        assert json.load(f)["traceEvents"]


def test_postmortem_empty_dir_exits_2(tmp_path, capsys):
    assert postmortem_main([str(tmp_path)]) == 2
    assert "no black boxes" in capsys.readouterr().out


# -- live pool: trace propagation, merge, overhead gate -----------------------


def _config(**overrides) -> EngineConfig:
    base = dict(
        model="tiny-llama", dtype="float32", max_decode_slots=4,
        page_size=8, num_pages=128, max_seq_len=64,
        prefill_buckets=(16, 32), decode_block_steps=2,
        adaptive_block=False, max_new_tokens_cap=12,
        default_max_new_tokens=12, supervise=False,
        disagg_heartbeat_s=0.1, disagg_recovery_wait_s=10.0,
        blackbox_every=4,
    )
    base.update(overrides)
    return EngineConfig(**base)


def _run(pool, prompt: str, n: int = 10, **kw):
    request = GenRequest(prompt=prompt, max_new_tokens=n, **kw)
    pool.submit(request)
    tokens = []
    while True:
        kind, value = request.out.get(timeout=60)
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            return tokens, None, request
        else:
            return tokens, value, request


class _Stack:
    def __init__(self, cfg, decode_workers=1, prefill_workers=1,
                 state_dir=None):
        self.cfg = cfg
        self.workers = []
        for i in range(prefill_workers):
            self.workers.append(WorkerServer(
                cfg, tier=PREFILL, replica=i, seed=7,
                exit_mode="simulate", state_dir=state_dir,
            ).start())
        for i in range(decode_workers):
            self.workers.append(WorkerServer(
                cfg, tier=DECODE, replica=i, seed=7,
                exit_mode="simulate", state_dir=state_dir,
            ).start())
        self.pool = DisaggPool.create(
            cfg,
            workers=[(w.tier, ("127.0.0.1", w.port)) for w in self.workers],
            state_dir=state_dir,
        )

    def close(self):
        self.pool.shutdown()
        for worker in self.workers:
            worker.stop()


@pytest.fixture()
def stacks():
    opened = []

    def make(cfg=None, **kw) -> _Stack:
        stack = _Stack(cfg or _config(), **kw)
        opened.append(stack)
        return stack

    yield make
    for stack in opened:
        stack.close()


def _wait_for_clocks(pool, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(w.clock.offset is not None for w in pool.workers):
            return
        time.sleep(0.05)
    raise AssertionError("heartbeat never delivered a clock sample")


def test_trace_id_continuity_across_reroute(stacks):
    stack = stacks(decode_workers=2)
    _wait_for_clocks(stack.pool)
    faults.install("worker-exit=3@1:tier=decode:replica=0")
    trace = Span("gateway", trace_id="t-route")
    toks, err, req = _run(stack.pool, "kill test prompt", trace=trace)
    assert err is None and len(toks) == 10
    assert req.restarted is True
    # Coordinator notes: start/ack/abort all joined the SAME trace.
    notes = [e for e in stack.pool.timeline.events() if e["kind"] == "note"]
    by_kind = {}
    for event in notes:
        by_kind.setdefault(event["note_kind"], []).append(event["attrs"])
    for kind in ("handoff_start", "handoff_ack", "handoff_abort"):
        assert by_kind.get(kind), f"missing {kind} note"
        assert all(a.get("trace") == "t-route" for a in by_kind[kind]), kind
    # The abort and the retry share the request's handoff id.
    abort = by_kind["handoff_abort"][0]
    assert abort["handoff_id"] in {a.get("handoff_id")
                                   for a in by_kind["handoff_start"]}
    # Worker-side rings saw the same id at op intake.
    worker_notes = []
    for worker in stack.workers:
        timeline = getattr(worker.engine, "timeline", None)
        if timeline is not None:
            worker_notes += [e for e in timeline.events()
                             if e["kind"] == "note"]
    intake = [e["attrs"] for e in worker_notes
              if e["note_kind"] in ("prefill_op", "decode_op")]
    assert intake and all(a.get("trace") == "t-route" for a in intake)
    # Grafted spans: the surviving decode worker's subtree landed under
    # the gateway root, re-timed onto the coordinator clock.
    names = [c.name for c in trace.children]
    assert "handoff_ship" in names and "handoff_fetch" in names
    grafted = [c for c in trace.children if c.name.startswith("worker:")]
    assert grafted, f"no worker subtree grafted (children: {names})"
    child_names = {c.name for g in grafted for c in g.children}
    assert "handoff_deserialize" in child_names


def test_merged_perfetto_live_pool(stacks, tmp_path):
    stack = stacks(state_dir=str(tmp_path))
    _wait_for_clocks(stack.pool)
    trace_span = Span("gateway", trace_id="t-merge")
    toks, err, _ = _run(stack.pool, "hello disagg world", trace=trace_span)
    assert err is None and len(toks) == 10
    trace = stack.pool.merged_perfetto()
    json.loads(json.dumps(trace))
    process_rows = {
        e["args"]["name"]
        for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert process_rows == {"polykey coordinator", "polykey prefill-0",
                            "polykey decode-0"}
    starts, ends = _arc_pair(trace)
    assert starts and ends
    pair = {(s["id"]) for s in starts} & {(e["id"]) for e in ends}
    assert pair, "no matched serialize→scatter arc"
    for start in starts:
        end = next((e for e in ends if e["id"] == start["id"]), None)
        if end is not None:
            assert start["ts"] <= end["ts"], \
                "handoff arc runs backwards after clock alignment"
    # The coordinator's black box carried offsets for the postmortem.
    stack.pool.shutdown()
    boxes = load_blackboxes(str(tmp_path))
    roles = {b["role"] for b in boxes}
    assert {"coordinator", "prefill-0", "decode-0"} <= roles
    offline = merged_perfetto(boxes)
    assert offline["otherData"]["source"] == "postmortem"


def test_pool_signal_windows_and_snapshot(stacks):
    stack = stacks()
    for prompt in ("hello disagg world", "kill test prompt"):
        toks, err, _ = _run(stack.pool, prompt)
        assert err is None
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        windows = stack.pool.signal_windows()
        if windows and any(
            w["handoffs"]["ok"] >= 2 for w in windows.values()
        ):
            break
        time.sleep(0.05)
    windows = stack.pool.signal_windows()
    assert windows, "heartbeat never sampled the signal ring"
    label, window = next(iter(windows.items()))
    assert window["covered_s"] > 0
    assert window["handoffs"]["ok"] >= 2
    assert window["handoff_bytes"] > 0
    assert window["wire_bandwidth_bytes_per_s"] > 0
    assert window["handoff_ms_count"] >= 2
    assert window["handoff_ms_p95"] >= window["handoff_ms_p50"] > 0
    assert window["tier_faults"] == {PREFILL: 0, DECODE: 0}
    assert window["fault_rate_per_min"] == 0
    snap = signals_snapshot(stack.pool)
    assert snap["replicas"] == {}          # engines live out of process
    assert snap["pool"] == windows or snap["pool"].keys() == windows.keys()
    assert set(snap["clock_offsets"]) == {"prefill-0", "decode-0"}


def test_blackbox_overhead_gate(stacks, tmp_path):
    """Checkpointing must be observability-only: greedy streams and the
    scheduler's lane shape are identical with black boxes on vs off."""
    on = stacks(cfg=_config(blackbox_every=2), state_dir=str(tmp_path))
    off = stacks(cfg=_config(blackbox_every=0))
    streams_on, streams_off = {}, {}
    for prompt in ("hello disagg world", "kill test prompt"):
        toks, err, _ = _run(on.pool, prompt)
        assert err is None
        streams_on[prompt] = toks
        toks, err, _ = _run(off.pool, prompt)
        assert err is None
        streams_off[prompt] = toks
    assert streams_on == streams_off
    lanes_on = [w.engine.stats().get("avg_lanes")
                for w in on.workers if w.tier == DECODE]
    lanes_off = [w.engine.stats().get("avg_lanes")
                 for w in off.workers if w.tier == DECODE]
    assert lanes_on == lanes_off
    # And the on-stack really did checkpoint.
    assert load_blackboxes(str(tmp_path))


def test_postmortem_after_mid_stream_death(stacks, tmp_path):
    """The acceptance path: kill a decode worker mid-stream, then
    reconstruct its final ring — fatal trace id included — from the
    black box alone."""
    state_dir = str(tmp_path)
    stack = stacks(cfg=_config(blackbox_every=2), decode_workers=2,
                   state_dir=state_dir)
    _wait_for_clocks(stack.pool)
    faults.install("worker-exit=3@1:tier=decode:replica=0")
    trace = Span("gateway", trace_id="t-victim")
    toks, err, req = _run(stack.pool, "kill test prompt", trace=trace)
    assert err is None and len(toks) == 10 and req.restarted
    victim_box = blackbox_path(state_dir, "decode-0")
    assert os.path.exists(victim_box), \
        "the victim's box must exist (forced flush at op intake)"
    with open(victim_box) as f:
        box = json.load(f)
    fatal = [e for e in box["timeline"]
             if e["kind"] == "note" and e["note_kind"] == "decode_op"]
    assert fatal and fatal[-1]["attrs"]["trace"] == "t-victim"
    report = triage_report(load_blackboxes(state_dir))
    assert "t-victim" in report
    # merged_timelines falls back to the corpse's box for dead workers.
    merged = dict(
        (label, events)
        for _, label, events in stack.pool.merged_timelines()
    )
    assert "decode-0" in merged
    assert any(
        e.get("note_kind") == "decode_op"
        and e.get("attrs", {}).get("trace") == "t-victim"
        for e in merged["decode-0"] if e["kind"] == "note"
    )
