"""Engine tests: continuous batching, streaming, cancellation, stats, and the
full gRPC stack with the TPU service mounted (tiny model, CPU device).

This is the concurrency-stress tier SURVEY.md §4 prescribes in place of Go's
race detector: many concurrent clients hammering the batcher with assertion
checks on every response.
"""

import queue
import threading
import time

import grpc
import numpy as np
import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.gateway import server as gateway_server
from polykey_tpu.gateway.jsonlog import Logger
from polykey_tpu.gateway.tpu_service import TpuService
from polykey_tpu.proto import polykey_v2_pb2 as pk
from polykey_tpu.proto.polykey_v2_grpc import PolykeyServiceStub

import io

TEST_CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    num_pages=64,
    max_seq_len=64,
    prefill_buckets=(16, 32),
    max_new_tokens_cap=32,
    default_max_new_tokens=8,
)


@pytest.fixture(scope="module")
def engine():
    eng = InferenceEngine(TEST_CONFIG)
    yield eng
    eng.shutdown()


def _collect(request: GenRequest, timeout=30.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def test_single_request(engine):
    request = GenRequest(prompt="hello", max_new_tokens=5)
    engine.submit(request)
    tokens, done, error = _collect(request)
    assert error is None
    assert done is not None
    assert len(tokens) == done.completion_tokens <= 5
    assert done.prompt_tokens == len(engine.tokenizer.encode("hello"))
    assert done.ttft_ms > 0


def test_greedy_reproducible(engine):
    outs = []
    for _ in range(2):
        request = GenRequest(prompt="abc", max_new_tokens=6, temperature=0.0)
        engine.submit(request)
        tokens, done, error = _collect(request)
        assert error is None
        outs.append(tokens)
    assert outs[0] == outs[1]


def test_concurrent_requests_batched(engine):
    """More requests than slots: all must complete, slots recycled."""
    requests = [
        GenRequest(prompt=f"prompt {i}", max_new_tokens=6, temperature=0.5)
        for i in range(10)
    ]
    for request in requests:
        engine.submit(request)
    results = [_collect(request) for request in requests]
    for tokens, done, error in results:
        assert error is None
        assert done is not None
        assert len(tokens) >= 1
    # All pages back in the pool afterwards.
    assert engine.allocator.num_free == TEST_CONFIG.num_pages - 1
    assert not engine.busy


def test_batched_greedy_matches_solo(engine):
    """Continuous batching must not change greedy output: run a probe alone,
    then again while 3 other requests occupy the batch."""
    probe_prompt = "determinism probe"
    solo = GenRequest(prompt=probe_prompt, max_new_tokens=6)
    engine.submit(solo)
    solo_tokens, _, _ = _collect(solo)

    noise = [
        GenRequest(prompt=f"noise {i}", max_new_tokens=12, temperature=1.0)
        for i in range(3)
    ]
    probe = GenRequest(prompt=probe_prompt, max_new_tokens=6)
    for request in noise:
        engine.submit(request)
    engine.submit(probe)
    probe_tokens, _, probe_err = _collect(probe)
    for request in noise:
        _collect(request)
    assert probe_err is None
    assert probe_tokens == solo_tokens


def test_burst_admission_matches_solo(engine):
    """A probe admitted inside a same-bucket burst (batched prefill group)
    must produce the same greedy stream as when admitted alone."""
    probe_prompt = "burst determinism"
    solo = GenRequest(prompt=probe_prompt, max_new_tokens=6)
    engine.submit(solo)
    solo_tokens, _, _ = _collect(solo)

    burst = [GenRequest(prompt=f"burst noise {i}", max_new_tokens=6)
             for i in range(3)]
    probe = GenRequest(prompt=probe_prompt, max_new_tokens=6)
    for r in burst + [probe]:
        engine.submit(r)
    probe_tokens, _, probe_err = _collect(probe)
    for r in burst:
        _collect(r)
    assert probe_err is None
    assert probe_tokens == solo_tokens


def test_decode_block_steps_equivalence():
    """Blocked decode (K steps per dispatch, device-side EOS/budget stop)
    must be a pure batching of the K=1 step loop: identical greedy tokens,
    including for requests whose budget is not a multiple of K."""
    import dataclasses

    outs = {}
    for k in (1, 8):
        eng = InferenceEngine(
            dataclasses.replace(TEST_CONFIG, decode_block_steps=k)
        )
        try:
            reqs = [
                GenRequest(prompt=p, max_new_tokens=n)
                for p, n in (("block probe", 11), ("x", 3), ("longer one", 8))
            ]
            for r in reqs:
                eng.submit(r)
            outs[k] = [_collect(r) for r in reqs]
        finally:
            eng.shutdown()
    for (t1, d1, e1), (t8, d8, e8) in zip(outs[1], outs[8]):
        assert e1 is None and e8 is None
        assert t1 == t8
        assert d1.completion_tokens == d8.completion_tokens


def test_compile_warmup_engine_serves_identically():
    """compile_warmup pre-runs the jitted shapes against the garbage page
    in __init__; the warmed engine must serve the same greedy streams."""
    import dataclasses

    ref_eng = InferenceEngine(TEST_CONFIG)
    try:
        r = GenRequest(prompt="warmup probe", max_new_tokens=6)
        ref_eng.submit(r)
        ref, _, _ = _collect(r)
    finally:
        ref_eng.shutdown()

    warm_eng = InferenceEngine(
        dataclasses.replace(TEST_CONFIG, compile_warmup=True)
    )
    try:
        r = GenRequest(prompt="warmup probe", max_new_tokens=6)
        warm_eng.submit(r)
        out, done, error = _collect(r)
        assert error is None
        assert out == ref
    finally:
        warm_eng.shutdown()


def test_stale_block_tokens_never_reach_new_occupant():
    """Lookahead safety net: a block dispatched while request A held slot 0
    must deliver nothing once the slot belongs to request B — the
    per-block request-identity snapshot (engine._snapshot_requests) is the
    only guard on this path, since B can be active with A's block still
    unprocessed only through host-side transitions (cancel + re-admit).
    White-box: the engine loop is stopped and _process_step driven
    directly with a forged stale block."""
    import numpy as np

    from polykey_tpu.engine.engine import _Slot

    eng = InferenceEngine(TEST_CONFIG)
    eng.shutdown()  # stop the loop; we drive internals directly

    req_a = GenRequest(prompt="A")          # the evicted occupant
    req_b = GenRequest(prompt="B")          # the new occupant
    slot_b = _Slot(request=req_b, pages=[], position_cap=10)
    slot_b.generated = 1
    eng._slots[0] = slot_b
    eng._active[0] = True
    eng._seq_lens[0] = 3

    B, K = TEST_CONFIG.max_decode_slots, TEST_CONFIG.decode_block_steps
    packed = np.full((K, B), 7, dtype=np.int32)   # every lane "emitted"
    reqs = [req_a] + [None] * (B - 1)       # snapshot from A's dispatch
    eng._process_step(("plain", packed, reqs))

    assert req_b.out.empty()                # B got nothing from A's block
    assert req_a.out.empty()                # A is gone; tokens are dropped
    assert slot_b.generated == 1            # no bookkeeping drift either


def test_lookahead_depth_greedy_equality():
    """The lookahead pipeline is a scheduling change only: greedy output at
    depth 4 (and at a block size that straddles request boundaries) must
    equal depth-1 token-at-a-time output, across overlapping admissions."""
    import dataclasses

    prompts = [f"pipeline prompt {i}" for i in range(6)]

    def run(depth, block):
        cfg = dataclasses.replace(
            TEST_CONFIG, lookahead_blocks=depth, decode_block_steps=block
        )
        eng = InferenceEngine(cfg)
        try:
            reqs = [GenRequest(prompt=p, max_new_tokens=7) for p in prompts]
            for r in reqs:
                eng.submit(r)
            outs = []
            for r in reqs:
                tokens, done, error = _collect(r)
                assert error is None and done is not None
                outs.append(tokens)
            return outs
        finally:
            eng.shutdown()

    assert run(4, 3) == run(1, 1)


def test_stop_sequences(monkeypatch):
    """`stop` cuts generation BEFORE the earliest match, never emits the
    stop text (even when it spans delta boundaries — every byte-tokenizer
    delta is one char, so any multi-char stop spans), and cancels the
    engine request. Unary and streaming agree.

    Uses an ASCII-vocab model variant (vocab 96 → every generated id
    renders one byte) so greedy output is dense text; tiny-llama's 512
    vocab mostly lands outside the byte tokenizer's range."""
    import dataclasses

    from google.protobuf import struct_pb2

    from polykey_tpu.gateway.tpu_service import TpuService
    from polykey_tpu.models.config import MODEL_REGISTRY, TINY_LLAMA

    # monkeypatch (not setdefault) so the registry entry is removed on
    # teardown — registry contents must not depend on test order.
    monkeypatch.setitem(
        MODEL_REGISTRY,
        "tiny-llama-ascii",
        dataclasses.replace(TINY_LLAMA, name="tiny-llama-ascii", vocab_size=96),
    )
    eng = InferenceEngine(
        dataclasses.replace(TEST_CONFIG, model="tiny-llama-ascii")
    )
    service = TpuService(eng)
    try:
        def run(stop=None, stream=False):
            params = struct_pb2.Struct()
            d = {"prompt": "stop test prompt", "max_tokens": 24}
            if stop is not None:
                d["stop"] = stop
            params.update(d)
            if stream:
                chunks = list(
                    service.execute_tool_stream(
                        "llm_generate", params, None, None
                    )
                )
                return "".join(c.delta for c in chunks)
            return service.execute_tool(
                "llm_generate", params, None, None
            ).string_output

        full = run()
        assert len(full) >= 6, repr(full)
        stop = full[3:6]            # guaranteed mid-stream match
        cut = run(stop=stop)
        assert cut == full[: full.index(stop)]
        assert stop not in cut
        assert run(stop=stop, stream=True) == cut
        # List form; a never-matching stop leaves the output unchanged.
        assert run(stop=["@@never@@", stop]) == cut
        assert run(stop="@@never@@") == full
        # Invalid stop types are rejected.
        import pytest as _pytest

        with _pytest.raises(Exception):
            run(stop=[""])
    finally:
        eng.shutdown()


def test_seeded_sampling_batch_independent():
    """A seeded sampled request must produce an identical stream no matter
    what else is in the batch, which engine geometry serves it, or how
    scheduling interleaves — every draw is keyed by (request seed, token
    position), not by a shared RNG chain. Different seeds must diverge."""
    import dataclasses

    def run(cfg, companions):
        eng = InferenceEngine(cfg)
        try:
            target = GenRequest(prompt="seeded stream", max_new_tokens=10,
                                temperature=1.0, top_p=0.9, seed=42)
            others = [
                GenRequest(prompt=f"noise {i}", max_new_tokens=8,
                           temperature=0.7, seed=100 + i)
                for i in range(companions)
            ]
            for r in [*others[:companions // 2], target,
                      *others[companions // 2:]]:
                eng.submit(r)
            result = None
            for r in [target, *others]:
                tokens, done, error = _collect(r)
                assert error is None and done is not None
                if r is target:
                    result = tokens
            return result
        finally:
            eng.shutdown()

    alone = run(TEST_CONFIG, 0)
    crowded = run(TEST_CONFIG, 3)
    other_geometry = run(
        dataclasses.replace(
            TEST_CONFIG, max_decode_slots=2, decode_block_steps=2,
            lookahead_blocks=3,
        ),
        1,
    )
    assert alone == crowded == other_geometry
    assert len(alone) > 1

    different_seed = None
    eng = InferenceEngine(TEST_CONFIG)
    try:
        r = GenRequest(prompt="seeded stream", max_new_tokens=10,
                       temperature=1.0, top_p=0.9, seed=43)
        eng.submit(r)
        different_seed, done, error = _collect(r)
        assert error is None
    finally:
        eng.shutdown()
    assert different_seed != alone


def test_cancellation_frees_slot(engine):
    request = GenRequest(prompt="cancel me", max_new_tokens=32, temperature=1.0)
    engine.submit(request)
    request.out.get(timeout=30)  # wait for the first token
    request.cancelled.set()
    deadline = time.monotonic() + 10
    while engine.busy and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not engine.busy
    assert engine.allocator.num_free == TEST_CONFIG.num_pages - 1


def test_pool_exhaustion_backpressure():
    """A pool that fits one request at a time still completes all requests."""
    config = EngineConfig(
        model="tiny-llama", tokenizer="byte", dtype="float32",
        max_decode_slots=2, page_size=8, num_pages=4, max_seq_len=32,
        prefill_buckets=(16,), max_new_tokens_cap=8, default_max_new_tokens=4,
    )
    eng = InferenceEngine(config)
    try:
        requests = [GenRequest(prompt=f"req {i}", max_new_tokens=4) for i in range(4)]
        for request in requests:
            eng.submit(request)
        for request in requests:
            tokens, done, error = _collect(request)
            assert error is None, error
            assert done is not None
        assert eng.allocator.num_free == config.num_pages - 1
    finally:
        eng.shutdown()


def test_stats_shape(engine):
    stats = engine.stats()
    for key in ("requests_admitted", "tokens_generated", "slots_busy",
                "pages_free", "model", "tokens_per_sec"):
        assert key in stats
    assert stats["model"] == "tiny-llama"


# -- full-stack gRPC tests --------------------------------------------------


@pytest.fixture(scope="module")
def grpc_stack(engine):
    logger = Logger(stream=io.StringIO(), level="debug")
    service = TpuService(engine)
    server, health, port = gateway_server.build_server(
        service, logger, address="127.0.0.1:0"
    )
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield PolykeyServiceStub(channel)
    channel.close()
    server.stop(grace=None)


def _llm_request(prompt="hi there", **params):
    request = pk.ExecuteToolRequest(tool_name="llm_generate")
    request.parameters.update({"prompt": prompt, "max_tokens": 6, **params})
    return request


def test_grpc_llm_generate_unary(grpc_stack):
    resp = grpc_stack.ExecuteTool(_llm_request(), timeout=60)
    assert resp.status.code == 200
    assert resp.WhichOneof("output") == "string_output"


def test_grpc_llm_generate_stream(grpc_stack):
    chunks = list(grpc_stack.ExecuteToolStream(_llm_request(), timeout=60))
    assert chunks[-1].final
    assert chunks[-1].status.code == 200
    usage = chunks[-1].usage
    assert usage.completion_tokens >= 1
    assert usage.ttft_ms > 0
    assert usage.prompt_tokens == len("hi there".encode()) + 1  # bytes + BOS


def test_grpc_mock_tools_still_work(grpc_stack):
    resp = grpc_stack.ExecuteTool(
        pk.ExecuteToolRequest(tool_name="example_tool"), timeout=30
    )
    assert resp.status.code == 200
    assert resp.string_output.startswith("Mock execution of example_tool")
    resp = grpc_stack.ExecuteTool(
        pk.ExecuteToolRequest(tool_name="nope"), timeout=30
    )
    assert resp.string_output == "Unknown tool: nope"


def test_grpc_engine_stats_tool(grpc_stack):
    resp = grpc_stack.ExecuteTool(
        pk.ExecuteToolRequest(tool_name="engine_stats"), timeout=30
    )
    assert resp.WhichOneof("output") == "struct_output"
    assert dict(resp.struct_output)["model"] == "tiny-llama"


def test_grpc_missing_prompt_errors(grpc_stack):
    request = pk.ExecuteToolRequest(tool_name="llm_generate")
    request.parameters.update({"max_tokens": 4})
    with pytest.raises(grpc.RpcError) as err:
        grpc_stack.ExecuteTool(request, timeout=30)
    assert "prompt" in err.value.details()


def test_grpc_concurrent_streams(grpc_stack):
    """Concurrent streaming clients — the race-detector analog."""
    errors: list = []

    def worker(i):
        try:
            chunks = list(
                grpc_stack.ExecuteToolStream(
                    _llm_request(prompt=f"client {i}", temperature=0.8),
                    timeout=120,
                )
            )
            assert chunks[-1].final
            assert chunks[-1].usage.completion_tokens >= 1
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors


def test_traced_request_span_tree(engine):
    """End-to-end tracing acceptance: a streaming generation through an
    obs-wired stack leaves a span tree in the flight recorder whose
    queue/prefill/decode/detokenize phases account for the request's
    wall time, retrievable via the engine_stats tool."""
    from polykey_tpu.obs import Observability

    obs = Observability()
    service = TpuService(engine, obs=obs)
    logger = Logger(stream=io.StringIO(), level="debug")
    server, _, port = gateway_server.build_server(
        service, logger, address="127.0.0.1:0", obs=obs
    )
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        stub = PolykeyServiceStub(channel)
        request = pk.ExecuteToolRequest(tool_name="llm_generate")
        request.parameters.update({"prompt": "trace this", "max_tokens": 8})
        chunks = list(stub.ExecuteToolStream(request, timeout=120))
        assert chunks[-1].final

        resp = stub.ExecuteTool(
            pk.ExecuteToolRequest(tool_name="engine_stats"), timeout=30
        )
        stats = dict(resp.struct_output)
        assert "last_trace" in stats
        trace = dict(stats["last_trace"])
        assert trace["attrs"]["tool"] == "llm_generate"
        children = {c["name"]: dict(c) for c in trace["children"]}
        for phase in ("queue_wait", "prefill", "decode", "detokenize"):
            assert phase in children, f"missing {phase} span"
        # decode carries per-block children with token counts.
        blocks = children["decode"].get("children", [])
        assert blocks and sum(
            int(b["attrs"]["tokens"]) for b in blocks
        ) >= chunks[-1].usage.completion_tokens - 1
        # The engine phases partition the request's wall time: their sum
        # must land within the RPC's root duration, close to it (slack
        # for RPC framing + scheduler jitter on busy CI hosts).
        phase_ms = sum(
            children[p]["duration_ms"]
            for p in ("queue_wait", "prefill", "decode", "detokenize")
        )
        assert phase_ms <= trace["duration_ms"] * 1.05
        assert phase_ms >= trace["duration_ms"] * 0.5

        # TTFT/ITL percentiles (histogram-backed) surface in the stats.
        assert stats["ttft_ms_p50"] > 0
        assert stats["ttft_ms_p99"] >= stats["ttft_ms_p50"]

        # metrics_text view renders the Prometheus page over gRPC.
        request = pk.ExecuteToolRequest(tool_name="engine_stats")
        request.parameters.update({"view": "metrics_text"})
        resp = stub.ExecuteTool(request, timeout=30)
        page = resp.string_output
        for family in ("polykey_ttft_ms_bucket", "polykey_decode_tokens_total",
                       "polykey_active_requests", "polykey_engine_up",
                       "polykey_watchdog_stalls_total"):
            assert family in page, f"missing {family} in exposition"

        # trace view dumps the recorder.
        request = pk.ExecuteToolRequest(tool_name="engine_stats")
        request.parameters.update({"view": "trace"})
        resp = stub.ExecuteTool(request, timeout=30)
        dump = dict(resp.struct_output)
        assert any(
            dict(dict(t).get("attrs") or {}).get("tool") == "llm_generate"
            for t in dump["traces"]
        )
    finally:
        channel.close()
        server.stop(grace=None)


def test_quantized_engine_serves():
    """POLYKEY_QUANTIZE path: int8 weight-only engine generates end to end
    and stays deterministic (greedy)."""
    import dataclasses

    eng = InferenceEngine(dataclasses.replace(TEST_CONFIG, quantize=True))
    try:
        r1 = GenRequest(prompt="hello", max_new_tokens=8, temperature=0.0)
        r2 = GenRequest(prompt="hello", max_new_tokens=8, temperature=0.0)
        eng.submit(r1)
        t1, d1, e1 = _collect(r1)
        eng.submit(r2)
        t2, d2, e2 = _collect(r2)
        assert e1 is None and e2 is None
        assert d1 is not None and d2 is not None
        assert t1 == t2 and len(t1) == 8
    finally:
        eng.shutdown()


def test_parse_seed_rejects_nonfinite_and_unsafe_floats():
    """JSON Struct numbers are doubles: NaN/Infinity and integers beyond
    2**53 must all raise the same descriptive ValueError (not
    OverflowError), and safe integer-valued floats must parse."""
    import pytest

    from polykey_tpu.gateway.tpu_service import TpuService

    parse = TpuService._parse_seed
    assert parse({}) is None
    assert parse({"seed": 42}) == 42
    assert parse({"seed": 42.0}) == 42
    for bad in (float("nan"), float("inf"), float("-inf"),
                1.5, float(2 ** 53 + 2)):
        with pytest.raises(ValueError, match="seed"):
            parse({"seed": bad})


def test_compile_warmup_covers_sampled_variants():
    """greedy is a batch-keyed static argname on both prefill and the
    decode block, so warmup must pre-compile the greedy=False variants
    too — the first sampled request must not trigger any new compile."""
    import dataclasses

    # Unique shape key (slots/buckets used by no other test): jax.jit
    # caches are shared across engine instances with equal jit params, so
    # a shared shape would let earlier sampled-request tests pre-populate
    # the entries and this test would pass even with warmup broken.
    eng = InferenceEngine(
        dataclasses.replace(
            TEST_CONFIG, compile_warmup=True,
            max_decode_slots=5, prefill_buckets=(24,),
        )
    )
    try:
        n_prefill = eng._jit_prefill._cache_size()
        n_decode = eng._jit_decode._cache_size()
        r = GenRequest(
            prompt="sampled warm probe", max_new_tokens=8,
            temperature=0.9, top_p=0.8, seed=11,
        )
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None and done is not None and tokens
        assert eng._jit_prefill._cache_size() == n_prefill
        assert eng._jit_decode._cache_size() == n_decode
    finally:
        eng.shutdown()


def test_compile_warmup_greedy_only_mode():
    """warm_sampled_variants=False (the greedy-only benchmark mode) must
    still fully pre-compile the greedy path: a greedy request triggers no
    new compile. (No cross-engine cache-size comparison here — jax.jit
    wrappers over the same function with equal jit params SHARE the
    underlying cache across engine instances, so only same-engine deltas
    are meaningful.)"""
    import dataclasses

    eng = InferenceEngine(
        dataclasses.replace(
            TEST_CONFIG, compile_warmup=True, warm_sampled_variants=False,
            # Unique shape key — see test_compile_warmup_covers_sampled_variants.
            max_decode_slots=6, prefill_buckets=(40,),
        )
    )
    try:
        n_prefill = eng._jit_prefill._cache_size()
        n_decode = eng._jit_decode._cache_size()
        r = GenRequest(prompt="greedy only probe", max_new_tokens=8)
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None and done is not None and tokens
        assert eng._jit_prefill._cache_size() == n_prefill
        assert eng._jit_decode._cache_size() == n_decode
    finally:
        eng.shutdown()


def test_adaptive_block_solo_vs_loaded():
    """Load-adaptive blocking: a lone stream dispatches the small solo
    block (max(1, K//8)); concurrent streams dispatch the full K. Output
    is identical to the static-block engine either way."""
    import dataclasses

    cfg = dataclasses.replace(TEST_CONFIG, decode_block_steps=8)
    static_cfg = dataclasses.replace(cfg, adaptive_block=False)

    def run_solo(config):
        import os as _os

        prior = _os.environ.get("POLYKEY_LOOP_TRACE")
        _os.environ["POLYKEY_LOOP_TRACE"] = "1"
        try:
            eng = InferenceEngine(config)
        finally:
            if prior is None:
                _os.environ.pop("POLYKEY_LOOP_TRACE", None)
            else:
                _os.environ["POLYKEY_LOOP_TRACE"] = prior
        try:
            r = GenRequest(prompt="adaptive probe", max_new_tokens=12)
            eng.submit(r)
            tokens, done, error = _collect(r)
            assert error is None and done is not None
            acc = eng._trace_acc or {}
            return (tokens, eng._last_dispatch_steps, eng._depth_target,
                    acc.get("max_depth", 0))
        finally:
            eng.shutdown()

    solo_tokens, solo_k, solo_tail_depth, solo_max = run_solo(cfg)
    static_tokens, static_k, static_tail_depth, static_max = run_solo(
        static_cfg)
    assert solo_k == 1 and static_k == 8
    assert solo_tokens == static_tokens
    # Constant LOOKAHEAD steps MID-STREAM: shrinking K deepens the
    # pipeline so the queued-ahead work keeps covering the roundtrip —
    # 1 + (depth-1) x (K/steps), i.e. 1+8=9 at K=1; only the lookahead
    # portion scales, so depth 1 stays exactly synchronous (the
    # escape-hatch contract test_dispatch_pipeline pins). Bounded by the
    # stream's remaining budget (12 new tokens -> ~12 blocks at K=1).
    assert solo_max >= 1 + (cfg.lookahead_blocks - 1) * 8, solo_max
    assert solo_max <= 1 + (cfg.lookahead_blocks - 1) * 8
    # Tail cap: in-flight work never exceeds what active streams still
    # need — the final dispatches shrink to one block, so stream tails
    # don't leave ~lookahead x K steps of dead full-batch work queued in
    # front of the next arrival's prefill.
    assert solo_tail_depth == 1, solo_tail_depth
    assert static_tail_depth == 1, static_tail_depth
    assert static_max <= cfg.lookahead_blocks

    # Under load (>1 active stream) the adaptive engine uses the full K.
    eng = InferenceEngine(cfg)
    try:
        reqs = [GenRequest(prompt=f"load {i}", max_new_tokens=12)
                for i in range(3)]
        for r in reqs:
            eng.submit(r)
        outs = [_collect(r) for r in reqs]
        assert all(e is None for _, _, e in outs)
        assert eng._last_dispatch_steps == 8
    finally:
        eng.shutdown()


def test_int4_engine_serves():
    """POLYKEY_QUANTIZE=int4 path: group-wise int4 weight-only engine
    generates end to end and stays deterministic (greedy)."""
    import dataclasses

    eng = InferenceEngine(
        dataclasses.replace(TEST_CONFIG, quantize=True, quantize_bits=4)
    )
    try:
        r1 = GenRequest(prompt="hello", max_new_tokens=8, temperature=0.0)
        r2 = GenRequest(prompt="hello", max_new_tokens=8, temperature=0.0)
        eng.submit(r1)
        t1, d1, e1 = _collect(r1)
        eng.submit(r2)
        t2, d2, e2 = _collect(r2)
        assert e1 is None and e2 is None
        assert d1 is not None and d2 is not None
        assert t1 == t2 and len(t1) == 8
    finally:
        eng.shutdown()


def test_top_k_one_is_greedy_end_to_end():
    """top_k=1 at temperature 1.0 must reproduce the greedy stream
    exactly — the sampler's rank mask leaves only the argmax."""
    import dataclasses

    eng = InferenceEngine(TEST_CONFIG)
    try:
        g = GenRequest(prompt="topk greedy probe", max_new_tokens=8)
        eng.submit(g)
        greedy_tokens, _, _ = _collect(g)

        r = GenRequest(prompt="topk greedy probe", max_new_tokens=8,
                       temperature=1.0, top_k=1, seed=9)
        eng.submit(r)
        tokens, done, error = _collect(r)
        assert error is None and done is not None
        assert tokens == greedy_tokens
    finally:
        eng.shutdown()


def test_top_k_seeded_reproducible():
    """Same (prompt, seed, top_k) → same stream, and a different top_k
    changes the distribution's support (k=1 vs unrestricted differ for
    this seed)."""
    eng = InferenceEngine(TEST_CONFIG)
    try:
        def run(top_k):
            r = GenRequest(prompt="topk seed probe", max_new_tokens=10,
                           temperature=1.2, top_k=top_k, seed=123)
            eng.submit(r)
            tokens, done, error = _collect(r)
            assert error is None and done is not None
            return tokens
        a, b = run(4), run(4)
        assert a == b
        assert run(1) != a or run(0) != a
    finally:
        eng.shutdown()


def test_parse_top_k_validation():
    from polykey_tpu.gateway.tpu_service import TpuService

    parse = TpuService._parse_top_k
    assert parse({}) == 0
    assert parse({"top_k": 5}) == 5
    assert parse({"top_k": 5.0}) == 5
    for bad in (-1, 1.5, float("nan"), float("inf")):
        with pytest.raises(ValueError, match="top_k"):
            parse({"top_k": bad})


def test_top_k_clamps_to_candidate_width():
    """With the top-k prefilter on (top_p_candidates=C), a wider top_k
    clamps to C at admission — the sampled paths only ever see the top-C
    logits, and the clamp makes that contract explicit instead of a
    silent sampler property."""
    import dataclasses

    eng = InferenceEngine(
        dataclasses.replace(TEST_CONFIG, top_p_candidates=8)
    )
    try:
        r = GenRequest(prompt="x", top_k=100)
        assert eng._eff_top_k(r) == 8
        assert eng._eff_top_k(GenRequest(prompt="x", top_k=3)) == 3
        assert eng._eff_top_k(GenRequest(prompt="x", top_k=0)) == 0
        # And the clamped request still serves.
        req = GenRequest(prompt="clamped topk", max_new_tokens=6,
                         temperature=1.0, top_k=100, seed=2)
        eng.submit(req)
        tokens, done, error = _collect(req)
        assert error is None and done is not None and tokens
    finally:
        eng.shutdown()


def test_prequantized_moe_engine_serves():
    """Bench phase E's exact path: a PRE-quantized int8 Mixtral-family
    tree handed to the engine (quantize=False — params arrive quantized,
    like the 8B/9B bench phases) serves greedily and matches the engine
    that quantizes the same weights itself."""
    import dataclasses

    import jax

    from polykey_tpu.models.config import get_config
    from polykey_tpu.models.quant import quantize_params
    from polykey_tpu.models.transformer import init_params

    cfg = dataclasses.replace(TEST_CONFIG, model="tiny-mixtral")
    mc = get_config("tiny-mixtral")
    fp = init_params(jax.random.PRNGKey(3), mc, "float32")
    pre = quantize_params(fp, mc, bits=8)

    def serve(config, params):
        eng = InferenceEngine(config, params=params)
        try:
            r = GenRequest(prompt="hello moe", max_new_tokens=8,
                           temperature=0.0)
            eng.submit(r)
            toks, done, err = _collect(r)
            assert err is None and done is not None
            return toks
        finally:
            eng.shutdown()

    got = serve(cfg, pre)
    want = serve(dataclasses.replace(cfg, quantize=True), fp)
    assert got == want and len(got) == 8


def test_admission_keeps_slots_occupied():
    """Occupancy regression gate for the admission policy: under a
    saturated closed loop (client queue deeper than the slot count) the
    average live-lane count per dispatched block must approach the slot
    count. The old one-admission-per-iteration policy equilibrated at
    ~max_new/decode_block_steps lanes (measured 5/32 on hardware —
    PERF.md r03); this pins the fix."""
    import threading

    cfg = EngineConfig(
        model="tiny-llama",
        tokenizer="byte",
        dtype="float32",
        max_decode_slots=8,
        page_size=8,
        num_pages=512,
        max_seq_len=128,
        prefill_buckets=(32,),
        max_new_tokens_cap=64,
        decode_block_steps=8,
        lookahead_blocks=2,
    )
    import os as _os

    # The engine latches the trace flag at CONSTRUCTION (engine.__init__
    # sets _trace_acc), so popping right after the constructor returns
    # cannot race the engine thread.
    _os.environ["POLYKEY_LOOP_TRACE"] = "1"
    try:
        engine = InferenceEngine(cfg)
    finally:
        _os.environ.pop("POLYKEY_LOOP_TRACE", None)
    try:
        sem = threading.Semaphore(cfg.max_decode_slots * 2)
        done = threading.Semaphore(0)

        def drain(r):
            try:
                while r.out.get(timeout=120.0)[0] == "token":
                    pass
            finally:
                sem.release()
                done.release()

        n_req = 48
        for _ in range(n_req):
            sem.acquire()
            r = GenRequest(prompt="occupancy", max_new_tokens=64)
            engine.submit(r)
            threading.Thread(target=drain, args=(r,), daemon=True).start()
        for _ in range(n_req):
            assert done.acquire(timeout=120.0)

        acc = engine._trace_acc or {}
        blocks = acc.get("blocks", 0)
        assert blocks > 0
        avg_lanes = acc.get("disp_lanes", 0) / blocks
        # Ramp/tail blocks drag the average below the slot count; 60% is
        # comfortably above the broken policy's ~max_new/K = 8... which
        # equals the slot count here, so ALSO bound total blocks: the
        # broken policy needs ~n_req extra admission-starved blocks.
        assert avg_lanes >= cfg.max_decode_slots * 0.6, avg_lanes
        ideal = n_req * 64 / cfg.max_decode_slots / cfg.decode_block_steps
        assert blocks <= ideal * 2.5, (blocks, ideal)
    finally:
        engine.shutdown()


def test_int8_kv_engine_serves():
    """EngineConfig.kv_dtype='int8': quantized KV pools (+ bf16 scale
    pools) through admission, batched prefill, blocked decode, and
    retirement — all requests complete with the full token budget."""
    cfg = EngineConfig(
        model="tiny-llama",
        tokenizer="byte",
        dtype="float32",
        kv_dtype="int8",
        max_decode_slots=4,
        page_size=8,
        num_pages=128,
        max_seq_len=64,
        prefill_buckets=(16, 32),
        max_new_tokens_cap=32,
    )
    import jax.numpy as jnp

    engine = InferenceEngine(cfg)
    try:
        assert engine.paged.quantized
        assert engine.paged.k.dtype == jnp.int8
        assert engine.paged.ks.dtype == jnp.bfloat16
        reqs = [GenRequest(prompt=f"int8 kv {i}", max_new_tokens=12)
                for i in range(6)]
        for r in reqs:
            engine.submit(r)
        for r in reqs:
            tokens = []
            while True:
                kind, v = r.out.get(timeout=120.0)
                if kind == "token":
                    tokens.append(v)
                elif kind == "done":
                    break
                else:
                    raise AssertionError(f"request failed: {v}")
            assert len(tokens) == 12
    finally:
        engine.shutdown()
