"""CPU dress rehearsal for the TPU-gated bench phases (VERDICT r5 #3).

One subprocess bench run with POLYKEY_BENCH_FORCE_PHASES=1 must produce
EVERY phase key — including the previously TPU-only C/C2/D/D2/E — with
no error inside any entry. This is outage insurance: r3 lost its only
hardware window ever to a harness-level failure, and before this smoke
the forced phases' harness code had never executed end-to-end anywhere.

The run stays honest: platform is "cpu", so the composed headline must
be no_tpu_evidence — a forced run can never masquerade as measurement.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keys a forced CPU run must land (B/B2 stay TPU-only: fabricating an
# 8B tree is not tiny-scale and proves nothing extra about the harness).
EXPECTED_KEYS = (
    "gateway_echo",
    "engine_1b",
    "prefix_cache",
    "grpc_e2e",
    "engine_longctx",
    "engine_longctx_xl",
    "engine_moe",
    "engine_spec",
    "engine_gemma_spec",
)


def test_forced_run_yields_every_phase_key():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "POLYKEY_BENCH_FORCE_PHASES": "1",
        "POLYKEY_BENCH_ISOLATE": "0",
        "POLYKEY_BENCH_NO_REPLAY": "1",
        "POLYKEY_BENCH_PROBE_TRIES": "1",
        "POLYKEY_BENCH_PROBE_TIMEOUT": "20",
        # Tiny load: the smoke proves the harness paths run, not numbers.
        "POLYKEY_BENCH_REQUESTS": "2",
        "POLYKEY_BENCH_NEW_TOKENS": "4",
    })
    # A-tok depends on the local tokenizer asset; when absent the phase
    # records an exclusion note, which is a valid (non-error) entry.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        timeout=1500,
    )
    lines = proc.stdout.decode(errors="replace").strip().splitlines()
    assert lines, f"bench produced no output; stderr tail: " \
                  f"{proc.stderr.decode(errors='replace')[-2000:]}"
    artifact = json.loads(lines[-1])
    details = artifact.get("details", {})

    missing = [k for k in EXPECTED_KEYS if k not in details]
    assert not missing, (
        f"forced run missing phase keys {missing}; "
        f"stderr tail: {proc.stderr.decode(errors='replace')[-2000:]}"
    )
    errors = {
        k: details[k]["error"] for k in EXPECTED_KEYS
        if isinstance(details.get(k), dict) and "error" in details[k]
    }
    assert not errors, f"forced phases errored: {errors}"

    # Engine phases carry the measured-lanes export (ISSUE 4).
    for k in ("engine_longctx", "engine_moe", "engine_spec"):
        assert "avg_lanes" in details[k], f"{k} lacks avg_lanes"

    # Honesty: a CPU-forced run must not headline a number.
    assert artifact["metric"] == "no_tpu_evidence"
    assert details.get("platform") == "cpu"
