"""Beautifier rendering tests (reference: test/utils/beautify.go and
cmd/utils/log-beautifier/main.go)."""

import io
import json

from polykey_tpu.gateway.beautify import beautify_server_stream, print_jest_report


def _app_lines(fail=False):
    lines = [
        {"time": "t", "level": "INFO", "msg": "Starting polykey client..."},
        {"time": "t", "level": "INFO", "msg": "Configuration loaded",
         "runtime": "local", "server": "localhost:50051"},
        {"time": "t", "level": "INFO", "msg": "Network connectivity test passed"},
        {"time": "t", "level": "DEBUG", "msg": "Connection state changed",
         "state": "READY"},
        {"time": "t", "level": "INFO", "msg": "gRPC connection established successfully"},
        {"time": "t", "level": "INFO", "msg": "Executing tool",
         "tool_name": "example_tool"},
        {"time": "t", "level": "INFO", "msg": "Tool execution completed",
         "status_code": 200, "status_message": "Tool executed successfully"},
    ]
    if fail:
        lines.append(
            {"time": "t", "level": "ERROR", "msg": "Application failed",
             "error": "boom"}
        )
    return [json.dumps(x) for x in lines]


def test_app_report_all_pass():
    out = io.StringIO()
    ok = print_jest_report(_app_lines(), out)
    text = out.getvalue()
    assert ok
    assert "All 4 checks passed" in text
    for suite in ("SETUP", "CONNECTION", "EXECUTION"):
        assert suite in text


def test_app_report_failure():
    out = io.StringIO()
    ok = print_jest_report(_app_lines(fail=True), out)
    text = out.getvalue()
    assert not ok
    assert "1 failed, 4 passed" in text
    assert "ERROR" in text


def test_report_skips_unparseable_lines():
    out = io.StringIO()
    ok = print_jest_report(["not json", "", "[1,2]"] + _app_lines(), out)
    assert ok


def test_pytest_report_mode():
    lines = [
        json.dumps({"$report_type": "TestReport", "nodeid": "tests/a.py::t1",
                    "when": "call", "outcome": "passed", "duration": 0.01}),
        json.dumps({"$report_type": "TestReport", "nodeid": "tests/a.py::t1",
                    "when": "teardown", "outcome": "passed", "duration": 0.0}),
        json.dumps({"$report_type": "TestReport", "nodeid": "tests/b.py::t2",
                    "when": "call", "outcome": "failed", "duration": 0.02}),
    ]
    out = io.StringIO()
    ok = print_jest_report(lines, out)
    assert not ok
    assert "1 failed, 1 passed" in out.getvalue()


def test_server_stream_beautifier():
    entries = [
        "some non-json noise",
        "compose-prefix | " + json.dumps(
            {"msg": "server starting", "address": ":50051"}),
        json.dumps({"msg": "gRPC call received",
                    "method": "/polykey.v2.PolykeyService/ExecuteTool"}),
        json.dumps({"msg": "gRPC call finished",
                    "method": "/polykey.v2.PolykeyService/ExecuteTool",
                    "duration": "1ms", "code": "OK"}),
        json.dumps({"msg": "gRPC call received",
                    "method": "/polykey.v2.PolykeyService/ExecuteToolStream"}),
        json.dumps({"msg": "gRPC call finished",
                    "method": "/polykey.v2.PolykeyService/ExecuteToolStream",
                    "duration": "2ms", "code": "Internal"}),
        json.dumps({"msg": "server shutting down"}),
    ]
    out = io.StringIO()
    beautify_server_stream(io.StringIO("\n".join(entries) + "\n"), out)
    text = out.getvalue()
    assert "some non-json noise" in text          # passthrough
    assert "Server Listening" in text
    assert "✓" in text and "✗" in text            # OK pass, Internal fail
    assert "SHUTDOWN" in text


def test_server_stream_ignores_unmatched_finish():
    out = io.StringIO()
    beautify_server_stream(
        io.StringIO(json.dumps({"msg": "gRPC call finished", "method": "/m",
                                "code": "OK"}) + "\n"),
        out,
    )
    assert "✓" not in out.getvalue()
