"""schedlint (polykey_tpu/analysis/sched.py) tests: a firing and a
non-firing fixture per SL rule (progress floor, cursor discipline,
frontier order, bounded wait, quota conservation), teeth against the
REAL engine.py (stripping the restore progress floor or the
starved-first re-anchor must re-block the gate), the starvation-witness
merge (multi-process dirs, version skew, the wait-age gate through the
CLI), SL-namespace suppression isolation, the stale-contract-anchor
SL000 surface, the shared-CLI-plumbing rc-2 surfaces, baseline
round-trip, the committed soak artifact's embedded verdict, and the
self-run gate asserting the repo is clean under the committed-empty
baseline."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from polykey_tpu.analysis import concurrency, sched, schedwitness
from polykey_tpu.analysis.baseline import load_baseline
from polykey_tpu.analysis.sched import (
    WITNESS_MAX_WAIT_AGE_S,
    run_sched,
    witness_findings,
    witness_verdict,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
ENGINE = REPO_ROOT / "polykey_tpu" / "engine" / "engine.py"


def schedlint(tmp_path: Path, rel: str, source: str, only=None):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_sched(tmp_path, only=only)


def blocking(findings, rule=None):
    return [f for f in findings if f.blocking
            and (rule is None or f.rule == rule)]


# -- registry / CLI surface ---------------------------------------------------


def test_rule_table_lists_the_rules(capsys):
    assert sched.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("SL000", "SL001", "SL002", "SL003", "SL004",
                    "SL005", "SL006"):
        assert rule_id in out


def test_only_typo_is_a_usage_error(capsys):
    assert sched.main(["--only", "SL999"]) == 2
    assert "unknown rule id" in capsys.readouterr().err


def test_only_refuses_prune_and_write_baseline(capsys):
    assert sched.main(["--only", "SL002", "--prune"]) == 2
    assert "full run" in capsys.readouterr().err
    assert sched.main(["--only", "SL002", "--write-baseline"]) == 2
    assert "full run" in capsys.readouterr().err


def test_prune_refuses_explicit_targets(tmp_path, capsys):
    (tmp_path / "polykey_tpu").mkdir()
    (tmp_path / "polykey_tpu" / "clean.py").write_text("x = 1\n")
    rc = sched.main(["--root", str(tmp_path), "--prune", "polykey_tpu"])
    assert rc == 2
    assert "full run" in capsys.readouterr().err


def test_unloadable_witness_is_a_usage_error(tmp_path, capsys):
    rc = sched.main(["--witness", str(tmp_path / "absent.json")])
    assert rc == 2
    assert "cannot load witness" in capsys.readouterr().err


# -- SL001 progress floor -----------------------------------------------------


FLOORLESS = """\
    class Eng:
        def pump(self, items, budget):
            issued = 0
            for it in items:
                if issued >= budget:
                    break
                issued += 1
                self.emit(it)
"""


def test_sl001_budget_exit_without_floor_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/a.py", FLOORLESS,
                         only={"SL001"})
    hits = blocking(findings, "SL001")
    assert len(hits) == 1
    assert "issued >= budget" in hits[0].message


def test_sl001_progress_conjunct_is_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/b.py", """\
        class Eng:
            def pump(self, items, budget):
                issued = 0
                for it in items:
                    if issued >= budget and issued > 0:
                        break
                    issued += 1
                    self.emit(it)
    """, only={"SL001"})
    assert not blocking(findings, "SL001")


def test_sl001_grown_worklist_conjunct_is_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/c.py", """\
        class Eng:
            def pump(self, items, chunk_quota):
                spent = 0
                ranges = []
                for it in items:
                    if spent >= chunk_quota and ranges:
                        break
                    ranges.append(it)
                    spent += it.width
                return ranges
    """, only={"SL001"})
    assert not blocking(findings, "SL001")


# -- SL002 cursor discipline --------------------------------------------------


def test_sl002_read_without_write_on_exit_path_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/d.py", """\
        class Eng:
            def __init__(self):
                self._scan_rr = 0

            def pick(self, n):
                for off in range(n):
                    i = (self._scan_rr + off) % n
                    if self.ok(i):
                        return i
                return None
    """, only={"SL002"})
    hits = blocking(findings, "SL002")
    assert hits
    assert any("neither advances nor re-anchors" in f.message
               for f in hits)


def test_sl002_unbounded_advance_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/e.py", """\
        class Eng:
            def __init__(self):
                self._scan_rr = 0

            def bump(self):
                self._scan_rr = self._scan_rr + 1
    """, only={"SL002"})
    hits = blocking(findings, "SL002")
    assert len(hits) == 1
    assert "without a modulo bound" in hits[0].message


def test_sl002_early_exit_sweep_without_reanchor_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/f.py", """\
        class Eng:
            def __init__(self):
                self._scan_rr = 0

            def pick(self, n):
                for off in range(n):
                    i = (self._scan_rr + off) % n
                    if self.ok(i):
                        self._scan_rr = (i + 1) % n
                        return i
                self._scan_rr = (self._scan_rr + 1) % n
                return None
    """, only={"SL002"})
    hits = blocking(findings, "SL002")
    assert len(hits) == 1
    assert "never re-anchors" in hits[0].message


def test_sl002_reanchor_plus_advance_is_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/g.py", """\
        class Eng:
            def __init__(self):
                self._scan_rr = 0

            def pick(self, n):
                for off in range(n):
                    i = (self._scan_rr + off) % n
                    if self.ok(i):
                        self._scan_rr = i
                        return i
                self._scan_rr = (self._scan_rr + 1) % n
                return None
    """, only={"SL002"})
    assert not blocking(findings, "SL002")


def test_sl002_rrcursor_helper_idiom_is_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/h.py", """\
        class _RRCursor:
            def __init__(self):
                self.pos = 0

        class Eng:
            def __init__(self):
                self._queue_cursor = _RRCursor()

            def pick(self, n):
                for i in self._queue_cursor.scan(n):
                    if self.ok(i):
                        self._queue_cursor.reanchor(i)
                        return i
                self._queue_cursor.advance(n)
                return None
    """, only={"SL002"})
    assert not blocking(findings, "SL002")


# -- SL003 frontier ordering --------------------------------------------------


def test_sl003_inverted_frontier_order_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/i.py", """\
        class Eng:
            def run(self):
                while not self._stop.is_set():
                    self._dispatch_step()
                    self._issue_restores()
    """, only={"SL003"})
    hits = blocking(findings, "SL003")
    assert len(hits) == 1
    assert "frontier order violated" in hits[0].message


def test_sl003_ordered_frontiers_are_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/j.py", """\
        class Eng:
            def run(self):
                while not self._stop.is_set():
                    self._issue_restores()
                    self._advance_chunked_prefills()
                    self._dispatch_step()
    """, only={"SL003"})
    assert not blocking(findings, "SL003")


def test_sl003_missing_faulting_slot_guard_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/k.py", """\
        class Eng:
            def _build_ragged_batch(self, width):
                for s in self.slots:
                    if s.pending is None:
                        continue
                    self.emit(s)

            def faulting(self, s):
                return s.restore_pages
    """, only={"SL003"})
    hits = blocking(findings, "SL003")
    assert len(hits) == 1
    assert "does not skip faulting slots" in hits[0].message


def test_sl003_guarded_builder_is_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/l.py", """\
        class Eng:
            def _build_ragged_batch(self, width):
                for s in self.slots:
                    if s.pending is None:
                        continue
                    if s.restore_pages is not None:
                        continue
                    self.emit(s)
    """, only={"SL003"})
    assert not blocking(findings, "SL003")


# -- SL004 bounded wait -------------------------------------------------------


UNBOUNDED_QUEUE = """\
    import threading
    from collections import deque


    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._inbox = deque()

        def drain(self):
            while self._inbox:
                item = self._inbox.popleft()
                self.handle(item)
"""


def test_sl004_unbounded_consumed_queue_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/m.py",
                         UNBOUNDED_QUEUE, only={"SL004"})
    hits = blocking(findings, "SL004")
    assert len(hits) == 1
    assert "no admission bound" in hits[0].message


def test_sl004_bounded_ctor_shed_and_size_check_are_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/n.py", """\
        import queue
        import threading
        from collections import deque


        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._bounded = queue.Queue(maxsize=64)
                self._ringed = deque(maxlen=128)
                self._shedded = deque()
                self._sized = deque()

            def drain(self):
                self._bounded.get()
                self._ringed.popleft()

            def reap(self):
                item = self._shedded.popleft()
                if self.deadline_expired(item):
                    return None
                return item

            def admit_and_pop(self, item, cap):
                if len(self._sized) < cap:
                    self._sized.append(item)
                return self._sized.popleft()
    """, only={"SL004"})
    assert not blocking(findings, "SL004")


# -- SL005 quota conservation -------------------------------------------------


CONSERVING_BUILDER = """\
    class Eng:
        def _build_ragged_batch(self, W):
            ranges = []
            spent = 0
            for s in self.slots:
                take = min(s.need, W - spent)
                ranges.append((s.idx, take))
                spent += take
                if spent >= W:
                    break
            return ranges
"""


def test_sl005_conserving_builder_is_clean(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/o.py",
                         CONSERVING_BUILDER, only={"SL005"})
    assert not blocking(findings, "SL005")


def test_sl005_uncharged_builder_fires(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/p.py", """\
        class Eng:
            def _build_ragged_batch(self, W):
                ranges = []
                for s in self.slots:
                    take = min(s.need, W)
                    ranges.append((s.idx, take))
                return ranges
    """, only={"SL005"})
    hits = blocking(findings, "SL005")
    assert len(hits) == 1
    assert "does not charge" in hits[0].message


def test_sl005_strict_budget_exit_fires(tmp_path):
    findings = schedlint(
        tmp_path, "polykey_tpu/engine/q.py",
        CONSERVING_BUILDER.replace("if spent >= W:", "if spent > W:"),
        only={"SL005"})
    hits = blocking(findings, "SL005")
    assert len(hits) == 1
    assert "`spent >`" in hits[0].message


def test_sl005_operands_identity_is_clean_and_teeth(tmp_path):
    operands = """\
        class Eng:
            def _ragged_prefill_operands(self, reqs):
                off = 0
                useful = 0
                lens = [0] * len(reqs)
                for j, r in enumerate(reqs):
                    width = r.width
                    lens[j] = width
                    off += width
                    useful += width
                return off, useful, lens
    """
    findings = schedlint(tmp_path, "polykey_tpu/engine/r.py", operands,
                         only={"SL005"})
    assert not blocking(findings, "SL005")
    # Dropping one of the three same-width advances breaks the
    # sum(lens) == offset identity and must fire.
    findings = schedlint(
        tmp_path.joinpath("broken"), "polykey_tpu/engine/r.py",
        operands.replace("useful += width", "useful += 1"),
        only={"SL005"})
    hits = blocking(findings, "SL005")
    assert len(hits) == 1
    assert "SAME width" in hits[0].message


# -- teeth against the real engine -------------------------------------------


def _engine_copy(tmp_path: Path, old: str, new: str) -> Path:
    source = ENGINE.read_text()
    assert old in source, f"teeth anchor gone from engine.py: {old!r}"
    target = tmp_path / "polykey_tpu" / "engine" / "engine.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(source.replace(old, new))
    return target


def test_teeth_stripping_the_restore_progress_floor_fires_sl001(tmp_path):
    """The SL001 fix this tier landed (`and issued > 0` on the restore
    budget exit) must be load-bearing: removing it re-blocks the gate."""
    _engine_copy(tmp_path,
                 "issued >= self._restore_slots and issued > 0",
                 "issued >= self._restore_slots")
    findings = run_sched(tmp_path, only={"SL001"})
    hits = blocking(findings, "SL001")
    assert len(hits) == 1
    assert "_restore_slots" in hits[0].message


def test_teeth_replacing_reanchor_with_advance_fires_sl002(tmp_path):
    """Always advancing past the anchor is fair in shape but hands the
    skipped slot's turn away — the starved-first re-anchor on the
    restore budget exit must be load-bearing."""
    _engine_copy(tmp_path,
                 "self._restore_rr.reanchor(i)",
                 "self._restore_rr.advance(i + 1)")
    findings = run_sched(tmp_path, only={"SL002"})
    hits = blocking(findings, "SL002")
    assert hits
    assert any("_restore_rr" in f.message
               and "never re-anchors" in f.message for f in hits)


def test_real_engine_is_clean_standalone(tmp_path):
    """The committed engine passes every SL rule on its own — the teeth
    fixtures above differ from green by exactly their one edit."""
    _engine_copy(tmp_path, "and issued > 0", "and issued > 0")
    assert not blocking(run_sched(tmp_path))


# -- SL000 stale contract anchors --------------------------------------------


def test_stale_contract_anchors_are_sl000(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/engine.py", """\
        x = 1
    """)
    hits = blocking(findings, "SL000")
    assert any("contract anchor" in f.message for f in hits)
    assert any("engine loop" in f.message for f in hits)
    names = {f.message.split("(")[0] for f in hits
             if "contract anchor" in f.message}
    assert len(names) == len(sched._CONTRACT_ANCHORS)


# -- SL006 witness merge ------------------------------------------------------


def _proc(pid=7, **frontiers):
    merged = {}
    for name, (age, skips) in frontiers.items():
        merged[name] = {
            "notes": 100, "serves": 50,
            "max_wait_age_s": age, "max_wait_slot": 3,
            "max_consecutive_skips": skips, "max_skip_slot": 3,
            "outstanding": [],
        }
    return {"version": 1, "pid": pid, "argv0": "t", "elapsed_s": 1.0,
            "frontiers": merged}


def test_witness_wait_age_over_gate_fires():
    fired = witness_findings([_proc(prefill=(9.0, 5))])
    assert len(fired) == 1
    assert fired[0].rule == "SL006"
    assert "prefill" in fired[0].message
    assert "9.000s" in fired[0].message
    assert not witness_findings([_proc(prefill=(1.0, 5))])


def test_witness_skip_count_over_gate_fires():
    fired = witness_findings([_proc(decode=(0.1, 200_000))])
    assert len(fired) == 1
    assert "200000 consecutive" in fired[0].message


def test_witness_verdict_aggregates_across_processes():
    verdict = witness_verdict([
        _proc(pid=1, prefill=(0.5, 3), restore=(0.1, 1)),
        _proc(pid=2, prefill=(2.0, 9)),
    ])
    assert verdict["processes"] == 2
    assert verdict["max_wait_age_s"] == 2.0
    assert verdict["frontiers"]["prefill"]["max_wait_age_s"] == 2.0
    assert verdict["frontiers"]["prefill"]["max_consecutive_skips"] == 9
    assert verdict["frontiers"]["prefill"]["notes"] == 200
    assert verdict["gate_max_wait_age_s"] == WITNESS_MAX_WAIT_AGE_S
    assert verdict["starvation_free"] is True
    assert verdict["findings"] == []
    tight = witness_verdict([_proc(prefill=(2.0, 9))],
                            max_wait_age_s=1.0)
    assert tight["starvation_free"] is False
    assert tight["gate_max_wait_age_s"] == 1.0
    assert tight["findings"]


def test_witness_dir_merge_and_version_skew(tmp_path):
    (tmp_path / "sched_witness_1.json").write_text(
        json.dumps(_proc(pid=1, decode=(0.1, 1))))
    (tmp_path / "sched_witness_2.json").write_text(
        json.dumps(_proc(pid=2, decode=(0.2, 2))))
    merged = schedwitness.load_witness(str(tmp_path))
    assert [p["pid"] for p in merged] == [1, 2]

    skewed = _proc(pid=3)
    skewed["version"] = 99
    (tmp_path / "sched_witness_3.json").write_text(json.dumps(skewed))
    with pytest.raises(ValueError, match="version"):
        schedwitness.load_witness(str(tmp_path))

    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no sched_witness_"):
        schedwitness.load_witness(str(empty))


def test_runtime_witness_end_to_end(tmp_path):
    """POLYKEY_SCHED_WITNESS=1 arms the recorder at package import;
    note() calls at dispatch boundaries dump per-process JSON that
    `sched --witness` merges and gates — the live half of the
    lock/heap-witness pattern."""
    out_dir = tmp_path / "wit"
    source = textwrap.dedent("""\
        import time

        import polykey_tpu  # noqa: F401  (arms the sched witness)
        from polykey_tpu.analysis import schedwitness

        assert schedwitness.installed()
        schedwitness.note("prefill", [0], [1, 2])
        time.sleep(0.05)
        schedwitness.note("prefill", [1], [2])
        schedwitness.note("decode", [0, 1, 2], [])
        print(schedwitness.dump())
    """)
    env = dict(os.environ)
    env.update({
        "POLYKEY_SCHED_WITNESS": "1",
        "POLYKEY_SCHED_WITNESS_OUT": str(out_dir),
        "PYTHONPATH": str(REPO_ROOT),
    })
    proc = subprocess.run(
        [sys.executable, "-"], input=source, env=env,
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    merged = schedwitness.load_witness(str(out_dir))
    assert len(merged) == 1
    prefill = merged[0]["frontiers"]["prefill"]
    assert prefill["notes"] == 2
    assert prefill["serves"] == 2
    # Slot 2 was skipped at both boundaries: its age spans the sleep.
    assert prefill["max_skip_slot"] == 2
    assert prefill["max_consecutive_skips"] == 2
    assert 0.04 <= prefill["max_wait_age_s"] < 5.0
    assert merged[0]["frontiers"]["decode"]["serves"] == 3
    assert not witness_findings(merged)
    # Through the CLI gate the smoke jobs run — and the gate has teeth:
    # the same dump fails under a wait-age gate tighter than the sleep.
    rc = sched.main(["--root", str(REPO_ROOT), "--only", "SL006",
                     "--witness", str(out_dir)])
    assert rc == 0
    rc = sched.main(["--root", str(REPO_ROOT), "--only", "SL006",
                     "--witness", str(out_dir),
                     "--max-wait-age", "0.001"])
    assert rc == 1


def test_witness_flag_off_means_not_installed_and_note_is_noop():
    if schedwitness.installed():       # another test armed it in-process
        pytest.skip("witness armed in this process")
    schedwitness.note("decode", [0], [1])    # must not raise
    assert schedwitness.dump() is None
    assert schedwitness.snapshot()["frontiers"] == {}


# -- namespaces, suppressions & baselines ------------------------------------


def test_sl_suppression_silences_schedlint_only(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/s.py", """\
        from collections import deque


        class Server:
            def __init__(self):
                # polylint: disable=SL004(drained whole every tick: bounded by arrival window)
                self._inbox = deque()

            def serve_forever(self):
                while True:
                    if self._inbox:
                        self.handle(self._inbox.popleft())
    """)
    assert not blocking(findings)
    assert any(f.suppressed and f.rule == "SL004" for f in findings)
    # racelint must neither honor nor complain about the SL namespace.
    race_findings, _ = concurrency.run_race(tmp_path)
    assert not blocking(race_findings)


def test_unused_sl_suppression_is_sl000(tmp_path):
    findings = schedlint(tmp_path, "polykey_tpu/engine/t.py", """\
        def quiet():
            return 1  # polylint: disable=SL002(nothing rotates here)
    """)
    hits = blocking(findings, "SL000")
    assert hits and "unused suppression" in hits[0].message


def test_baseline_round_trip_and_prune(tmp_path, capsys):
    pkg = tmp_path / "polykey_tpu" / "engine"
    pkg.mkdir(parents=True)
    (pkg / "w.py").write_text(textwrap.dedent(UNBOUNDED_QUEUE))
    root = str(tmp_path)
    assert sched.main(["--root", root]) == 1
    capsys.readouterr()
    assert sched.main(["--root", root, "--write-baseline"]) == 0
    base = load_baseline(tmp_path / "schedlint-baseline.json")
    assert len(base["findings"]) == 1
    assert sched.main(["--root", root]) == 0      # grandfathered
    out = capsys.readouterr().out
    assert "baselined" in out
    # Fix the debt: the entry goes stale, prune drops it.
    (pkg / "w.py").write_text("x = 1\n")
    assert sched.main(["--root", root]) == 0
    assert "stale baseline" in capsys.readouterr().out
    assert sched.main(["--root", root, "--prune"]) == 0
    base = load_baseline(tmp_path / "schedlint-baseline.json")
    assert base["findings"] == {}


def test_json_output_shape(tmp_path, capsys):
    (tmp_path / "polykey_tpu").mkdir()
    (tmp_path / "polykey_tpu" / "clean.py").write_text("x = 1\n")
    (tmp_path / "wit").mkdir()
    (tmp_path / "wit" / "sched_witness_9.json").write_text(
        json.dumps(_proc(pid=9, decode=(0.2, 4))))
    rc = sched.main(["--root", str(tmp_path), "--json",
                     "--witness", str(tmp_path / "wit")])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["summary"]["sched_clean"] is True
    assert payload["summary"]["witness_processes"] == 1
    assert payload["witness_verdict"]["starvation_free"] is True
    assert payload["witness_verdict"]["max_wait_age_s"] == 0.2


# -- the repo itself ----------------------------------------------------------


def test_self_run_repo_is_clean_under_committed_baseline(capsys):
    """The acceptance gate: `python -m polykey_tpu.analysis sched`
    exits 0 on this repo with the committed-empty baseline — every
    surfaced finding is fixed or reason-annotated."""
    rc = sched.main(["--root", str(REPO_ROOT)])
    out = capsys.readouterr().out
    assert rc == 0, f"schedlint found blocking findings:\n{out}"


def test_committed_baseline_is_empty():
    data = load_baseline(REPO_ROOT / "schedlint-baseline.json")
    assert data["findings"] == {}


def test_committed_soak_artifact_carries_starvation_verdict():
    """The witnessed occupancy soak is a committed acceptance artifact:
    the merged verdict rides the perf JSON, starvation-free with a
    bounded max wait-age."""
    path = REPO_ROOT / "perf" / "occupancy_soak_sched_witness_2026-08-07.json"
    art = json.loads(path.read_text())
    verdict = art["sched_witness"]
    assert verdict["starvation_free"] is True
    assert verdict["findings"] == []
    assert verdict["processes"] >= 1
    assert 0.0 <= verdict["max_wait_age_s"] <= verdict["gate_max_wait_age_s"]
    served = {name for name, st in verdict["frontiers"].items()
              if st["serves"] > 0}
    assert "decode" in served
    assert "prefill" in served
