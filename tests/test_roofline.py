"""Roofline accounting (engine/roofline.py): the physics scorecard every
bench phase emits (VERDICT r4 #4). Pins the geometry math so a silent
formula regression can't skew every artifact's mbu/mfu at once."""

import pytest

from polykey_tpu.engine.roofline import (
    CHIP_SPECS,
    decode_flops_per_token,
    detect_chip,
    grade,
    kv_bytes_per_token,
    prefill_flops,
    weight_read_bytes,
)
from polykey_tpu.models.config import get_config


def test_8b_geometry():
    cfg = get_config("llama-3-8b")
    # ~8.03e9 params; int8 weight read ~= params minus the gathered-only
    # embedding table (~0.5 GB), i.e. ~7.5 GB.
    assert 8.0e9 < cfg.num_params() < 8.1e9
    w8 = weight_read_bytes(cfg, "bfloat16", True, 8)
    assert 7.4e9 < w8 < 7.6e9
    # bf16 doubles it; int4 halves the block weights but not the head.
    assert weight_read_bytes(cfg, "bfloat16", False, 8) == pytest.approx(
        2 * w8, rel=0.01)
    w4 = weight_read_bytes(cfg, "bfloat16", True, 4)
    assert 0.5 * w8 < w4 < 0.6 * w8
    # GQA KV: 2 * 32 layers * 8 kv heads * 128 dim * 2 B = 128 KiB/token.
    assert kv_bytes_per_token(cfg, "bfloat16") == 2 * 32 * 8 * 128 * 2
    assert kv_bytes_per_token(cfg, "int8") == 2 * 32 * 8 * 128
    # Decode FLOPs ~ 2 * params at short context.
    assert decode_flops_per_token(cfg, 0) == pytest.approx(
        2 * cfg.num_params(), rel=1e-6)
    # Prefill FLOPs scale superlinearly (attention P^2 term).
    assert prefill_flops(cfg, 2048) > 16 * prefill_flops(cfg, 128)
    # Dense weight reads are lane-independent.
    assert weight_read_bytes(cfg, "bfloat16", True, 8, lanes=32) == w8


def test_moe_active_params_and_step_reads():
    cfg = get_config("mixtral-8x7b")
    active = cfg.num_active_params()
    assert active < cfg.num_params() / 2     # top-2 of 8 experts
    assert active > cfg.num_params() / 8     # attn + 2 experts > 1/8
    # Per-STEP weight reads grow with lanes until every expert is hit
    # (batched MoE decode does NOT amortize experts the way dense does —
    # code-review r5), then saturate at the full expert set.
    w1 = weight_read_bytes(cfg, "bfloat16", True, 8, lanes=1)
    w4 = weight_read_bytes(cfg, "bfloat16", True, 8, lanes=4)
    w16 = weight_read_bytes(cfg, "bfloat16", True, 8, lanes=16)
    w64 = weight_read_bytes(cfg, "bfloat16", True, 8, lanes=64)
    assert w1 < w4 <= w16 == w64   # saturates at num_experts=8 by 4 lanes
    # At saturation every parameter streams: ~ num_params * 1 B (int8),
    # minus the gathered-only embedding table.
    assert w16 == pytest.approx(
        cfg.num_params() - cfg.vocab_size * cfg.hidden_size, rel=0.02)


def test_grade_tpu_fields():
    spec = CHIP_SPECS["tpu-v5e"]
    g = grade("llama-3-8b", "bfloat16", True, 8, "int8",
              tok_s=117.9, avg_lanes=7.1, avg_ctx=192,
              p50_ttft_ms=150.0, prompt_len=128, chip=spec)
    assert g["chip"] == "tpu-v5e"
    assert g["avg_lanes_source"] == "measured"
    # r3's measured 117.9 tok/s at 7.1 lanes grades to ~15% MBU — the
    # occupancy diagnosis (PERF.md) expressed as physics.
    assert 0.10 < g["mbu"] < 0.20
    assert 0 < g["mfu"] < 0.05
    # Weight amortization: more lanes -> higher roofline ceiling.
    g32 = grade("llama-3-8b", "bfloat16", True, 8, "int8",
                tok_s=117.9, avg_lanes=32, avg_ctx=192, chip=spec)
    assert g32["roofline_tok_s"] > 2 * g["roofline_tok_s"]
    # The north-star 2,000 tok/s is BELOW the 32-lane int8-KV roofline —
    # i.e. the target is physically reachable on one v5e chip.
    assert g32["roofline_tok_s"] > 2000


def test_grade_draft_and_chips():
    spec = CHIP_SPECS["tpu-v5e"]
    base = grade("llama-3-8b", "bfloat16", True, 8, "int8",
                 tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec)
    # draft == target doubles the weight stream (bench phase C shape).
    spec_g = grade("llama-3-8b", "bfloat16", True, 8, "int8",
                   tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec,
                   draft_model="llama-3-8b")
    assert spec_g["weight_read_bytes"] == pytest.approx(
        2 * base["weight_read_bytes"], rel=1e-6)
    assert spec_g["roofline_tok_s"] < base["roofline_tok_s"]
    # n_chips scales the roofline denominator (tp/ep phases).
    multi = grade("llama-3-8b", "bfloat16", True, 8, "int8",
                  tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec,
                  n_chips=4)
    assert multi["mbu"] == pytest.approx(base["mbu"] / 4, rel=1e-3)
    assert multi["roofline_tok_s"] == pytest.approx(
        4 * base["roofline_tok_s"], rel=1e-3)


def test_grade_unmeasured_lanes_flagged():
    # No loop-trace counter -> the scorecard says the occupancy is
    # assumed, never passing an unmeasured number off as data.
    g = grade("llama-3-8b", "bfloat16", True, 8, "int8",
              tok_s=100.0, avg_lanes=None, avg_ctx=192,
              chip=CHIP_SPECS["tpu-v5e"], assumed_lanes=32.0)
    assert g["avg_lanes_source"] == "assumed_full"
    assert g["avg_lanes"] == 32.0


def test_grade_cpu_null_utilization():
    g = grade("tiny-llama", "bfloat16", False, 8, "",
              tok_s=2900.0, avg_lanes=4, avg_ctx=24, chip=None)
    assert g["chip"] is None and g["mbu"] is None and g["mfu"] is None
    assert g["bytes_per_token"] > 0 and g["flops_per_token"] > 0


def test_detect_chip_off_tpu():
    # Tests force JAX_PLATFORMS=cpu (conftest), so detection returns None.
    assert detect_chip() is None


def test_grade_hbm_weight_fraction():
    spec = CHIP_SPECS["tpu-v5e"]
    g = grade("llama-3-8b", "bfloat16", True, 8, "int8",
              tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec)
    # ~8 GB of int8-resident weights on a 16 GiB chip: roughly half the
    # HBM is weights, the rest is the KV-page (decode slot) budget.
    assert 0.4 < g["hbm_weight_fraction"] < 0.6
    # bf16 doubles residency; the draft adds its own tree.
    g_bf16 = grade("llama-3-8b", "bfloat16", False, 8, "",
                   tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec)
    assert g_bf16["hbm_weight_fraction"] > 1.5 * g["hbm_weight_fraction"]
    g_draft = grade("llama-3-8b", "bfloat16", True, 8, "int8",
                    tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec,
                    draft_model="llama-3-8b")
    assert g_draft["hbm_weight_fraction"] == pytest.approx(
        2 * g["hbm_weight_fraction"], rel=0.01)
    # Off-chip runs have no capacity denominator.
    g_cpu = grade("tiny-llama", "bfloat16", False, 8, "",
                  tok_s=100.0, avg_lanes=4, avg_ctx=24, chip=None)
    assert "hbm_weight_fraction" not in g_cpu


def test_grade_resident_fraction_extends_without_breaking_replay():
    """ISSUE 17: passing the pool bytes folds device KV + scale pools
    into a full-residency fraction as NEW sibling fields —
    hbm_weight_fraction keeps its weights-only meaning and committed
    BENCH artifacts (graded without the pool) replay with the same
    schema."""
    from polykey_tpu.engine.roofline import kv_pool_bytes_spec
    from polykey_tpu.models.config import get_config

    spec = CHIP_SPECS["tpu-v5e"]
    base = grade("llama-3-8b", "bfloat16", True, 8, "int8",
                 tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec)
    assert "hbm_resident_fraction" not in base     # replay-compatible
    assert "hbm_kv_pool_bytes" not in base
    pool = kv_pool_bytes_spec(get_config("llama-3-8b"), 2048, 16, "int8")
    g = grade("llama-3-8b", "bfloat16", True, 8, "int8",
              tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec,
              kv_pool_bytes=pool)
    assert g["hbm_weight_fraction"] == base["hbm_weight_fraction"]
    assert g["hbm_kv_pool_bytes"] == round(pool)
    assert g["hbm_resident_fraction"] == pytest.approx(
        g["hbm_weight_fraction"] + pool / spec.hbm_bytes, abs=2e-4)
    assert g["hbm_resident_fraction"] < 1.0        # the config fits
    # Multi-chip: the pool shards with the weights.
    g4 = grade("llama-3-8b", "bfloat16", True, 8, "int8",
               tok_s=100.0, avg_lanes=8, avg_ctx=192, chip=spec,
               n_chips=4, kv_pool_bytes=pool)
    assert g4["hbm_resident_fraction"] == pytest.approx(
        g["hbm_resident_fraction"] / 4, rel=1e-3)
    # Off-chip runs still emit no capacity fields at all.
    g_cpu = grade("tiny-llama", "bfloat16", False, 8, "",
                  tok_s=100.0, avg_lanes=4, avg_ctx=24, chip=None,
                  kv_pool_bytes=pool)
    assert "hbm_resident_fraction" not in g_cpu


def test_detect_chip_unknown_kind_returns_none(monkeypatch):
    """An unknown v5 variant (or any unrecognized kind) must NOT grade
    against the v5p roofline (ADVICE r5): only explicit v5e/v5p kinds
    map; everything else returns None and the scorecard degrades to
    geometry-only."""
    import jax as _jax

    class _Dev:
        def __init__(self, kind):
            self.platform = "tpu"
            self.device_kind = kind

    for kind, expected in (
        ("TPU v5 lite", "tpu-v5e"),
        ("TPU v5e", "tpu-v5e"),
        ("TPU v5p", "tpu-v5p"),
        ("TPU v5x-mystery", None),   # old code: silently v5p
        ("TPU v6e", None),
        ("warp-drive", None),
    ):
        monkeypatch.setattr(_jax, "devices", lambda k=kind: [_Dev(k)])
        got = detect_chip()
        assert (got.name if got else None) == expected, kind
