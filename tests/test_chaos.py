"""Deterministic chaos tests (ISSUE 3): fault injection proves the
resilience layer end to end on CPU.

Acceptance criteria covered here:

- an expired queued request is dropped at dequeue and never reaches
  prefill (phase=queued counter, prefill never starts);
- over-limit admission rejects in O(1) with RESOURCE_EXHAUSTED and a
  retry-after-ms trailing-metadata hint, in well under 50 ms;
- an injected step-stall trips the watchdog, the supervisor restarts
  the engine, and health returns to SERVING — with the restart budget
  enforced when the fault persists.

All timeouts are test-scaled (watchdog 0.25 s, check intervals 50 ms);
no sleep exceeds the injected stall durations.
"""

import dataclasses
import queue
import time

import grpc
import pytest

from polykey_tpu import faults
from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import (
    EngineOverloadedError,
    GenRequest,
    InferenceEngine,
)
from polykey_tpu.engine.supervisor import EngineSupervisor
from polykey_tpu.engine.watchdog import Watchdog
from polykey_tpu.gateway import server as gateway_server
from polykey_tpu.gateway.health import NOT_SERVING, SERVING, HealthService
from polykey_tpu.gateway.jsonlog import Logger
from polykey_tpu.gateway.tpu_service import TpuService
from polykey_tpu.obs import Observability
from polykey_tpu.proto import polykey_v2_pb2 as pk
from polykey_tpu.proto.polykey_v2_grpc import PolykeyServiceStub

import io

CHAOS_CONFIG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=1,            # one slot: queueing is deterministic
    page_size=8,
    num_pages=64,
    max_seq_len=64,
    prefill_buckets=(16, 32),
    max_new_tokens_cap=32,
    default_max_new_tokens=8,
    decode_block_steps=1,          # per-token dispatch: slow-step paces finely
    adaptive_block=False,
    lookahead_blocks=1,
    watchdog_timeout_s=0.25,       # test-scaled liveness window
    max_queue_depth=1,
)


@pytest.fixture(autouse=True)
def _clean_injector(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.clear()
    yield
    faults.clear()


def _drain(request: GenRequest, timeout=30.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _await(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def test_faults_off_engine_has_no_injector():
    # The no-op guard: with POLYKEY_FAULTS unset the engine holds None
    # and every injection point is a single `is None` check — no parsing,
    # no lookups, no clock reads on the hot path (bench invariance).
    engine = InferenceEngine(CHAOS_CONFIG)
    try:
        assert engine._faults is None
        request = GenRequest(prompt="hello", max_new_tokens=4)
        engine.submit(request)
        tokens, done, error = _drain(request)
        assert error is None and done is not None and tokens
    finally:
        engine.shutdown()


def test_expired_queued_request_never_reaches_prefill():
    # A occupies the single slot (slow-step paces it); B's deadline
    # expires while it waits in the queue → dropped at dequeue: no
    # tokenize, no page allocation, no device work.
    faults.install("slow-step=0.04")
    engine = InferenceEngine(CHAOS_CONFIG)
    try:
        a = GenRequest(prompt="occupant", max_new_tokens=16)
        engine.submit(a)
        # A must hold the slot before B queues (max_queue_depth=1: B in
        # the queue at the same time as A would be shed, not queued).
        assert _await(lambda: engine.stats()["slots_busy"] == 1)
        b = GenRequest(prompt="expired", max_new_tokens=4,
                       deadline=time.monotonic() + 0.2)
        engine.submit(b)
        tokens_b, done_b, error_b = _drain(b)
        assert done_b is None and not tokens_b
        assert error_b is not None and error_b.startswith("deadline exceeded")
        # Never prepared: prefill_start is only stamped in
        # _prepare_request, which an expired dequeue must not reach.
        assert b.timings.prefill_start == 0.0
        snap = engine.metrics.snapshot()
        assert snap["deadline_expired_queued"] == 1
        assert snap["deadline_expired_prefill"] == 0
        assert snap["deadline_expired_decode"] == 0
        # A is unaffected by B's expiry.
        _, done_a, error_a = _drain(a)
        assert error_a is None and done_a is not None
    finally:
        engine.shutdown()


def test_expired_decode_drops_at_block_boundary():
    # A's own deadline passes mid-generation: the block-boundary check
    # retires the lane with phase=decode and a deadline error.
    faults.install("slow-step=0.05")
    engine = InferenceEngine(CHAOS_CONFIG)
    try:
        a = GenRequest(prompt="midstream", max_new_tokens=32,
                       deadline=time.monotonic() + 0.4)
        engine.submit(a)
        tokens, done, error = _drain(a)
        assert done is None
        assert error is not None and error.startswith("deadline exceeded")
        assert len(tokens) < 32            # cut off before the budget
        assert engine.metrics.snapshot()["deadline_expired_decode"] == 1
    finally:
        engine.shutdown()


def test_overload_sheds_fast_with_retry_hint():
    faults.install("slow-step=0.04")
    engine = InferenceEngine(CHAOS_CONFIG)   # max_queue_depth=1
    try:
        a = GenRequest(prompt="occupant", max_new_tokens=16)
        engine.submit(a)
        # Wait until A holds the slot so B stays queued deterministically.
        assert _await(lambda: engine.stats()["slots_busy"] == 1)
        b = GenRequest(prompt="queued", max_new_tokens=4)
        engine.submit(b)
        assert engine.stats()["queued"] >= 1
        c = GenRequest(prompt="shed me", max_new_tokens=4)
        t0 = time.monotonic()
        with pytest.raises(EngineOverloadedError) as err:
            engine.submit(c)
        elapsed_ms = (time.monotonic() - t0) * 1e3
        assert elapsed_ms < 50, f"shed took {elapsed_ms:.1f}ms"
        assert err.value.retry_after_ms >= 50
        assert engine.metrics.snapshot()["requests_shed"] == 1
        for req in (a, b):
            _, done, error = _drain(req)
            assert error is None and done is not None
    finally:
        engine.shutdown()


def test_grpc_shed_maps_to_resource_exhausted_with_trailer():
    # Full-stack version: the shed surfaces as RESOURCE_EXHAUSTED with
    # the retry-after-ms trailing-metadata hint, without clobbering the
    # x-trace-id echo.
    faults.install("slow-step=0.04")
    engine = InferenceEngine(CHAOS_CONFIG)
    logger = Logger(stream=io.StringIO())
    obs = Observability()
    service = TpuService.create(engine, logger=logger, obs=obs)
    server, health, port = gateway_server.build_server(
        service, logger, address="127.0.0.1:0", obs=obs
    )
    server.start()
    try:
        occupant = GenRequest(prompt="occupant", max_new_tokens=24)
        engine.submit(occupant)
        assert _await(lambda: engine.stats()["slots_busy"] == 1)
        filler = GenRequest(prompt="filler", max_new_tokens=4)
        engine.submit(filler)

        with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
            # Warm the channel first: the <50ms bound is about the shed
            # path, not TCP/HTTP2 connection setup.
            grpc.channel_ready_future(channel).result(timeout=5)
            stub = PolykeyServiceStub(channel)
            request = pk.ExecuteToolRequest(tool_name="llm_generate")
            request.parameters.update({"prompt": "shed", "max_tokens": 4})
            t0 = time.monotonic()
            with pytest.raises(grpc.RpcError) as err:
                stub.ExecuteTool(request, timeout=5)
            elapsed_ms = (time.monotonic() - t0) * 1e3
            assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert elapsed_ms < 50, f"shed RPC took {elapsed_ms:.1f}ms"
            trailers = dict(err.value.trailing_metadata() or ())
            assert int(trailers["retry-after-ms"]) >= 50
            assert "x-trace-id" in trailers     # echo survived the merge

            # The shed shows up in the struct stats view too.
            stats_req = pk.ExecuteToolRequest(tool_name="engine_stats")
            stats = dict(stub.ExecuteTool(stats_req, timeout=10).struct_output)
            assert stats["requests_shed"] >= 1
            assert "engine_restarts" in stats    # supervisor wired by create()
        for req in (occupant, filler):
            _drain(req)
    finally:
        server.stop(grace=None)
        service.close()


def _check_status(health: HealthService, name: str = ""):
    return health._statuses.get(name)


def test_step_stall_trips_watchdog_and_supervisor_recovers():
    # The headline chaos scenario: one injected 1 s stall in the decode
    # dispatch wedges the engine thread; the watchdog (0.25 s window)
    # trips, health flips NOT_SERVING, the supervisor swaps in a fresh
    # engine, re-arms the watchdog, and health returns to SERVING. The
    # @1 budget is spent, so the restarted engine runs clean.
    faults.install("step-stall=1.0@1")
    config = CHAOS_CONFIG
    engine = InferenceEngine(config)
    health = HealthService()
    health.set_serving_status("", SERVING)
    watchdog = Watchdog(engine, health=health, check_interval_s=0.05)
    watchdog.start()
    supervisor = EngineSupervisor(
        engine, lambda: InferenceEngine(config),
        watchdog=watchdog, health=health,
        max_restarts=2, restart_window_s=60.0,
        check_interval_s=0.05, join_timeout_s=5.0,
    ).start()
    try:
        a = GenRequest(prompt="stall victim", max_new_tokens=8)
        engine.submit(a)
        # Trip: watchdog notices the wedged dispatch and flips health.
        assert _await(lambda: watchdog.tripped or supervisor.restarts > 0,
                      timeout=5.0)
        # The stalled request fails cleanly instead of hanging.
        _, done_a, error_a = _drain(a, timeout=10.0)
        assert done_a is None and error_a is not None
        # Recovery: fresh engine, re-armed watchdog, SERVING again.
        assert _await(lambda: supervisor.restarts == 1, timeout=10.0)
        assert supervisor.engine is not engine
        assert watchdog.engine is supervisor.engine
        assert not watchdog.tripped
        assert _check_status(health) == SERVING
        # Metric continuity: the fresh engine adopted the old metrics.
        assert supervisor.engine.metrics is engine.metrics
        # The restarted engine serves.
        b = GenRequest(prompt="after restart", max_new_tokens=4)
        supervisor.engine.submit(b)
        tokens, done_b, error_b = _drain(b, timeout=30.0)
        assert error_b is None and done_b is not None and tokens
    finally:
        supervisor.stop()
        watchdog.stop()
        supervisor.engine.shutdown()


def test_supervisor_gives_up_when_fault_persists():
    # A persistent stall exhausts the restart budget: the supervisor
    # stops restarting, leaves health NOT_SERVING, and marks gave_up —
    # the platform's process-level restart policy takes over from there.
    faults.install("step-stall=0.6@4")
    config = dataclasses.replace(CHAOS_CONFIG, watchdog_timeout_s=0.2)
    engine = InferenceEngine(config)
    health = HealthService()
    health.set_serving_status("", SERVING)
    watchdog = Watchdog(engine, health=health, check_interval_s=0.05)
    watchdog.start()
    supervisor = EngineSupervisor(
        engine, lambda: InferenceEngine(config),
        watchdog=watchdog, health=health,
        max_restarts=1, restart_window_s=60.0,
        check_interval_s=0.05, join_timeout_s=5.0,
    ).start()
    try:
        a = GenRequest(prompt="stall one", max_new_tokens=8)
        engine.submit(a)
        assert _await(lambda: supervisor.restarts == 1, timeout=10.0)
        # Stall the restarted engine too: budget (1) is now exhausted.
        b = GenRequest(prompt="stall two", max_new_tokens=8)
        supervisor.engine.submit(b)
        assert _await(lambda: supervisor.gave_up, timeout=10.0)
        assert supervisor.restarts == 1
        assert _check_status(health) == NOT_SERVING
    finally:
        supervisor.stop()
        watchdog.stop()
        supervisor.engine.shutdown()


def test_prefill_error_contained_to_request():
    # An injected prefill failure errors ONE request and leaves the
    # engine serving (containment, not crash).
    faults.install("prefill-error@1")
    engine = InferenceEngine(CHAOS_CONFIG)
    try:
        a = GenRequest(prompt="doomed", max_new_tokens=4)
        engine.submit(a)
        _, done_a, error_a = _drain(a)
        assert done_a is None
        assert error_a is not None and "injected fault" in error_a
        b = GenRequest(prompt="fine", max_new_tokens=4)
        engine.submit(b)
        tokens, done_b, error_b = _drain(b)
        assert error_b is None and done_b is not None and tokens
        assert engine.dead is None
    finally:
        engine.shutdown()


def test_tokenizer_and_alloc_faults_degrade_gracefully():
    faults.install("tokenizer-error@1,alloc-fail@1")
    engine = InferenceEngine(CHAOS_CONFIG)
    try:
        a = GenRequest(prompt="tokenizer victim", max_new_tokens=4)
        engine.submit(a)
        _, done_a, error_a = _drain(a)
        assert done_a is None and "injected fault" in (error_a or "")
        # alloc-fail requeues once (pool-exhaustion path), then the
        # retry admits and the request completes.
        b = GenRequest(prompt="alloc victim", max_new_tokens=4)
        engine.submit(b)
        tokens, done_b, error_b = _drain(b)
        assert error_b is None and done_b is not None and tokens
    finally:
        engine.shutdown()
