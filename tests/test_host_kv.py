"""Host-memory KV tier (ISSUE 15): two-tier paging under the block
allocator — cold-page offload to host RAM, page-aware restore
scheduling, and the restart-durable prefix cache.

The acceptance bar mirrors the prefix-cache suite's: sharing pages
across tiers must be invisible to the math (greedy streams identical
with the tier forced on vs off, fp32 AND int8-KV), lifetime must
balance (host pool + allocator + cache account for every page under
cap/LRU pressure), the durable store must survive a restart with warm
TTFT (and reject corrupt state files cleanly), and a lane whose pages
are resident must never wait on one whose pages are in flight."""

import dataclasses
import os
import queue
import time

import numpy as np
import pytest

from polykey_tpu.engine.config import EngineConfig
from polykey_tpu.engine.engine import GenRequest, InferenceEngine
from polykey_tpu.engine.kv_cache import AllocationError, BlockAllocator, HostKVPool
from polykey_tpu.engine.prefix_cache import (
    TIER_DEVICE,
    TIER_HOST,
    PrefixCache,
    PrefixStateStore,
)
from polykey_tpu.models.config import get_config

# Tight device pool (23 usable pages at 8-token pages, 64-token seqs)
# so a handful of cached sessions oversubscribes it and spills; the
# resident floor makes retirements spill aggressively.
CFG = EngineConfig(
    model="tiny-llama",
    tokenizer="byte",
    dtype="float32",
    max_decode_slots=4,
    page_size=8,
    num_pages=24,
    max_seq_len=64,
    prefill_buckets=(16, 32),
    prefill_chunk=16,
    max_new_tokens_cap=16,
    prefix_cache=True,
    host_kv_bytes=64 << 20,
    host_kv_resident_pages=12,
)

# All-device reference: same math, pool big enough that nothing spills.
REF_CFG = dataclasses.replace(
    CFG, num_pages=128, host_kv_bytes=0, host_kv_resident_pages=0,
)

# Sticky sessions whose aggregate KV exceeds the tiny pool; revisits
# fault spilled prefixes back in.
SESSION_PROMPTS = [
    f"session {s} header padded out to be long enough xx" for s in range(4)
]
STICKY_MIX = SESSION_PROMPTS + [
    SESSION_PROMPTS[0], SESSION_PROMPTS[2],
    SESSION_PROMPTS[1], SESSION_PROMPTS[3],
]


def _collect(request, timeout=120.0):
    tokens, done, error = [], None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, value = request.out.get(timeout=deadline - time.monotonic())
        except queue.Empty:
            break
        if kind == "token":
            tokens.append(value)
        elif kind == "done":
            done = value
            break
        else:
            error = value
            break
    return tokens, done, error


def _serve(config, prompts, max_new=8, engine=None):
    eng = engine or InferenceEngine(config)
    outs = []
    try:
        for p in prompts:            # sequential: later prompts see cache
            r = GenRequest(prompt=p, max_new_tokens=max_new)
            eng.submit(r)
            tokens, done, error = _collect(r)
            assert error is None, error
            assert done is not None
            outs.append(tokens)
        return outs, eng.stats()
    finally:
        if engine is None:
            eng.shutdown()


# --- unit tier: pool, cache tiers, durable store --------------------------


def test_host_pool_alloc_release_balance():
    cfg = get_config("tiny-llama")
    pool = HostKVPool(cfg, capacity_pages=4, page_size=8,
                      dtype=np.float32, quantized=False)
    pages = [pool.alloc() for _ in range(4)]
    assert pool.used == 4 and pool.num_free == 0
    with pytest.raises(AllocationError):
        pool.alloc()
    for p in pages:
        pool.release(p)
    assert pool.used == 0 and pool.num_free == 4


def test_cache_tier_moves_and_probe_weighting():
    cfg = get_config("tiny-llama")
    alloc = BlockAllocator(32, prefer_native=False)
    host = HostKVPool(cfg, capacity_pages=8, page_size=4,
                      dtype=np.float32, quantized=False)
    cache = PrefixCache(alloc, page_size=4, capacity_pages=16,
                        host_pool=host)
    ids = np.arange(13, dtype=np.int32)          # 3 full pages
    pages = alloc.alloc(4)
    cache.insert(ids, pages)
    alloc.release_all(pages)                     # slot done; cache holds
    assert cache.device_entries() == 3
    assert cache.probe_tiered(ids) == (12, 0)

    # Spill the LRU page to host: probe stays warm but tier-split.
    (key, page), = cache.spill_candidates(1)
    hp = host.alloc()
    cache.mark_host(key, hp)
    assert cache.device_entries() == 2 and cache.host_entries() == 1
    # The spilled page was the chain HEAD (LRU == oldest == page 0 of
    # the prefix), so device matching stops there and host picks up.
    assert cache.probe_tiered(ids) == (0, 4) or \
        cache.probe_tiered(ids)[1] == 4

    # lookup_chain reports the host hit as a fault at its position.
    chain, faults = cache.lookup_chain(ids)
    assert len(chain) == 3 and len(faults) == 1
    assert chain[faults[0]][1] == TIER_HOST
    cache.release_chain(chain)

    # detach → reinsert (the engine's fault cycle), page accounting even.
    hp2 = cache.detach_host(key)
    assert hp2 == hp and cache.host_entries() == 0
    new_page = alloc.alloc(1)[0]
    host.release(hp2)
    assert cache.reinsert_device(key, new_page)
    alloc.release(new_page)                      # slot's own ref drops
    assert cache.device_entries() == 3
    chain, faults = cache.lookup_chain(ids)
    assert not faults and [t for _, t, _ in chain] == [TIER_DEVICE] * 3
    cache.release_chain(chain)


def test_cache_host_lru_pressure_drops_oldest():
    cfg = get_config("tiny-llama")
    alloc = BlockAllocator(64, prefer_native=False)
    host = HostKVPool(cfg, capacity_pages=2, page_size=4,
                      dtype=np.float32, quantized=False)
    cache = PrefixCache(alloc, page_size=4, capacity_pages=32,
                        host_pool=host)
    keys = []
    for seed in range(4):
        ids = np.full((5,), seed, dtype=np.int32)
        pages = alloc.alloc(1)
        cache.insert(ids, pages)
        alloc.release_all(pages)
    for key, _page in cache.spill_candidates(4):
        try:
            hp = host.alloc()
        except AllocationError:
            assert cache.pop_lru_host() is not None
            hp = host.alloc()
        cache.mark_host(key, hp)
        keys.append(key)
    # Cap 2: the two oldest host entries were LRU-dropped to admit the
    # two newest; pool exactly full, nothing leaked.
    assert cache.host_entries() == 2
    assert host.used == 2
    cache.clear()
    assert host.used == 0
    assert alloc.num_free == 63


def test_resident_floor_must_fit_device_pool():
    """A floor the pool can never satisfy would turn every retire into
    a full-cache spill — rejected at construction."""
    with pytest.raises(ValueError):
        dataclasses.replace(CFG, host_kv_resident_pages=23).validate()
    dataclasses.replace(CFG, host_kv_resident_pages=22).validate()


def test_evict_for_never_sacrifices_host_entries():
    """Pressure eviction drops only DEVICE-tier entries: dropping a
    host entry frees no device page, so an unsatisfiable demand must
    not wipe the warm host tier for nothing."""
    cfg = get_config("tiny-llama")
    alloc = BlockAllocator(32, prefer_native=False)
    host = HostKVPool(cfg, capacity_pages=4, page_size=4,
                      dtype=np.float32, quantized=False)
    cache = PrefixCache(alloc, page_size=4, capacity_pages=32,
                        host_pool=host)
    for seed in range(3):
        ids = np.full((5,), seed, dtype=np.int32)
        pages = alloc.alloc(1)
        cache.insert(ids, pages)
        alloc.release_all(pages)
    (key, _page), = cache.spill_candidates(1)
    cache.mark_host(key, host.alloc())
    assert cache.device_entries() == 2 and cache.host_entries() == 1
    cache.evict_for(10_000)                      # unsatisfiable demand
    assert cache.device_entries() == 0
    assert cache.host_entries() == 1, "warm host tier was wiped"


def test_disagg_config_env_ships_host_kv_knobs():
    """A programmatically-configured disagg pool must spawn workers
    with the host tier ON — the spawn-time env channel carries the
    four new knobs and they round-trip through from_env."""
    from polykey_tpu.engine.disagg_pool import _config_env

    cfg = dataclasses.replace(CFG, kv_state_dir="/tmp/hostkv-env-test")
    env = _config_env(cfg)
    assert env["POLYKEY_HOST_KV_BYTES"] == str(cfg.host_kv_bytes)
    assert env["POLYKEY_KV_RESIDENT_PAGES"] == "12"
    assert env["POLYKEY_KV_RESTORE_SLOTS"] == "2"
    assert env["POLYKEY_KV_STATE_DIR"] == "/tmp/hostkv-env-test"
    saved = dict(os.environ)
    try:
        os.environ.update(env)
        rt = EngineConfig.from_env()
        assert rt.host_kv_bytes == cfg.host_kv_bytes
        assert rt.host_kv_resident_pages == cfg.host_kv_resident_pages
        assert rt.host_kv_restore_slots == cfg.host_kv_restore_slots
        assert rt.kv_state_dir == cfg.kv_state_dir
        assert rt.prefix_cache
    finally:
        os.environ.clear()
        os.environ.update(saved)


def test_state_store_roundtrip_and_params_gate(tmp_path):
    cfg = get_config("tiny-llama")
    shape = (cfg.num_layers, 2, 8, cfg.num_kv_heads, cfg.head_dim)
    k = np.random.default_rng(0).standard_normal(shape).astype(np.float32)
    v = np.random.default_rng(1).standard_normal(shape).astype(np.float32)
    keys = [b"\x01" * 16, b"\x02" * 16]
    store = PrefixStateStore(str(tmp_path), "tiny-llama", 8,
                             params_key="abc", quantized=False)
    store.save_batch(keys, k, v, None, None)

    alloc = BlockAllocator(16, prefer_native=False)
    host = HostKVPool(cfg, capacity_pages=4, page_size=8,
                      dtype=np.float32, quantized=False)
    cache = PrefixCache(alloc, page_size=8, capacity_pages=16,
                        host_pool=host)
    expect = (cfg.num_layers, 0, 8, cfg.num_kv_heads, cfg.head_dim)
    adopted = store.load_into(cache, host, expect)
    assert adopted == 2 and cache.host_entries() == 2
    # Contents round-tripped bit-exactly into the host pool.
    for i, key in enumerate(keys):
        page = cache._map[key][0]
        assert cache._map[key][1] == TIER_HOST
        assert np.array_equal(host.k[:, page], k[:, i])
        assert np.array_equal(host.v[:, page], v[:, i])

    # A store written under DIFFERENT weights must not warm this cache.
    other = PrefixStateStore(str(tmp_path), "tiny-llama", 8,
                             params_key="different", quantized=False)
    cache2 = PrefixCache(alloc, page_size=8, capacity_pages=16,
                         host_pool=host)
    assert other.load_into(cache2, host, expect) == 0


# --- engine tier: bit-identity with the tier forced on vs off -------------


def test_state_store_restart_does_not_clobber(tmp_path):
    """A supervisor restart builds a new store in the SAME process with
    its batch counter back at 0 — its writes must not overwrite the
    previous incarnation's batches (the state a SECOND crash needs)."""
    cfg = get_config("tiny-llama")
    shape = (cfg.num_layers, 1, 8, cfg.num_kv_heads, cfg.head_dim)
    k = np.ones(shape, np.float32)
    v = np.ones(shape, np.float32)
    store1 = PrefixStateStore(str(tmp_path), "tiny-llama", 8,
                              params_key="abc", quantized=False)
    store1.save_batch([b"\x01" * 16], k, v, None, None)
    store2 = PrefixStateStore(str(tmp_path), "tiny-llama", 8,
                              params_key="abc", quantized=False)
    store2.save_batch([b"\x02" * 16], 2 * k, 2 * v, None, None)
    blobs = [n for n in os.listdir(tmp_path) if n.endswith(".pkkv")]
    assert len(blobs) == 2, "second incarnation clobbered the first"

    alloc = BlockAllocator(16, prefer_native=False)
    host = HostKVPool(cfg, capacity_pages=4, page_size=8,
                      dtype=np.float32, quantized=False)
    cache = PrefixCache(alloc, page_size=8, capacity_pages=16,
                        host_pool=host)
    expect = (cfg.num_layers, 0, 8, cfg.num_kv_heads, cfg.head_dim)
    assert store1.load_into(cache, host, expect) == 2


def test_sticky_sessions_bit_identical_fp32():
    ref, _ = _serve(REF_CFG, STICKY_MIX)
    out, stats = _serve(CFG, STICKY_MIX)
    assert out == ref
    assert stats["kv_pages_evicted"] > 0, "tier never spilled"
    assert stats["kv_pages_restored"] > 0, "tier never faulted back"
    assert (stats["kv_page_faults_prefix"]
            + stats["kv_page_faults_ctx"]) > 0
    assert stats["host_kv"] is True


def test_sticky_sessions_bit_identical_int8_kv():
    cfg_q = dataclasses.replace(CFG, kv_dtype="int8")
    ref, _ = _serve(dataclasses.replace(REF_CFG, kv_dtype="int8"),
                    STICKY_MIX)
    out, stats = _serve(cfg_q, STICKY_MIX)
    assert out == ref
    assert stats["kv_pages_restored"] > 0


def test_tiny_host_pool_pressure_never_kills_engine():
    """Host tier smaller than one session's chain: admission-pressure
    spills into a FULL host pool LRU-drop other entries — never a page
    an in-flight lookup chain depends on (the chain's host pages detach
    to the request before the allocation that can trigger the spill).
    Regression: this used to KeyError in `_admit` and kill the loop."""
    from polykey_tpu.engine.kv_cache import host_kv_page_bytes

    page_b = host_kv_page_bytes(get_config("tiny-llama"), 8, np.float32)
    cfg = dataclasses.replace(CFG, host_kv_bytes=3 * page_b)
    mix = STICKY_MIX * 3                 # heavy revisits under churn
    ref, _ = _serve(REF_CFG, mix)
    out, stats = _serve(cfg, mix)
    assert out == ref
    assert stats["kv_host_capacity"] == 3


def test_ragged_dispatch_with_host_tier_bit_identical():
    """Ragged mode (ISSUE 12) composes: faulting slots are skipped by
    the ragged batch builder until their restore issues, then their
    suffix ranges ride the mixed dispatch — streams stay identical."""
    cfg_r = dataclasses.replace(CFG, ragged_dispatch=True)
    ref, _ = _serve(
        dataclasses.replace(REF_CFG, ragged_dispatch=True), STICKY_MIX
    )
    out, stats = _serve(cfg_r, STICKY_MIX)
    assert out == ref
    assert stats["kv_pages_restored"] > 0


def test_spec_engine_with_host_tier_greedy_exact():
    """Speculative engines + host tier: restores refill only the TARGET
    pool (the draft's prefix KV is lost with the device pages), which
    by rejection-sampling construction costs acceptance, never
    correctness — greedy streams still equal the plain engine's."""
    spec_cfg = dataclasses.replace(CFG, draft_model="tiny-llama",
                                   spec_gamma=2)
    ref, _ = _serve(REF_CFG, STICKY_MIX)      # plain, all-device
    out, stats = _serve(spec_cfg, STICKY_MIX)
    assert out == ref
    assert stats["kv_pages_evicted"] > 0


def test_tier_disabled_allocates_nothing():
    eng = InferenceEngine(REF_CFG)
    try:
        assert eng._host_kv is None
        stats = eng.stats()
        assert stats["host_kv"] is False
        assert stats["kv_host_pages"] == 0
        assert stats["kv_host_capacity"] == 0
    finally:
        eng.shutdown()


def test_pages_balance_after_idle_with_tier():
    """Every device page is free, cache-held, or reserved after the
    engine drains — spills/restores must not leak allocator refs; host
    pages are exactly the cache's host entries."""
    eng = InferenceEngine(CFG)
    try:
        outs, _ = _serve(CFG, STICKY_MIX, engine=eng)
        assert all(len(t) >= 1 for t in outs)
        deadline = time.monotonic() + 10
        while eng.busy and time.monotonic() < deadline:
            time.sleep(0.05)
        stats = eng.stats()
        assert (
            stats["pages_free"] + stats["prefix_cache_pages"]
            == CFG.num_pages - 1
        )
        assert stats["kv_host_pages"] == stats["prefix_host_pages"]
    finally:
        eng.shutdown()


# --- page-aware scheduling: resident lanes never wait on faulting ones ----


def test_resident_lane_dispatches_while_faulting_lane_waits():
    """Submit spilled-session revisits (faulting) together with a fresh
    prompt (resident): the resident admission's activating prefill must
    land on the timeline BEFORE any fault's restore — the faulting
    lanes wait on the restore frontier, never the other way around."""
    cfg = dataclasses.replace(CFG, host_kv_restore_slots=1)
    eng = InferenceEngine(cfg)
    try:
        # Warm + spill: serve the sessions, then let retire-floor
        # eviction push their prefixes to host.
        _serve(cfg, SESSION_PROMPTS, engine=eng)
        deadline = time.monotonic() + 10
        while eng.busy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.stats()["prefix_host_pages"] > 0, "nothing spilled"

        requests = []
        for p in (SESSION_PROMPTS[0], SESSION_PROMPTS[1],
                  "totally fresh resident prompt here yy"):
            r = GenRequest(prompt=p, max_new_tokens=6)
            requests.append(r)
        for r in requests:
            eng.submit(r)
        for r in requests:
            _, done, error = _collect(r)
            assert error is None, error
            assert done is not None

        events = eng.timeline.events()
        restore_idx = [i for i, e in enumerate(events)
                       if e["kind"] == "note"
                       and e["note_kind"] == "kv_restore"]
        final_prefill_idx = [i for i, e in enumerate(events)
                             if e["kind"] == "prefill" and e["final"]]
        assert restore_idx, "revisits never faulted"
        # The burst's restores come after at least one activating
        # prefill that preceded them (the resident lane's): faulting
        # admissions register-and-wait, resident ones dispatch inline.
        burst_restores = [i for i in restore_idx
                          if i > final_prefill_idx[0]]
        resident_before = [i for i in final_prefill_idx
                           if i < burst_restores[0]]
        assert resident_before, (
            "no prefill dispatched ahead of the first restore — a "
            "faulting lane stalled the resident one"
        )
    finally:
        eng.shutdown()


# --- restart durability ----------------------------------------------------


def test_durable_reload_recovers_warm_streams(tmp_path):
    cfg = dataclasses.replace(CFG, kv_state_dir=str(tmp_path))
    first, _ = _serve(cfg, SESSION_PROMPTS)
    assert any(n.endswith(".pkkv") for n in os.listdir(tmp_path)), \
        "no durable spill batches were written"

    fresh = InferenceEngine(cfg)
    try:
        assert fresh._kv_reloaded_pages > 0
        second, stats = _serve(cfg, SESSION_PROMPTS, engine=fresh)
        assert second == first
        assert stats["kv_pages_restored"] > 0, \
            "reloaded pages never served a fault"
    finally:
        fresh.shutdown()


def test_corrupt_state_file_rejected_cleanly(tmp_path):
    cfg = dataclasses.replace(CFG, kv_state_dir=str(tmp_path))
    first, _ = _serve(cfg, SESSION_PROMPTS)
    blobs = sorted(n for n in os.listdir(tmp_path) if n.endswith(".pkkv"))
    assert blobs
    path = os.path.join(tmp_path, blobs[0])
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF                 # flip one payload bit
    open(path, "wb").write(bytes(data))

    fresh = InferenceEngine(cfg)                 # must not raise
    try:
        # The corrupt batch is rejected (and discarded); others load.
        assert not os.path.exists(path)
        second, _ = _serve(cfg, SESSION_PROMPTS, engine=fresh)
        assert second == first                   # correctness unharmed
    finally:
        fresh.shutdown()


def test_supervised_restart_reloads_durable_prefix(tmp_path):
    """The ROADMAP item 3 story end-to-end: a supervisor-driven restart
    rebuilds the engine from the factory, which reloads the durable
    store — the fresh engine serves the old sessions warm (faults >0)
    and bit-identically."""
    from polykey_tpu.engine.supervisor import EngineSupervisor

    cfg = dataclasses.replace(CFG, kv_state_dir=str(tmp_path))
    engine = InferenceEngine(cfg, seed=0)
    sup = EngineSupervisor(
        engine, lambda: InferenceEngine(cfg, seed=0),
        max_restarts=2, check_interval_s=0.05,
    ).start()
    try:
        first, _ = _serve(cfg, SESSION_PROMPTS, engine=sup.engine)
        old = sup.engine
        old.dead = "test: injected crash"
        deadline = time.monotonic() + 120
        while sup.engine is old:
            assert time.monotonic() < deadline, "supervisor never restarted"
            time.sleep(0.05)
        fresh = sup.engine
        assert fresh._kv_reloaded_pages > 0
        second, stats = _serve(cfg, SESSION_PROMPTS, engine=fresh)
        assert second == first
        assert stats["kv_pages_restored"] > 0
        # The restart note carries the reload evidence.
        notes = [e for e in fresh.timeline.events()
                 if e["kind"] == "note"
                 and e["note_kind"] == "engine_restart"]
        assert notes and notes[0]["attrs"]["kv_reloaded"] > 0
    finally:
        sup.stop()
        sup.engine.shutdown()


def test_worker_wires_state_dir_and_advertises_host_kv(tmp_path):
    """Disagg workers (ISSUE 13) with the tier on: the per-worker KV
    state dir derives from the worker state dir, and ping advertises
    host-tier warmth alongside warm_sessions."""
    from polykey_tpu.engine.worker import WorkerConn, WorkerServer

    cfg = dataclasses.replace(CFG, supervise=False)
    server = WorkerServer(
        cfg, tier="prefill", replica=0, exit_mode="simulate",
        state_dir=str(tmp_path),
    ).start()
    try:
        assert server.engine.config.kv_state_dir.endswith("kv-prefill-0")
        assert server.engine._kv_state is not None
        with WorkerConn(("127.0.0.1", server.port)) as conn:
            reply, _ = conn.request({"op": "ping"})
        assert reply["ok"]
        assert "kv_host_pages" in reply
        assert "kv_reloaded_pages" in reply
    finally:
        server.stop()

    # An EXPLICIT kv_state_dir is still worker-scoped: a shared dir
    # would let each worker's durable gc delete the others' batches.
    explicit = dataclasses.replace(
        cfg, kv_state_dir=str(tmp_path / "shared")
    )
    server2 = WorkerServer(
        explicit, tier="decode", replica=1, exit_mode="simulate",
    ).start()
    try:
        assert server2.engine.config.kv_state_dir == os.path.join(
            str(tmp_path / "shared"), "kv-decode-1"
        )
    finally:
        server2.stop()


# --- warmth advertisement --------------------------------------------------


def test_prefix_warmth_is_tier_aware():
    """A spilled-but-warm prefix must probe above cold (the PR 7/13
    routers would otherwise treat the session as cold) but below an
    equally-long device-resident one."""
    eng = InferenceEngine(CFG)
    try:
        _serve(CFG, SESSION_PROMPTS, engine=eng)
        deadline = time.monotonic() + 10
        while eng.busy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.stats()["prefix_host_pages"] > 0
        warmths = []
        for p in SESSION_PROMPTS:
            ids = eng.tokenizer.encode(p)
            dev, host = eng._prefix.probe_tiered(
                np.asarray(ids, np.int32))
            warmth = eng.prefix_warmth(ids)
            warmths.append((dev, host, warmth, len(ids)))
        spilled = [w for w in warmths if w[1] > 0]
        assert spilled, "no probed session was host-resident"
        for dev, host, warmth, n in spilled:
            assert warmth > 0.0                       # not cold
            assert warmth < (dev + host) / n or dev + host == 0
            assert abs(warmth - (dev + 0.5 * host) / n) < 1e-9
    finally:
        eng.shutdown()
