"""Security cipher adapter parity (VERDICT r1 #6).

Mirrors the behavior of /root/reference/internal/adapters/security/
cipher.go:92-141: 32-byte key check, nonce-prepended AES-256-GCM framing,
roundtrip, batch loops — plus the consumption the reference never built:
the encrypted-at-rest SecretStore and its resolution through TpuService.
"""

import importlib.util
import io
import json
import os

import pytest

from polykey_tpu.gateway.security import (
    KEY_SIZE,
    NONCE_SIZE,
    CipherError,
    SecretCipher,
    SecretStore,
)

KEY = bytes(range(32))


def test_missing_cryptography_is_a_clear_gated_error():
    """Images without the optional `cryptography` wheel must get an
    actionable CipherError naming the package and the knobs it powers —
    never a bare ImportError from inside a request path. Runs on every
    platform: with the wheel present the constructor succeeds instead."""
    try:
        import cryptography  # noqa: F401
    except ImportError:
        with pytest.raises(CipherError, match="cryptography"):
            SecretCipher(KEY)
    else:
        SecretCipher(KEY)  # wheel present: construction must work


# Everything below exercises real AES-256-GCM and requires the wheel.
requires_crypto = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="optional dependency: this image ships no cryptography wheel "
           "(the gated-error path is covered above)",
)


@requires_crypto
def test_key_must_be_32_bytes():
    for bad in (b"", b"short", bytes(31), bytes(33)):
        with pytest.raises(CipherError):
            SecretCipher(bad)
    SecretCipher(bytes(KEY_SIZE))  # exact size accepted


@requires_crypto
def test_roundtrip():
    c = SecretCipher(KEY)
    for pt in (b"", b"x", b"hello secret world", os.urandom(4096)):
        assert c.decrypt(c.encrypt(pt)) == pt


@requires_crypto
def test_nonce_prepended_framing():
    c = SecretCipher(KEY)
    blob = c.encrypt(b"payload")
    # nonce || ct || 16-byte tag
    assert len(blob) == NONCE_SIZE + len(b"payload") + 16
    # Distinct random nonce per call → distinct ciphertexts.
    assert blob != c.encrypt(b"payload")
    # Manual re-open using the framing proves the layout.
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    assert AESGCM(KEY).decrypt(blob[:NONCE_SIZE], blob[NONCE_SIZE:], None) \
        == b"payload"


@requires_crypto
def test_tamper_detected():
    c = SecretCipher(KEY)
    blob = bytearray(c.encrypt(b"payload"))
    blob[-1] ^= 0x01
    with pytest.raises(CipherError):
        c.decrypt(bytes(blob))


@requires_crypto
def test_short_ciphertext_rejected():
    c = SecretCipher(KEY)
    with pytest.raises(CipherError):
        c.decrypt(b"tiny")


@requires_crypto
def test_wrong_key_fails():
    a, b = SecretCipher(KEY), SecretCipher(bytes(reversed(KEY)))
    with pytest.raises(CipherError):
        b.decrypt(a.encrypt(b"payload"))


@requires_crypto
def test_batch_roundtrip():
    c = SecretCipher(KEY)
    pts = [b"one", b"two", b"", os.urandom(100)]
    assert c.decrypt_batch(c.encrypt_batch(pts)) == pts


@requires_crypto
def test_from_hex():
    c = SecretCipher.from_hex(KEY.hex())
    assert c.decrypt(c.encrypt(b"x")) == b"x"
    with pytest.raises(CipherError):
        SecretCipher.from_hex("zz" * 32)
    with pytest.raises(CipherError):
        SecretCipher.from_hex("ab" * 16)  # 16 bytes, not 32


@requires_crypto
def test_secret_store_roundtrip(tmp_path):
    store = SecretStore(SecretCipher(KEY))
    store.put("api-key-1", "s3cr3t-value")
    store.put("api-key-2", "другой секрет")   # non-ASCII plaintext
    assert store.resolve("api-key-1") == "s3cr3t-value"
    assert store.resolve("missing") is None

    path = str(tmp_path / "secrets.json")
    store.save(path)
    # At rest: base64 blobs, never plaintext.
    with open(path) as f:
        raw = f.read()
    assert "s3cr3t-value" not in raw
    assert json.loads(raw).keys() == {"api-key-1", "api-key-2"}

    reloaded = SecretStore(SecretCipher(KEY))
    reloaded.load(path)
    assert reloaded.resolve("api-key-2") == "другой секрет"


@requires_crypto
def test_secret_store_from_env(tmp_path, monkeypatch):
    path = str(tmp_path / "secrets.json")
    store = SecretStore(SecretCipher(KEY))
    store.put("secret-123", "hunter2")
    store.save(path)

    monkeypatch.setenv("POLYKEY_SECRET_KEY", KEY.hex())
    monkeypatch.setenv("POLYKEY_SECRETS_FILE", path)
    loaded = SecretStore.from_env()
    assert loaded is not None
    assert loaded.resolve("secret-123") == "hunter2"

    monkeypatch.delenv("POLYKEY_SECRET_KEY")
    assert SecretStore.from_env() is None


@requires_crypto
def test_tpu_service_resolves_secret(tmp_path):
    # The dev client's canonical request carries secret_id="secret-123"
    # (dev_client/main.go:238-258); with a store mounted the service logs
    # the resolution without changing response semantics.
    from polykey_tpu.gateway.jsonlog import Logger
    from polykey_tpu.gateway.mock_service import MockService
    from polykey_tpu.gateway.tpu_service import TpuService

    store = SecretStore(SecretCipher(KEY))
    store.put("secret-123", "hunter2")
    buf = io.StringIO()
    service = TpuService.__new__(TpuService)
    service.engine = None
    service.watchdog = None
    service.secrets = store
    service.logger = Logger(stream=buf)
    service._mock = MockService()

    resp = service.execute_tool("example_tool", None, "secret-123", None)
    assert resp.status.code == 200
    assert "secret resolved" in buf.getvalue()

    resp = service.execute_tool("example_tool", None, "nope", None)
    assert resp.status.code == 200           # unknown id is NOT an error
    assert "secret unknown" in buf.getvalue()
