"""Ring attention tests: parity vs full attention on a simulated mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from polykey_tpu.ops.attention import attention, make_attention_mask
from polykey_tpu.ops.ring_attention import ring_attention_spmd

TOL = 2e-5


def _case(B, T, Hq, Hk, D, seed=0):
    return (
        jax.random.normal(jax.random.PRNGKey(seed), (B, T, Hq, D), jnp.float32),
        jax.random.normal(jax.random.PRNGKey(seed + 1), (B, T, Hk, D), jnp.float32),
        jax.random.normal(jax.random.PRNGKey(seed + 2), (B, T, Hk, D), jnp.float32),
    )


@pytest.mark.parametrize("softcap,win", [
    (None, None), (50.0, None), (None, 24), (30.0, 24),
])
def test_ring_matches_full_attention(softcap, win):
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    B, T, Hq, Hk, D = 2, 64, 4, 2, 32
    q, k, v = _case(B, T, Hq, Hk, D)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    mask = make_attention_mask(pos, T, sliding_window=win)
    ref = attention(q, k, v, mask, scale=0.2, logit_softcap=softcap)
    w = None if win is None else jnp.int32(win)
    out = ring_attention_spmd(
        q, k, v, pos, pos, mesh, scale=0.2, logit_softcap=softcap,
        window=w, head_axis=None,
    )
    assert float(jnp.max(jnp.abs(ref - out))) < TOL


def test_ring_with_tp_head_sharding():
    """Heads sharded over tp inside the same shard_map (GQA: kv heads must
    divide the tp axis — contiguous head blocks keep q↔kv group alignment)."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
    B, T, Hq, Hk, D = 2, 32, 8, 2, 16
    q, k, v = _case(B, T, Hq, Hk, D)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    ref = attention(q, k, v, make_attention_mask(pos, T), scale=0.25)
    out = ring_attention_spmd(q, k, v, pos, pos, mesh, scale=0.25)
    assert float(jnp.max(jnp.abs(ref - out))) < TOL


def test_ring_with_offset_positions():
    """Positions that do not start at 0 (packed/continued sequences)."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "sp"))
    B, T, Hq, Hk, D = 1, 64, 2, 2, 16
    q, k, v = _case(B, T, Hq, Hk, D)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T)) + 100

    # kv slot j holds position 100 + j here, so the reference mask
    # (kv slot index vs absolute q position) is wrong; build it explicitly.
    kv_pos = pos[:, None, :]
    mask = kv_pos <= pos[:, :, None]
    ref = attention(q, k, v, mask, scale=0.25)
    out = ring_attention_spmd(q, k, v, pos, pos, mesh, scale=0.25,
                              head_axis=None)
    assert float(jnp.max(jnp.abs(ref - out))) < TOL


def test_ring_gradients_flow():
    """ppermute/online-softmax must be differentiable end to end."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "sp"))
    B, T, Hq, Hk, D = 1, 32, 2, 1, 16
    q, k, v = _case(B, T, Hq, Hk, D)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention_spmd(q, k, v, pos, pos, mesh, scale=0.25,
                                head_axis=None) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            attention(q, k, v, make_attention_mask(pos, T), scale=0.25) ** 2
        )

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4
