"""Direct tests of the gRPC health surface (gateway/health.py) — the
recovery-critical piece the supervisor leans on (ISSUE 3): Watch
streaming transitions (SERVING → NOT_SERVING → resume), SERVICE_UNKNOWN
for unregistered names, resume_serving() un-latching shutdown, and
probe() exit codes (the container healthcheck contract)."""

import io
import queue
import threading

import grpc
import pytest

from polykey_tpu.gateway import server as gateway_server
from polykey_tpu.gateway.health import (
    NOT_SERVING,
    SERVICE_UNKNOWN,
    SERVING,
    HealthService,
    probe,
)
from polykey_tpu.gateway.jsonlog import Logger
from polykey_tpu.gateway.mock_service import MockService
from polykey_tpu.proto import health_v1_pb2 as health_pb
from polykey_tpu.proto.health_v1_grpc import HealthStub


@pytest.fixture()
def stack():
    server, health, port = gateway_server.build_server(
        MockService(), Logger(stream=io.StringIO()), address="127.0.0.1:0"
    )
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield channel, health, port
    channel.close()
    server.stop(grace=None)


def _watch(stub, name, out: queue.Queue, stop: threading.Event):
    try:
        for resp in stub.Watch(
            health_pb.HealthCheckRequest(service=name), timeout=30
        ):
            out.put(resp.status)
            if stop.is_set():
                return
    except grpc.RpcError:
        pass  # stream torn down at test end — expected


def test_watch_streams_transitions(stack):
    channel, health, _ = stack
    stub = HealthStub(channel)
    out: queue.Queue = queue.Queue()
    stop = threading.Event()
    thread = threading.Thread(
        target=_watch, args=(stub, "", out, stop), daemon=True
    )
    thread.start()
    # Initial status streams immediately.
    assert out.get(timeout=5) == SERVING
    # Shutdown (watchdog trip path) → NOT_SERVING pushed to watchers.
    health.shutdown()
    assert out.get(timeout=5) == NOT_SERVING
    # Supervised recovery → SERVING pushed again: the exact transition
    # orchestration needs to resume routing without a process restart.
    health.resume_serving()
    stop.set()
    assert out.get(timeout=5) == SERVING
    thread.join(timeout=5)


def test_watch_unknown_service_streams_service_unknown(stack):
    channel, _, _ = stack
    stub = HealthStub(channel)
    responses = stub.Watch(
        health_pb.HealthCheckRequest(service="never.registered"), timeout=10
    )
    first = next(iter(responses))
    assert first.status == SERVICE_UNKNOWN
    responses.cancel()


def test_resume_serving_unlatches_shutdown():
    health = HealthService()
    health.set_serving_status("svc.a", SERVING)
    health.set_serving_status("svc.b", SERVING)
    health.shutdown()
    assert health._statuses == {"svc.a": NOT_SERVING, "svc.b": NOT_SERVING}
    # Latched: updates are ignored while shut down.
    health.set_serving_status("svc.a", SERVING)
    assert health._statuses["svc.a"] == NOT_SERVING
    # resume_serving un-latches AND flips every registered name back.
    health.resume_serving()
    assert health._statuses == {"svc.a": SERVING, "svc.b": SERVING}
    # No longer latched: normal updates apply again.
    health.set_serving_status("svc.a", NOT_SERVING)
    assert health._statuses["svc.a"] == NOT_SERVING


def test_probe_exit_codes(stack):
    _, health, port = stack
    target = f"127.0.0.1:{port}"
    assert probe(target) == 0                      # SERVING
    assert probe(target, "polykey.v2.PolykeyService") == 0
    assert probe(target, "never.registered") == 1  # NOT_FOUND abort
    health.shutdown()
    assert probe(target) == 1                      # NOT_SERVING
    health.resume_serving()
    assert probe(target) == 0                      # recovered


def test_probe_unreachable_is_nonzero():
    # Nothing listens here: connection failure must map to exit 1, not
    # an exception (the compose healthcheck execs this).
    assert probe("127.0.0.1:1", timeout=1.0) == 1
